"""Per-node control plane: scheduler + worker pool + object directory.

This is the raylet-equivalent (ref: src/ray/raylet/node_manager.h NodeManager,
worker_pool.h WorkerPool, scheduling/cluster_task_manager.h +
local_task_manager.h). It runs an asyncio event loop in a background thread;
workers connect over a unix socket with framed pickled messages
(protocol.py), and peer nodes connect over TCP (peers.py).

Cluster mode: the head node hosts the GCS-equivalent control plane
(gcs.py GcsService) on the same loop; remote nodes (spawned by
cluster_utils.Cluster.add_node or node_main) register with it, gossip load
reports, and learn the cluster view from its broadcasts (ref analogue: the
RaySyncer resource gossip, src/ray/common/ray_syncer/ray_syncer.h:88).
Tasks whose resources don't fit locally — or whose scheduling strategy says
otherwise — are forwarded to the node picked by the hybrid/spread/affinity
policies (scheduling_policy.py), the moral equivalent of the reference's
spillback re-leasing (ref: ClusterTaskManager::ScheduleAndDispatchTasks).
Objects are pulled between nodes on demand and re-homed into the local store
(ref analogue: PullManager + ObjectManagerService Push/Pull).
"""

from __future__ import annotations

import asyncio
import os
import struct
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Set, Tuple


from .config import Config
from .exceptions import (
    ActorDiedError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from .gcs import GcsClient, GcsService, LocalGcsHandle, RemoteGcsHandle
from .rpc import RpcError
from .ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from .object_store import (
    ArenaLocation,
    InlineLocation,
    LocalObjectStore,
    Location,
    ObjectDirectory,
    RemoteLocation,
    ShmLocation,
    SpilledLocation,
    current_arena,
    init_arena,
    shutdown_arena,
)
from .spilling import SpillManager
from . import fencing as _fencing
from .peers import PeerClient
from .placement_group import BundleState
from .protocol import AioFramedWriter, aio_read_frame
from .resources import CPU, NodeResources, ResourceSet
from .scheduling_policy import pick_node
from .scheduling_strategies import PlacementGroupSchedulingStrategy
from .task_spec import TaskSpec, TaskType, intern_spec
from ..util import dispatch_obs, loop_monitor
from ..util import events as cluster_events
from ..util import faults
from ..util.backoff import Backoff

_HEADER = struct.Struct("<I")


def _free_location(loc) -> None:
    """Release an object's storage: arena delete, shm unlink, or spill-file
    removal."""
    if isinstance(loc, SpilledLocation):
        try:
            os.remove(loc.path)
        except OSError:
            pass
    elif isinstance(loc, ArenaLocation):
        arena = current_arena()
        if arena is not None:
            try:
                arena.delete(loc.oid)
            except Exception:
                pass
    elif isinstance(loc, ShmLocation):
        try:
            from multiprocessing import shared_memory

            seg = shared_memory.SharedMemory(name=loc.name)
            seg.close()
            seg.unlink()
        except FileNotFoundError:
            pass
        except Exception:
            pass


def _read_text_tail(path: str, nbytes: int) -> str:
    """Last ``nbytes`` of a text file via seek (bounded read — never the
    whole file). Executor-thread helper for crash diagnosis; '' on any
    I/O error."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - nbytes))
            return f.read(nbytes).decode("utf-8", errors="replace")
    except OSError:
        return ""


def _system_memory_usage_fraction() -> float:
    """System memory usage in [0, 1] from /proc/meminfo (ref analogue:
    MemoryMonitor::GetMemoryBytes, common/memory_monitor.h)."""
    info = {}
    # procfs is memory-backed: this "file" read never touches disk.
    with open("/proc/meminfo") as f:  # rtlint: disable=loop-blocking
        for line in f:
            key, _, rest = line.partition(":")
            try:
                info[key] = int(rest.strip().split()[0])
            except (ValueError, IndexError):
                pass
    total = info.get("MemTotal", 0)
    if total <= 0:
        return 0.0
    return 1.0 - info.get("MemAvailable", total) / total


def _task_worker_type(spec: TaskSpec) -> str:
    """Tasks/actors requesting TPU resources run in workers that keep the
    accelerator environment; everything else runs in fast-starting CPU
    workers (the chip is exclusive-access, so TPU workers are scarce)."""
    return "tpu" if spec.resources.get("TPU") > 0 else "cpu"


# Asyncio framing shared with the GCS/peer channels (protocol.py).
_read_frame = aio_read_frame
_FramedWriter = AioFramedWriter

# Shared empty-location placeholder for pre-registered return slots and
# borrow stubs (frozen dataclass — one instance serves every record).
_RETURN_PLACEHOLDER = InlineLocation(b"")


@dataclass(slots=True)
class TaskRecord:
    """Queue-resident task bookkeeping. ``slots=True``: a 1M-deep queue
    holds 1M of these, and the per-instance ``__dict__`` was the single
    largest slice of the 4.4 GB driver RSS the r5 envelope probe
    measured (PERF_r05.json)."""

    spec: TaskSpec
    state: str = "waiting"  # waiting | ready | running | forwarded | finished | failed | cancelled
    worker_id: Optional[WorkerID] = None
    resources_held: bool = False
    deps_unpinned: bool = False
    # Cluster fields: ``origin`` is the hex node id that forwarded this task
    # here (results are pushed back to it); ``target`` is the node this
    # record was forwarded to; ``spillbacks`` bounds forwarding hops.
    origin: Optional[str] = None
    target: Optional[str] = None
    spillbacks: int = 0
    # Bundle this task's resources were acquired from, if placed in a
    # placement group: (pg_id, bundle_index).
    bundle_key: Optional[Tuple[str, int]] = None
    created: float = field(default_factory=time.monotonic)
    # When the record first looked cluster-wide infeasible (grace timing).
    infeasible_since: Optional[float] = None
    # Cached scheduling-class key (shape + strategy + worker type); records
    # of one class are interchangeable for capacity decisions.
    sched_class: Optional[Tuple] = None
    # monotonic time this record was handed to a worker (feeds the
    # per-task-duration histogram in /metrics).
    dispatched: Optional[float] = None
    # Hang detector bookkeeping: the WARNING event fires once per record
    # (re-dispatch after a retry resets it with the record state).
    hang_warned: bool = False


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    writer: _FramedWriter
    proc: Optional[subprocess.Popen] = None
    state: str = "idle"  # idle | busy | blocked | actor | dead
    worker_type: str = "cpu"  # cpu | tpu — tpu workers own the accelerator env
    current: Optional[TaskRecord] = None
    # Pipelined tasks shipped ahead of completion (ref analogue: actor
    # submit pipelining via max_tasks_in_flight_per_worker). Resources are
    # held while queued; a worker that blocks gets them reclaimed.
    pending: Deque[TaskRecord] = field(default_factory=deque)
    # Execute frames still being written by an async _send_execute (blob
    # fetch in flight). While nonzero the send_nowait fast path is off so
    # frames cannot overtake each other (per-caller actor call order).
    slow_sends: int = 0
    # Serializes slow sends themselves: two blob-fetching sends would
    # otherwise race on fetch latency and reorder. FIFO-fair asyncio lock,
    # acquired in frame-submission order.
    send_lock: "asyncio.Lock" = field(default_factory=lambda: asyncio.Lock())
    known_functions: Set[str] = field(default_factory=set)
    actor_id: Optional[ActorID] = None
    last_active: float = field(default_factory=time.monotonic)
    # Open chunked-put writers from a thin-client connection, keyed by
    # object id; aborted if the client dies mid-put.
    client_writers: Dict[ObjectID, Any] = field(default_factory=dict)
    # Execute frames coalesced within one loop iteration and flushed as a
    # single socket write: on a contended host every write wakes the
    # worker process and the kernel's wakeup preemption turns per-frame
    # writes into one context switch per task (the dispatch wall at
    # PERF_r03's 2.5-3k tasks/s).
    exec_buf: List[Dict[str, Any]] = field(default_factory=list)


class _ReadyQueue:
    """Ready tasks bucketed by scheduling class (ref analogue:
    ClusterTaskManager's per-SchedulingClass queues,
    scheduling/cluster_task_manager.h): a dispatch pass visits each CLASS
    once and stops at the first blocked head, so a deep homogeneous queue
    costs O(#classes + #dispatched) — not O(#queued) resource checks."""

    __slots__ = ("classes", "_count", "_keyfn")

    def __init__(self, keyfn):
        self.classes: Dict[Tuple, Deque[TaskRecord]] = {}
        self._count = 0
        self._keyfn = keyfn

    def append(self, rec: "TaskRecord"):
        self.classes.setdefault(self._keyfn(rec), deque()).append(rec)
        self._count += 1

    def popleft(self) -> "TaskRecord":
        for cls, q in self.classes.items():
            rec = q.popleft()
            self._count -= 1
            if not q:
                del self.classes[cls]
            return rec
        raise IndexError("pop from empty ready queue")

    def remove_head(self, cls: Tuple):
        q = self.classes[cls]
        q.popleft()
        self._count -= 1
        if not q:
            del self.classes[cls]

    def count_worker_type(self, wtype: str) -> int:
        return sum(
            len(q) for cls, q in self.classes.items() if cls[2] == wtype
        )

    def __len__(self) -> int:
        return self._count

    def __bool__(self) -> bool:
        return self._count > 0

    def __iter__(self):
        for q in self.classes.values():
            yield from q


@dataclass
class ActorInfo:
    actor_id: ActorID
    creation_spec: TaskSpec
    state: str = "pending"  # pending | alive | restarting | dead
    worker_id: Optional[WorkerID] = None
    queued: Deque[TaskSpec] = field(default_factory=deque)
    inflight: Dict[TaskID, TaskRecord] = field(default_factory=dict)
    restarts_left: int = 0
    restart_count: int = 0
    name: str = ""
    death_cause: str = ""
    # Direct-call endpoints the actor's worker listens on (callers
    # bypass the node manager for method calls; see worker_main
    # _start_direct_listener / runtime._DirectChannel): a unix socket
    # for same-node callers, a TLS-aware TCP (host, port) for remote
    # workers and thin clients, and the worker's direct protocol
    # version (mismatched callers stay on the NM route).
    direct_path: Optional[str] = None
    direct_addr: Optional[Tuple[str, int]] = None
    direct_ver: int = 1
    # GCS-assigned incarnation of the CURRENT start of this actor
    # (bumped on every start/restart cluster-wide). Resolution returns
    # it, the direct hello carries it, and the worker refuses a
    # mismatch — a cached endpoint to a stale incarnation can never
    # execute against the wrong actor state (split-brain fencing).
    incarnation: int = 0


class NodeManager:
    def __init__(
        self,
        node_id: NodeID,
        session_dir: str,
        resources: Dict[str, float],
        config: Config,
        *,
        is_head: bool = True,
        gcs_address: Optional[Tuple[str, int]] = None,
        node_ip: str = "127.0.0.1",
        labels: Optional[Dict[str, str]] = None,
    ):
        self.node_id = node_id
        self.session_dir = session_dir
        self.socket_path = os.path.join(session_dir, "node.sock")
        self.config = config
        self.is_head = is_head
        self.node_ip = node_ip
        self.labels = labels or {}
        self.node_resources = NodeResources(ResourceSet(resources))
        capacity = config.object_store_memory
        self.directory = ObjectDirectory(capacity)
        # Spilling: admit puts over capacity and relieve pressure by moving
        # LRU objects to disk (ref: raylet/local_object_manager.h:41).
        self.spill_manager = SpillManager(os.path.join(session_dir, "spill"))
        if config.object_spilling_enabled:
            self.directory.spill_enabled = True
        self._spilling = False
        self._restores: Dict[ObjectID, asyncio.Future] = {}
        # Native C++ arena store (plasma-equivalent, src/store/): created by
        # the head process; workers attach via RAY_TPU_ARENA. Pure-Python
        # per-object shm remains the fallback when the toolchain is missing.
        self.arena_name: Optional[str] = None
        if config.use_native_store:
            name = f"/rtpu-{node_id.hex()[:16]}"
            if init_arena(name, capacity=capacity or (1 << 30), create=True):
                self.arena_name = name

        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run_loop, name="ray_tpu-node-manager", daemon=True
        )
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = threading.Event()
        self._shutdown = False
        # Drain lifecycle (gcs.drain_node): once draining, this node is
        # unschedulable cluster-wide, finishes in-flight work, replicates
        # primary object copies off-node, then exits cleanly.
        self._draining = False
        # Host-process hook (node_main): called once the drain state
        # machine finished and the ack is on the wire — the process
        # should exit.
        self.on_drain_complete = None
        # Chaos plane: node-filtered specs need to know where they run.
        faults.set_local_node(node_id.hex())

        # Scheduling state (loop-thread only).
        self._ready = _ReadyQueue(self._sched_class)
        self._sched_pending = False
        # Workers with buffered execute frames awaiting the end-of-
        # iteration flush (see _send_execute_to / _flush_execute_bufs).
        self._exec_dirty: List[WorkerHandle] = []
        self._waiting: Dict[TaskID, Tuple[TaskRecord, Set[ObjectID]]] = {}
        self._dep_index: Dict[ObjectID, Set[TaskID]] = {}
        self._tasks: Dict[TaskID, TaskRecord] = {}

        self._workers: Dict[WorkerID, WorkerHandle] = {}
        self._idle: Dict[str, Deque[WorkerID]] = {"cpu": deque(), "tpu": deque()}
        self._starting_workers = {"cpu": 0, "tpu": 0}
        self._pending_types: Dict[WorkerID, str] = {}

        self._actors: Dict[ActorID, ActorInfo] = {}
        self._named_actors: Dict[str, ActorID] = {}

        self._functions: Dict[str, bytes] = {}
        self._kv: Dict[str, bytes] = {}

        self._sealed: Set[ObjectID] = set()
        self._seal_events: Dict[ObjectID, asyncio.Event] = {}
        self._pending_procs: Dict[WorkerID, subprocess.Popen] = {}

        # Cluster plane.
        self.gcs_service: Optional[GcsService] = None  # head only
        self._gcs = None  # LocalGcsHandle | RemoteGcsHandle | None
        self._gcs_client: Optional[GcsClient] = None  # remote only
        self._gcs_address = gcs_address
        self.peer_port: int = 0
        self._peer_server: Optional[asyncio.AbstractServer] = None
        # Striped transfer data plane (core/data_channel.py): raw-socket
        # listener advertised to pullers via the pull_object locate
        # reply; 0 = disabled (control-plane chunks only).
        self.data_port: int = 0
        self._data_server = None
        self._cluster_view: Dict[str, Dict[str, Any]] = {}  # hex -> view
        self._peers: Dict[str, PeerClient] = {}
        self._forwarded: Dict[TaskID, TaskRecord] = {}
        self._actor_homes: Dict[ActorID, str] = {}  # hex node or "dead"
        # Membership-fence plane (core/fencing.py). incarnation/epoch
        # come from the GCS register reply; _fenced_nodes holds peers
        # the GCS declared dead (their frames are refused and our
        # channels to them torn down) until a fresh incarnation of the
        # same node id rejoins; _fenced_self_epoch makes the zombie
        # self-termination idempotent per fence decision.
        self.incarnation = 0
        self.cluster_epoch = 0
        self._fenced_nodes: Dict[str, int] = {}  # hex -> fence epoch
        self._fenced_self_epoch = 0
        # Hook the co-resident driver runtime installs so a fence
        # broadcast tears down ITS direct channels to the fenced node
        # (worker/client runtimes learn via forwarded node_fenced
        # frames instead).
        self.on_node_fenced_runtime = None
        # Restart-elsewhere: the ORIGIN node of a restartable actor
        # creation (max_restarts != 0) pins the creation spec + a
        # restart budget, and re-places the actor on a surviving node
        # when its home is fenced (ref analogue:
        # GcsActorManager::OnNodeDead rescheduling).
        self._actor_creations: Dict[ActorID, TaskSpec] = {}
        self._actor_restart_budget: Dict[ActorID, int] = {}
        # Calls parked while a fenced actor restarts elsewhere: ONE
        # ordered queue per actor, drained FIFO once the new home
        # resolves — independent per-record polls would re-route them
        # in arbitrary order and break per-caller actor-call ordering
        # across the restart boundary.
        self._fence_parked: Dict[ActorID, List[TaskRecord]] = {}
        self._pulls: Dict[ObjectID, asyncio.Future] = {}
        self._heartbeat_task: Optional[asyncio.Task] = None
        # NM-process store client for the pull/push data path.
        self.local_store = LocalObjectStore()
        # Chunked, admission-controlled transfer plane (object_transfer.py).
        from .object_transfer import ObjectTransfer

        self._transfer = ObjectTransfer(self)
        # Placement-group bundles reserved on this node + pg routing cache.
        self._bundles: Dict[Tuple[str, int], BundleState] = {}
        self._pg_nodes: Dict[str, Dict[int, str]] = {}
        # Records parked on an in-flight pg-map resolution, keyed by pg id
        # (one GCS round-trip per group, not per record).
        self._pg_waiters: Dict[str, List[TaskRecord]] = {}

        # Strong refs to fire-and-forget coroutines so they are neither
        # GC'd mid-flight nor dropped unawaited at loop shutdown (advisor
        # r1: drop_named_actor cleanup was lost that way).
        self._bg_tasks: Set[asyncio.Task] = set()

        # Lineage table: return object -> creating TaskSpec, pinned while
        # the object's directory entry lives; re-executed to rebuild lost
        # objects (ref analogue: lineage pinning in reference_count.h:61 +
        # ObjectRecoveryManager re-execution via task_manager.h:195).
        self._lineage: Dict[ObjectID, TaskSpec] = {}
        self._reconstructions: Dict[ObjectID, int] = {}

        # Borrower protocol (ref analogue: reference_count.h:61 borrower
        # tracking). Borrower side: count-only stub entries created when a
        # ref to an object this node does not own is pinned or held here;
        # each registers this node with the owner and releases on local GC.
        self._borrow_stubs: Set[ObjectID] = set()
        self._borrowed_from: Dict[ObjectID, str] = {}  # oid -> owner hex
        # Acked client submits already accepted (bounded FIFO): dedups a
        # reconnect replay even after the task finished and left _tasks.
        from collections import OrderedDict as _OD

        self._recent_client_submits: "_OD[TaskID, None]" = _OD()
        self._borrow_registering: Set[ObjectID] = set()
        # Containment pins: container object -> refs serialized inside it
        # (a put'ed list of refs, a returned dict of refs). Pinned while
        # the container's entry lives; released when it is collected.
        self._nested_pins: Dict[ObjectID, List[ObjectID]] = {}

        # Profiling plane (ref analogue: `ray stack` + the reporter's
        # profile_manager): in-flight stack_dump/profile requests to this
        # node's workers, keyed by req_id (loop-thread only).
        self._profile_pending: Dict[int, asyncio.Future] = {}
        self._profile_req_seq = 0

        # Head-side leak sweep (util/data_obs.py): oids already warned
        # this leak episode (pruned when the object stops looking
        # leaked, so GC clears the dedup and a fresh leak warns again)
        # plus the one-sweep-in-flight guard.
        self._leak_warned: Set[str] = set()
        self._leak_last_sweep = 0.0
        self._leak_sweep_task: Optional[asyncio.Task] = None

        # Failure history: bounded deque of TERMINAL task records (state,
        # duration, error type/message) retained after the live record
        # leaves _tasks, merged into _local_state_snapshot so list_tasks
        # can answer "what failed" (ref analogue: the task-event buffer
        # retaining terminal states behind `ray summary tasks`).
        self._task_history: Deque[Dict[str, Any]] = deque(
            maxlen=config.task_history_size
        )

        self._stats = {
            "tasks_submitted": 0,
            "tasks_finished": 0,
            "tasks_failed": 0,
            "tasks_retried": 0,
            "workers_started": 0,
            "actors_created": 0,
            # Direct actor-call plane: completions reported by this
            # node's actor workers via direct_done_batch notifications,
            # and the number of batch frames that carried them (the
            # ratio shows the debounce coalescing under load).
            "direct_calls_done": 0,
            "direct_done_batches": 0,
        }
        # Dispatch-to-completion wall-time histogram for tasks executed on
        # this node (rendered as ray_tpu_task_duration_seconds by
        # util/prometheus._core_lines; ref analogue: the task-duration
        # metrics in src/ray/stats/metric_defs.h).
        bounds = [0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                  60.0]
        self._task_duration = {
            "count": 0,
            "sum": 0.0,
            "bounds": bounds,
            "buckets": [0] * (len(bounds) + 1),
        }

    # ------------------------------------------------------------------ boot

    def start(self):
        self._thread.start()
        self._started.wait(timeout=30)
        if not self._started.is_set():
            raise RuntimeError(
                "node manager failed to start (GCS unreachable?)"
            )
        for _ in range(self.config.num_prestart_workers):
            self._loop.call_soon_threadsafe(self._spawn_worker)
        self.dashboard_agent = None
        if getattr(self.config, "dashboard_agent", True):
            try:
                from ..dashboard_agent import DashboardAgent

                self.dashboard_agent = DashboardAgent(
                    self, host=self.node_ip
                ).start()
            except Exception:
                self.dashboard_agent = None

    def _run_loop(self):
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._start_server())
        self._started.set()
        profile_to = os.environ.get("RAY_TPU_PROFILE_NM")
        if profile_to:
            import cProfile

            pr = cProfile.Profile()
            pr.enable()
            self._loop.run_forever()
            pr.disable()
            pr.dump_stats(profile_to)
        else:
            self._loop.run_forever()
        # Drain pending callbacks after stop().
        self._loop.run_until_complete(self._loop.shutdown_asyncgens())
        self._loop.close()

    async def _start_server(self):
        self._server = await asyncio.start_unix_server(
            self._handle_connection, path=self.socket_path
        )
        # Loop-health watchdog + GIL probe: the NM loop is the node's
        # control plane — a stall here stalls every worker frame.
        loop_monitor.attach("nm", self._loop)
        from ..util import profiler as _profiler

        _profiler.start_gil_monitor()
        # JSON control channel for native (C/C++) clients (ref
        # analogue: the cpp/ worker API's core-worker channel).
        from .capi_server import CapiServer

        self.capi_server = CapiServer(self)
        await self.capi_server.start(
            os.path.join(self.session_dir, "capi.sock")
        )
        # Peer channel for node<->node traffic (spillback + object pulls).
        from .tls import server_ssl_context

        self._peer_server = await asyncio.start_server(
            self._handle_peer_connection, host=self.node_ip, port=0,
            ssl=server_ssl_context(),
        )
        self.peer_port = self._peer_server.sockets[0].getsockname()[1]
        # Data plane: object payload rides dedicated raw stream sockets
        # (length-prefixed binary, zero-copy both ends) so a gigabyte
        # pull never holds the pickled control channel. Failure to start
        # is non-fatal — transfers then ride the chunk fallback.
        if self.config.transfer_streams_per_peer > 0:
            try:
                from .data_channel import DataPlaneServer

                self._data_server = DataPlaneServer(
                    self.node_ip, self.config.session_token,
                    self._transfer.open_range,
                    chunk_bytes=self.config.object_transfer_chunk_bytes,
                    max_streams=self.config.serve_chunks_in_flight,
                    on_served=self._transfer.on_range_served,
                    on_range_done=self._transfer.on_range_done,
                    io_timeout=self.config.transfer_io_timeout_s,
                )
                self.data_port = self._data_server.start()
            except Exception:
                self._data_server = None
                self.data_port = 0
        if self.is_head:
            self.gcs_service = GcsService(self.config, self._loop)
            await self.gcs_service.start(
                host=self.node_ip, port=self.config.gcs_port
            )
            self.gcs_service.on_node_added = self._on_gcs_node_added
            self.gcs_service.on_node_dead = self._on_gcs_node_dead
            self.gcs_service.on_load_update = self._on_gcs_load_update
            self.gcs_service.on_pgs_invalidated = self._invalidate_pgs
            self.gcs_service.on_node_draining = self._on_gcs_node_draining
            self.gcs_service.on_node_undrain = self._on_gcs_node_undrain
            self.gcs_service.on_chaos_update = self._on_gcs_chaos_update
            self.gcs_service.on_node_fenced = self._on_gcs_node_fenced
            self._gcs = LocalGcsHandle(self.gcs_service)
            reply = await self.gcs_service.register_node(
                self.node_id,
                self.node_ip,
                self.peer_port,
                self.node_resources.total.to_dict(),
                is_head=True,
                labels=self.labels,
            )
            self.incarnation = int(reply.get("incarnation") or 1)
            self.cluster_epoch = int(reply.get("epoch") or 0)
            self._apply_cluster_views(reply["nodes"])
        elif self._gcs_address is not None:
            await self._connect_gcs()
        self._heartbeat_task = asyncio.ensure_future(self._heartbeat_loop())
        self._gc_task = asyncio.ensure_future(self._gc_loop())
        self._health_task = asyncio.ensure_future(self._health_loop())
        self._memmon_task = asyncio.ensure_future(self._memory_monitor_loop())
        # This process's cluster-event transport: batches publish through
        # our GCS handle on this loop (node-manager processes have no
        # driver runtime for events to route through).
        cluster_events.set_publish_hook(self._publish_event_batch)

    def _publish_event_batch(self, batch: List[Dict[str, Any]]):
        """events.py flusher-thread entry: ship a drained batch via the
        GCS pubsub without blocking the flusher."""
        if self._shutdown or self._gcs is None:
            raise RuntimeError("node manager not connected")
        asyncio.run_coroutine_threadsafe(
            self._publish_events_async(list(batch)), self._loop
        )

    async def _publish_events_async(self, batch: List[Dict[str, Any]]):
        for e in batch:
            if e.get("node_id") is None:
                e["node_id"] = self.node_id.hex()
        try:
            await self._gcs.psub_publish(
                cluster_events.CLUSTER_EVENTS, batch
            )
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(
                f"[ray_tpu] node {self.node_id.hex()[:8]}: cluster-event "
                f"publish failed ({e!r}); {len(batch)} event(s) dropped\n"
            )

    async def _connect_gcs(self):
        """Dial the GCS and register this node (first boot AND after a
        head restart — registration is idempotent by node id)."""
        client = GcsClient(
            self.node_id, self._gcs_address[0], self._gcs_address[1]
        )
        client.on_push = self._on_gcs_push
        await client.connect()
        try:
            reply = await client.request(
                {
                    "op": "register_node",
                    "host": self.node_ip,
                    "peer_port": self.peer_port,
                    "resources": self.node_resources.total.to_dict(),
                    "labels": self.labels,
                }
            )
        except BaseException:
            # A connected-but-unregistered client must not linger: its
            # reader task and on_push hook would mutate node state from an
            # abandoned socket on every retry.
            client.close()
            raise
        self._gcs_client = client
        self._gcs = RemoteGcsHandle(client)
        prev_incarnation = self.incarnation
        self.incarnation = int(reply.get("incarnation") or 1)
        self.cluster_epoch = max(
            self.cluster_epoch, int(reply.get("epoch") or 0)
        )
        self._apply_cluster_views(reply["nodes"])
        # Late joiner / reconnect: adopt the head's current chaos plan
        # (empty = disarm — correct after a head restart too).
        chaos = reply.get("chaos") or {}
        faults.apply_plan(chaos.get("specs") or [], chaos.get("gen"))
        fenced_at = int(reply.get("fenced_at") or 0)
        if fenced_at and prev_incarnation:
            # The reply says this node was declared dead at epoch
            # fenced_at while we were partitioned: the registration that
            # just happened is a FRESH incarnation, and the old one's
            # workers (stale actor incarnations, stale sealed objects)
            # must die before we resume — rejoining a split brain as-is
            # would double-execute calls and resurrect stale locations.
            await self._zombie_self_fence(fenced_at)

    async def _reconnect_gcs(self) -> bool:
        """Head-restart tolerance (ref analogue: NotifyGCSRestart,
        node_manager.proto:361 + gcs_rpc_server_reconnect_timeout_s,
        ray_config_def.h:451): a worker node that loses the GCS retries
        the address with backoff, re-registers, and re-publishes its local
        truth — named actors homed here and sealed object locations — so
        the restarted head rebuilds runtime state from the survivors."""
        wait = Backoff(
            base=0.5, factor=1.5, max_delay=3.0, jitter=0.2,
            deadline_s=self.config.gcs_reconnect_timeout_s,
        )
        sys.stderr.write(
            "[ray_tpu] GCS connection lost; attempting reconnect\n"
        )
        while not wait.expired and not self._shutdown:
            try:
                await self._connect_gcs()
            # The retry loop IS the handler (jittered backoff, deadline
            # bounded); final expiry is reported after the loop.
            except Exception:  # rtlint: disable=swallowed-failure
                if not await wait.async_sleep():
                    break
                continue
            await self._republish_to_gcs()
            sys.stderr.write("[ray_tpu] reconnected to restarted GCS\n")
            return True
        return False

    async def _republish_to_gcs(self):
        """After the head restarts from its snapshot, runtime state lives
        only on surviving nodes: push ours back."""
        # list(): each await below yields the loop to handlers that may
        # mutate _actors mid-iteration.
        for info in list(self._actors.values()):
            if info.state not in ("alive", "restarting", "pending"):
                continue
            spec = info.creation_spec
            try:
                # Reconnect re-registration: pass the incarnation we
                # already run as — the GCS must NOT mint a new one (the
                # actor did not restart, the head did).
                await self._gcs.register_actor_node(
                    spec.actor_id, self.node_id,
                    incarnation=info.incarnation,
                )
                if spec.name:
                    await self._gcs.register_named_actor(
                        spec.name, spec.actor_id, self.node_id, spec
                    )
            except Exception as e:  # noqa: BLE001
                sys.stderr.write(
                    f"[ray_tpu] node {self.node_id.hex()[:8]}: actor "
                    f"{spec.actor_id.hex()[:8]} re-registration after "
                    f"reconnect failed ({e!r}); named lookups may miss "
                    f"it until the next reconnect\n"
                )
        await self._publish_all_sealed()

    async def _zombie_self_fence(self, epoch: int):
        """This node learned it was declared dead at ``epoch`` while it
        was (asymmetrically) partitioned. The cluster has moved on:
        peers tore down their channels, restartable actors restarted
        elsewhere, lineage re-executed what we owned. Resuming the old
        identity would split the brain — callers holding cached direct
        endpoints would execute against stale actor incarnations and
        our sealed-object republish would resurrect locations consumers
        already recovered away from. So: kill the workers (the stale
        incarnations die with them), drop queued work and local state,
        and continue as the fresh incarnation the re-register reply
        assigned — empty, but a first-class member again."""
        if self._fenced_self_epoch >= epoch:
            return  # already fenced for this (or a later) decision
        self._fenced_self_epoch = epoch
        _fencing.ZOMBIE_KILLS.inc()
        workers = [
            w for w in self._workers.values()
            if w.state != "dead" and w.worker_type != "client"
        ]
        cluster_events.emit(
            cluster_events.WARNING, cluster_events.NODE,
            f"node {self.node_id.hex()[:8]} was declared dead at epoch "
            f"{epoch} while partitioned: terminating "
            f"{len(workers)} worker(s) and rejoining as incarnation "
            f"{self.incarnation} with empty state (zombie fencing)",
            node_id=self.node_id.hex(),
            custom_fields={"epoch": epoch,
                           "incarnation": self.incarnation,
                           "workers_killed": len(workers)},
        )
        # Mark every actor dead BEFORE the kills so the worker-death
        # handler cannot restart a stale incarnation locally.
        for info in self._actors.values():
            if info.state == "dead":
                continue
            info.state = "dead"
            info.death_cause = "node fenced (zombie incarnation terminated)"
            info.restarts_left = 0
            for rec in list(info.inflight.values()):
                self._fail_task(
                    rec, ActorDiedError(rec.spec.name, info.death_cause)
                )
            info.inflight.clear()
            self._fail_actor_queue(info, info.death_cause)
        # Cooperative kill first (lets completion buffers and the event
        # ring's tail flush), hard kill whatever outlives the grace.
        for w in workers:
            w._intentional_kill = True
            try:
                await w.writer.send({"type": "kill"})
            # Dying worker — the hard kill below covers it.
            except Exception:  # rtlint: disable=swallowed-failure
                pass
        grace = max(
            0.0, float(getattr(self.config, "fence_kill_grace_s", 1.0))
        )
        deadline = self._loop.time() + grace
        while self._loop.time() < deadline and any(
            w.proc is not None and w.proc.poll() is None for w in workers
        ):
            await asyncio.sleep(0.05)
        for w in workers:
            if w.proc is not None and w.proc.poll() is None:
                try:
                    w.proc.kill()
                # Already reaped between the poll and the kill.
                except Exception:  # rtlint: disable=swallowed-failure
                    pass
        # Stale state must not resurrect: nothing sealed here is
        # publishable (consumers re-located or re-executed during the
        # fence window), queued work was already re-executed by its
        # owners' lineage after the death broadcast, and remote-actor
        # routing caches re-resolve through the GCS.
        self._sealed.clear()
        self._ready = _ReadyQueue(self._sched_class)
        self._waiting.clear()
        self._dep_index.clear()
        self._named_actors.clear()
        self._actor_homes.clear()
        try:
            cluster_events.flush()
        # Event transport mid-reconnect: the ring keeps the record.
        except Exception:  # rtlint: disable=swallowed-failure
            pass

    # ------------------------------------------------------- cluster plumbing

    @property
    def _multi_node(self) -> bool:
        return len(self._cluster_view) > 1

    def _apply_cluster_views(self, views):
        for v in views:
            if v["state"] in ("alive", "draining"):
                # Draining nodes stay REACHABLE (they push replicas at
                # us and answer pulls until exit) — a late joiner must
                # keep them in view or _get_peer fails mid-drain; the
                # schedulers already skip any non-"alive" state.
                self._cluster_view[v["node_id"]] = v
                # A live view of a previously fenced node id is a FRESH
                # incarnation rejoining (the GCS only re-admits after
                # re-registration, and the zombie self-terminated its
                # old incarnation first): stop refusing its frames.
                self._fenced_nodes.pop(v["node_id"], None)
            else:
                self._cluster_view.pop(v["node_id"], None)
            epoch = v.get("epoch")
            if epoch:
                self.cluster_epoch = max(self.cluster_epoch, int(epoch))

    def _local_view(self, include_shapes: bool = False) -> Dict[str, Any]:
        view = {
            "node_id": self.node_id.hex(),
            "host": self.node_ip,
            "peer_port": self.peer_port,
            "resources_total": self.node_resources.total.to_dict(),
            "resources_available": self.node_resources.available.to_dict(),
            "pending_tasks": (
                len(self._ready) + len(self._waiting)
                + sum(len(w.pending) for w in self._workers.values()
                      if w.state != "dead")
            ),
            "is_head": self.is_head,
            # Draining: still reachable, never schedulable (pick_node /
            # place_bundles filter to state == "alive").
            "state": "draining" if self._draining else "alive",
            "labels": self.labels,
            "incarnation": self.incarnation,
            "epoch": self.cluster_epoch,
        }
        if include_shapes:
            # O(queue) — heartbeat-rate only, never per _schedule pass.
            view["pending_shapes"] = self._pending_shapes()
        return view

    def _pending_shapes(self, cap: int = 32):
        """Aggregate queued-task resource shapes for the autoscaler (ref:
        resource_load_by_shape in gcs.proto / resource_demand_scheduler.py).
        Returns [[shape_dict, count], ...], at most ``cap`` distinct shapes.
        Ready records are already class-bucketed (shape at key index 1), so
        this is O(#classes + #waiting), not O(#queued)."""
        counts: Dict[Tuple, int] = {}
        for cls, q in self._ready.classes.items():
            key = cls[1]
            if key not in counts and len(counts) >= cap:
                continue  # cap DISTINCT shapes, keep counting known ones
            counts[key] = counts.get(key, 0) + len(q)
        for rec, _missing in self._waiting.values():
            try:
                shape = rec.spec.resources.to_dict()
            # A malformed shape only drops one row from the autoscaler
            # demand report; the task itself is untouched.
            except Exception:  # rtlint: disable=swallowed-failure
                continue
            key = tuple(sorted(shape.items()))
            if key not in counts and len(counts) >= cap:
                continue
            counts[key] = counts.get(key, 0) + 1
        # Lease riders: tasks queued in a worker's pipeline have NOT
        # started — they are latent demand exactly like ready-queue
        # entries (without this, riding hides parallelizable work from
        # the autoscaler: 6 queued CPU-seconds on a 1-CPU node would
        # look satisfied). Report them under their shape.
        for w in self._workers.values():
            if w.state == "dead" or not w.pending:
                continue
            for rec in w.pending:
                try:
                    shape = rec.spec.resources.to_dict()
                # Same contract as the waiting-queue rows above.
                except Exception:  # rtlint: disable=swallowed-failure
                    continue
                key = tuple(sorted(shape.items()))
                if key not in counts and len(counts) >= cap:
                    continue
                counts[key] = counts.get(key, 0) + 1
        return [[dict(k), n] for k, n in counts.items()]

    def _on_gcs_node_added(self, entry):
        was_single = not self._multi_node
        self._cluster_view[entry.node_id.hex()] = entry.view()
        if was_single and self._multi_node:
            # Objects sealed while the head was alone were never published;
            # back-publish so new nodes can locate them.
            asyncio.ensure_future(self._publish_all_sealed())
        self._schedule()

    async def _publish_all_sealed(self):
        failed = 0
        for oid in list(self._sealed):
            loc = self.directory.lookup(oid)
            if loc is not None and not isinstance(loc, RemoteLocation):
                try:
                    await self._gcs.publish_object(oid, self.node_id)
                # Aggregated into ONE stderr warning below the loop.
                except Exception:  # rtlint: disable=swallowed-failure
                    failed += 1
        if failed:
            sys.stderr.write(
                f"[ray_tpu] node {self.node_id.hex()[:8]}: {failed} "
                f"sealed object(s) failed to re-publish after reconnect; "
                f"remote consumers may need the next reconnect to "
                f"locate them\n"
            )

    def _on_gcs_node_dead(self, entry):
        asyncio.ensure_future(
            self._on_node_dead_hex(entry.node_id.hex(), dead_actors=None)
        )

    def _on_gcs_node_fenced(self, entry, epoch: int):
        """Head-side hook for the GCS fence decision (remote nodes
        learn via the node_fenced broadcast)."""
        self._on_node_fenced(entry.node_id.hex(), epoch,
                             getattr(entry, "incarnation", 0))

    def _on_node_fenced(self, node_hex: str, epoch: int,
                        incarnation: int = 0):
        """The GCS fenced ``node_hex`` at membership epoch ``epoch``:
        stop trusting that incarnation NOW. Our direct channels to it
        are torn down (the co-resident driver runtime via the installed
        hook, worker/client runtimes via forwarded node_fenced frames);
        the reader failure path parks their in-flight calls into the
        exactly-once NM replay path, where calls bound to the fenced
        incarnation are REFUSED rather than re-executed. Subsequent
        peer frames from the fenced node are dropped until a fresh
        incarnation of it rejoins."""
        if epoch:
            self.cluster_epoch = max(self.cluster_epoch, int(epoch))
        if node_hex == self.node_id.hex():
            # We can still hear the GCS but IT declared US dead (e.g. a
            # one-way partition where only our sends are lost): fence
            # ourselves now; the reconnect loop re-registers fresh.
            asyncio.ensure_future(
                self._zombie_self_fence(epoch or self.cluster_epoch)
            )
            return
        self._fenced_nodes[node_hex] = epoch
        _fencing.EVENT_CHANNEL_TEARDOWN.inc()
        hook = self.on_node_fenced_runtime
        if hook is not None:
            try:
                hook(node_hex, epoch)
            except Exception as e:  # noqa: BLE001
                sys.stderr.write(
                    f"[ray_tpu] node {self.node_id.hex()[:8]}: driver "
                    f"fence hook failed ({e!r}); its direct channels "
                    f"to {node_hex[:8]} die on next use instead\n"
                )
        asyncio.ensure_future(
            self._broadcast_fence_to_workers(node_hex, epoch)
        )

    async def _broadcast_fence_to_workers(self, node_hex: str,
                                          epoch: int):
        """Forward the fence decision to every local worker AND thin
        client: their runtimes hold their own direct channels to the
        fenced node's actors (healthy sockets under an asymmetric
        partition — they would keep executing calls on the stale
        incarnation without this)."""
        frame = {"type": "node_fenced", "node_id": node_hex,
                 "epoch": epoch}
        for w in list(self._workers.values()):
            if w.state == "dead":
                continue
            try:
                await w.writer.send(dict(frame))
            # Dying worker/client: its channels die with the process.
            except Exception:  # rtlint: disable=swallowed-failure
                pass

    def _on_gcs_node_draining(self, entry):
        """Head-side hook for the GCS drain RPC (remote nodes learn via
        the node_draining broadcast)."""
        self._on_peer_draining(entry.node_id.hex())

    def _on_peer_draining(self, node_hex: str):
        """A node began draining: keep it REACHABLE (in-flight actor
        traffic and the drain RPC itself still flow) but unschedulable —
        pick_node/place_bundles skip non-alive views, so marking the
        view is enough to stop new forwards/creations landing there.
        When the draining node is THIS one, local workers are told too
        (``node_draining`` frames → core/preemption.py), so cooperative
        tenants — above all a train gang — checkpoint at their next
        step boundary and surrender the node instead of dying with it."""
        if node_hex == self.node_id.hex():
            self._draining = True
            asyncio.ensure_future(self._broadcast_drain_to_workers(True))
            return
        view = self._cluster_view.get(node_hex)
        if view is not None:
            view["state"] = "draining"

    def _on_gcs_node_undrain(self, entry):
        """Head-side hook for a drain rollback (remote nodes learn via
        the node_undrain broadcast)."""
        self._on_peer_undrain(entry.node_id.hex())

    def _on_peer_undrain(self, node_hex: str):
        """A drain was aborted: the node rejoins the schedulable pool."""
        if node_hex == self.node_id.hex():
            self._draining = False
            asyncio.ensure_future(self._broadcast_drain_to_workers(False))
            return
        view = self._cluster_view.get(node_hex)
        if view is not None and view.get("state") == "draining":
            view["state"] = "alive"

    async def _broadcast_drain_to_workers(self, draining: bool):
        """Forward this node's drain state to every local worker
        process (the worker-side signal behind TrainSession.preemption)."""
        frame = {
            "type": "node_draining" if draining else "node_undrain",
            "node_id": self.node_id.hex(),
        }
        for w in list(self._workers.values()):
            if w.state == "dead" or w.worker_type == "client":
                continue
            try:
                await w.writer.send(dict(frame))
            except Exception:  # rtlint: disable=swallowed-failure
                pass  # dying worker; the drain proceeds regardless

    def _on_gcs_chaos_update(self, specs, gen):
        """Head-side hook: the GCS applied the plan in this process
        already; forward it to this node's workers."""
        asyncio.ensure_future(self._broadcast_chaos_to_workers(specs, gen))

    def _apply_chaos(self, specs, gen):
        faults.apply_plan(specs or [], gen)
        asyncio.ensure_future(self._broadcast_chaos_to_workers(specs, gen))

    async def _broadcast_chaos_to_workers(self, specs, gen):
        frame = {"type": "chaos_update", "specs": list(specs or []),
                 "gen": gen}
        for w in list(self._workers.values()):
            if w.state == "dead" or w.worker_type == "client":
                continue
            try:
                await w.writer.send(dict(frame))
            # Dying worker: it re-adopts the current plan in its next
            # registration reply; nothing to do here.
            except Exception:  # rtlint: disable=swallowed-failure
                pass

    def _on_gcs_load_update(self, msg):
        self._apply_cluster_views(msg["nodes"])

    async def _on_gcs_push(self, msg: Dict[str, Any]):
        mtype = msg["type"]
        if mtype == "node_added":
            self._apply_cluster_views([msg["node"]])
            self._schedule()
        elif mtype == "cluster_load":
            self._apply_cluster_views(msg["nodes"])
        elif mtype == "node_fenced":
            self._on_node_fenced(
                msg["node_id"], int(msg.get("epoch") or 0),
                int(msg.get("incarnation") or 0),
            )
        elif mtype == "node_dead":
            self._invalidate_pgs(msg.get("invalid_pgs") or [])
            await self._on_node_dead_hex(
                msg["node_id"], dead_actors=msg.get("dead_actors")
            )
        elif mtype == "node_draining":
            self._on_peer_draining(msg["node_id"])
        elif mtype == "node_undrain":
            self._on_peer_undrain(msg["node_id"])
        elif mtype == "chaos_update":
            self._apply_chaos(msg.get("specs") or [], msg.get("gen"))

    async def _heartbeat_loop(self):
        interval = self.config.heartbeat_interval_s
        while not self._shutdown:
            await asyncio.sleep(interval)
            # Chaos plane: a suppressed heartbeat looks exactly like a
            # lost load report — the GCS death sweep eventually declares
            # this node dead. Only the SEND is faulted: the reconnect
            # branch below stays live, so after the death broadcast the
            # node re-registers and receives the current plan (a
            # disarmed plan heals it; an armed one keeps it flapping,
            # which is what a heartbeat-only partition really does).
            suppressed = False
            try:
                delay = faults.fire(faults.HEARTBEAT)
                if delay:
                    await asyncio.sleep(delay)
            except faults.InjectedFault:
                suppressed = True
            view = self._local_view(include_shapes=True)
            self._cluster_view[view["node_id"]] = view
            if self.is_head and self.gcs_service is not None:
                if not suppressed:
                    self.gcs_service.heartbeat(
                        self.node_id,
                        view["resources_available"],
                        view["pending_tasks"],
                        view.get("pending_shapes"),
                    )
            elif self._gcs_client is not None and not self._gcs_client.closed:
                if not suppressed:
                    try:
                        await self._gcs_client.notify(
                            {
                                "op": "heartbeat",
                                "available": view["resources_available"],
                                "pending": view["pending_tasks"],
                                "shapes": view.get("pending_shapes"),
                                "msg_id": None,
                            }
                        )
                    except Exception:
                        pass
            elif self._gcs_client is not None and self._gcs_client.closed:
                # Head gone: try to ride out a GCS restart before giving
                # up (the node only dies once the reconnect window ends).
                if not await self._reconnect_gcs():
                    sys.stderr.write(
                        "[ray_tpu] GCS gone past reconnect window; "
                        "exiting node\n"
                    )
                    os._exit(1)

    async def _health_loop(self):
        """Detect workers that died before registering (e.g. import errors)
        so pending tasks fail loudly instead of hanging (ref analogue:
        WorkerPool startup-failure handling + GcsHealthCheckManager)."""
        consecutive_failures = 0
        while not self._shutdown:
            await asyncio.sleep(0.5)
            for worker_id, proc in list(self._pending_procs.items()):
                if worker_id not in self._pending_procs:
                    # The log-tail await below yields the loop: a later
                    # snapshot entry may have registered (and been
                    # popped) during an earlier iteration's hop — its
                    # accounting already happened at registration.
                    continue
                if proc.poll() is None:
                    continue
                self._pending_procs.pop(worker_id, None)
                wtype = self._pending_types.pop(worker_id, "cpu")
                self._starting_workers[wtype] = max(
                    0, self._starting_workers[wtype] - 1
                )
                consecutive_failures += 1
                log = os.path.join(
                    self.session_dir, "logs", f"worker-{worker_id.hex()[:8]}.log"
                )
                # Crash diagnosis reads the log tail off the loop: the
                # old inline read pulled the WHOLE file through the loop
                # thread (rtlint loop-blocking).
                detail = await self._loop.run_in_executor(
                    None, _read_text_tail, log, 2000
                )
                sys.stderr.write(
                    f"[ray_tpu] worker {worker_id.hex()[:8]} exited during "
                    f"startup (code {proc.returncode}). Log tail:\n{detail}\n"
                )
                cluster_events.emit(
                    cluster_events.ERROR, cluster_events.WORKER,
                    f"worker {worker_id.hex()[:8]} exited during startup "
                    f"(code {proc.returncode})",
                    node_id=self.node_id.hex(),
                    custom_fields={"exit_code": proc.returncode,
                                   "log_tail": detail[-500:]},
                )
                if consecutive_failures >= 3:
                    # Workers cannot start at all: fail queued work loudly.
                    while self._ready:
                        rec = self._ready.popleft()
                        self._fail_task(
                            rec,
                            TaskError(
                                None,
                                rec.spec.name,
                                f"worker processes fail to start; last log:\n"
                                f"{detail}",
                            ),
                        )
                else:
                    self._schedule()
            if self._workers:
                consecutive_failures = 0
            # Hang/straggler sweep rides the same cadence; detected
            # records warn via background tasks so the stack capture's
            # round-trip never stalls this loop.
            try:
                await self._check_hung_tasks()
            except Exception as e:  # noqa: BLE001
                if not getattr(self, "_hang_sweep_warned", False):
                    self._hang_sweep_warned = True
                    sys.stderr.write(
                        f"[ray_tpu] node {self.node_id.hex()[:8]}: "
                        f"hang-diagnosis sweep failed ({e!r}); further "
                        f"failures suppressed\n"
                    )
            # Data-plane stall watchdog rides the same 0.5 s cadence:
            # publishes the live stalled{peer} gauge and emits one
            # deduped WARNING + flight-recorder record per stall
            # episode (check_stalls itself never raises).
            transfer = getattr(self, "_transfer", None)
            if transfer is not None:
                transfer.check_stalls()
            # Head-side leak sweep: kicks a background census fan-out
            # when due (the fan-out can wait out a dead node's timeout,
            # so it never rides this loop inline).
            if self.is_head:
                self._maybe_leak_sweep()

    def _call(self, coro):
        """Run a coroutine on the loop from a foreign thread."""
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    def call_sync(self, coro, timeout: Optional[float] = None):
        return self._call(coro).result(timeout)

    # ------------------------------------------------------- worker lifecycle

    def _spawn_worker(self, worker_type: str = "cpu"):
        """Synchronous spawn entry: reserves the starting-worker slot
        immediately so back-to-back scheduler passes can't over-spawn."""
        self._starting_workers[worker_type] += 1
        asyncio.ensure_future(self._spawn_worker_async(worker_type))

    async def _spawn_worker_async(self, worker_type: str = "cpu") -> WorkerID:
        worker_id = WorkerID.from_random()
        try:
            # Chaos plane: a suppressed spawn releases its starting slot
            # so the next scheduler pass simply retries (the advertised
            # degradation for worker_spawn).
            delay = faults.fire(faults.WORKER_SPAWN,
                                worker_type=worker_type)
            if delay:
                await asyncio.sleep(delay)
        except faults.InjectedFault:
            self._starting_workers[worker_type] = max(
                0, self._starting_workers[worker_type] - 1
            )
            self._schedule()
            return worker_id
        env = dict(os.environ)
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_NODE_SOCKET"] = self.socket_path
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        env["RAY_TPU_WORKER_TYPE"] = worker_type
        # Direct actor-call plane: the worker's TCP listener binds this
        # node's advertised IP, and its hello handshake + TLS wrap need
        # the session security config even when it was set through
        # system_config rather than the environment.
        env["RAY_TPU_NODE_IP"] = self.node_ip
        if self.config.session_token:
            env["RAY_TPU_SESSION_TOKEN"] = self.config.session_token
        if self.config.tls_cert_path:
            env["RAY_TPU_TLS_CERT_PATH"] = self.config.tls_cert_path
            env["RAY_TPU_TLS_KEY_PATH"] = self.config.tls_key_path
            env["RAY_TPU_TLS_CA_PATH"] = self.config.tls_ca_path
        # Task print() output must reach the log file (and the driver's log
        # monitor) as it happens, not at process exit.
        env["PYTHONUNBUFFERED"] = "1"
        if self.arena_name:
            env["RAY_TPU_ARENA"] = self.arena_name
        # Ensure the worker can import this package even when the driver was
        # launched from elsewhere with ray_tpu on sys.path but not installed.
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        existing_pp = env.get("PYTHONPATH", "")
        if pkg_root not in existing_pp.split(os.pathsep):
            env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + existing_pp if existing_pp else "")
            )
        if worker_type == "cpu":
            # CPU workers skip accelerator-runtime registration at interpreter
            # start (it costs seconds per process and the chip is exclusive);
            # only "tpu"-typed workers keep the accelerator environment.
            env.pop("PALLAS_AXON_POOL_IPS", None)
            if env.get("JAX_PLATFORMS", "") in ("", "axon", "tpu"):
                env["JAX_PLATFORMS"] = "cpu"
        # Type registered BEFORE the executor hop: a worker that boots
        # fast enough to register during the await below must pop its
        # real type (and decrement the right starting slot), not the
        # "cpu" default.
        self._pending_types[worker_id] = worker_type
        # fork+exec and the log-file open are milliseconds of blocking
        # syscalls — off the loop (rtlint loop-blocking), so a spawn
        # burst can't stall heartbeats/dispatch for the whole batch.
        try:
            proc = await self._loop.run_in_executor(
                None, self._spawn_worker_proc, worker_id, env
            )
        except OSError as e:
            # Spawn itself failed (unwritable log dir, EMFILE, ENOMEM):
            # release the starting slot so the scheduler retries instead
            # of waiting forever on a worker that never forked.
            self._pending_types.pop(worker_id, None)
            self._starting_workers[worker_type] = max(
                0, self._starting_workers[worker_type] - 1
            )
            cluster_events.emit(
                cluster_events.WARNING, cluster_events.WORKER,
                f"worker spawn failed before exec: {e!r}",
                node_id=self.node_id.hex(),
                custom_fields={"worker_type": worker_type,
                               "error_type": type(e).__name__},
            )
            self._schedule()
            return worker_id
        self._stats["workers_started"] += 1
        cluster_events.emit(
            cluster_events.DEBUG, cluster_events.WORKER,
            f"worker {worker_id.hex()[:8]} spawned "
            f"(pid {proc.pid}, type {worker_type})",
            node_id=self.node_id.hex(),
            custom_fields={"pid": proc.pid, "worker_type": worker_type},
        )
        if worker_id in self._workers:
            # Registration won the race against this resume: attach the
            # proc to the live handle (shutdown waits on it) instead of
            # parking a stale entry the health loop would misread as a
            # startup crash when the worker eventually exits.
            self._workers[worker_id].proc = proc
            return worker_id
        if worker_id not in self._pending_types:
            # Registered AND died during the hop: registration consumed
            # the type entry and _on_worker_death already did the death
            # accounting. Reap the exit status here; parking the proc
            # would make the health loop double-count the death as a
            # startup crash.
            proc.poll()
            return worker_id
        if self._shutdown:
            # Spawned into a closing node: the shutdown sweep already
            # drained _pending_procs, so reap the orphan here.
            try:
                proc.terminate()
            except OSError:
                pass  # already dead: nothing to reap
            self._pending_types.pop(worker_id, None)
            return worker_id
        # The handle is registered when the worker connects and
        # registers (_pending_types was set before the executor hop).
        self._pending_procs[worker_id] = proc
        return worker_id

    def _spawn_worker_proc(self, worker_id: WorkerID, env) -> "subprocess.Popen":
        """Blocking half of the worker spawn (log dir/file + fork+exec);
        runs in the loop's default executor, never on the loop."""
        log_path = os.path.join(self.session_dir, "logs")
        os.makedirs(log_path, exist_ok=True)
        out = open(os.path.join(
            log_path, f"worker-{worker_id.hex()[:8]}.log"), "wb")
        try:
            return subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.core.worker_main"],
                env=env,
                stdout=out,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        finally:
            out.close()

    async def _handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ):
        framed = _FramedWriter(writer)
        handle: Optional[WorkerHandle] = None
        try:
            msg = await _read_frame(reader)
            if msg.get("type") != "register":
                framed.close()
                return
            worker_id = WorkerID.from_hex(msg["worker_id"])
            proc = self._pending_procs.pop(worker_id, None)
            wtype = self._pending_types.pop(worker_id, "cpu")
            handle = WorkerHandle(
                worker_id=worker_id, writer=framed, proc=proc, worker_type=wtype
            )
            self._workers[worker_id] = handle
            self._starting_workers[wtype] = max(0, self._starting_workers[wtype] - 1)
            self._idle[wtype].append(worker_id)
            await framed.send({
                "type": "registered", "node_id": self.node_id.hex(),
                # Workers born under an armed chaos plan adopt it with
                # their registration ack (updates arrive as
                # chaos_update frames).
                "chaos": {"specs": faults.current_plan(),
                          "gen": faults.generation()},
            })
            self._schedule()
            while True:
                msg = await _read_frame(reader)
                await self._dispatch_message(handle, msg,
                                             time.monotonic())
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            if handle is not None:
                await self._on_worker_death(handle)
            framed.close()

    async def _dispatch_message(self, w: WorkerHandle, msg: Dict[str, Any],
                                recv_ts: Optional[float] = None):
        """Stage-clocked entry for every worker/client frame: queue-wait
        is recv->here, the handler stage covers the branch body, and
        branches that reply stamp handler_done via _send_reply so the
        flush shows up as reply_send. Deferred branches hand their clock
        to _bg_op and close it when the background handler finishes."""
        clock = dispatch_obs.op_clock("nm", msg.get("type"), recv_ts)
        if clock is not None:
            clock.start()
        try:
            await self._dispatch_message_op(w, msg, clock)
        finally:
            if clock is not None and not clock.deferred:
                clock.done()

    async def _send_reply(self, clock, w: WorkerHandle,
                          payload: Dict[str, Any]):
        if clock is not None:
            clock.handler_done()
        await w.writer.send(payload)

    async def _dispatch_message_op(self, w: WorkerHandle,
                                   msg: Dict[str, Any], clock=None):
        mtype = msg["type"]
        w.last_active = time.monotonic()
        if mtype == "task_done":
            await self._on_task_done(w, msg)
        elif mtype == "task_done_batch":
            # One wakeup for a burst of completions (the worker coalesces
            # dones while more queued tasks are waiting); _schedule() is
            # debounced so the batch costs one dispatch pass.
            for item in msg["items"]:
                await self._on_task_done(w, item)
        elif mtype == "submit":
            spec = msg["spec"]
            # Dedup by task_id: a thin client replaying a submit after a
            # connection blip must not double-queue the task. Live tasks
            # dedup against the record table; FAST tasks that finished
            # during the redial dedup against a bounded recent-ids set
            # (only acked submits are recorded — fire-and-forget worker
            # submits never replay).
            acked = msg.get("msg_id") is not None
            seen = (spec.task_id in self._tasks
                    or spec.task_id in self._recent_client_submits)
            if not seen:
                if acked:
                    self._recent_client_submits[spec.task_id] = None
                    while len(self._recent_client_submits) > 8192:
                        self._recent_client_submits.popitem(last=False)
                await self.submit_task(spec)
            if acked:
                await self._send_reply(clock, w, {
                    "type": "reply", "msg_id": msg["msg_id"], "ok": True,
                })
        elif mtype == "get_locations":
            self._bg_op(clock, self._reply_locations(w, msg))
        elif mtype == "wait":
            self._bg_op(clock, self._reply_wait(w, msg))
        elif mtype == "put":
            await self.put_object(
                msg["object_id"], msg["loc"], msg.get("refs", 1),
                pin_if_new=msg.get("pin_if_new", False),
                nested=msg.get("nested"),
            )
        elif mtype == "add_refs":
            for oid in msg["object_ids"]:
                self._pin_ref_bg(oid)
        elif mtype == "remove_refs":
            for oid, count in msg["counts"].items():
                self._remove_ref(oid, count)
        elif mtype == "fetch_function":
            await self._send_reply(
                clock, w,
                {
                    "type": "reply",
                    "msg_id": msg["msg_id"],
                    "blob": await self._function_blob(msg["function_id"]),
                }
            )
        elif mtype == "register_function":
            await self.register_function(msg["function_id"], msg["blob"])
        elif mtype == "blocked":
            self._on_worker_blocked(w)
        elif mtype == "unblocked":
            self._on_worker_unblocked(w)
        elif mtype == "reclaimed":
            self._on_tasks_reclaimed(w, msg)
        elif mtype == "kv":
            await self._handle_kv(w, msg)
        elif mtype == "pubsub":
            # Long-polls block; never hold up the worker's message loop.
            self._bg_op(clock, self._handle_pubsub(w, msg))
        elif mtype == "pg":
            self._bg_op(clock, self._handle_pg(w, msg))
        elif mtype == "actor_direct":
            if w.actor_id is not None:
                info = self._actors.get(w.actor_id)
                if info is not None:
                    info.direct_path = msg["path"]
                    addr = msg.get("addr")
                    info.direct_addr = tuple(addr) if addr else None
                    info.direct_ver = msg.get("ver", 1)
        elif mtype == "get_actor_direct":
            # Endpoint resolution long-polls the actor's drain window;
            # never inline it on this worker's message loop.
            self._bg_op(clock, self._reply_actor_direct(w, msg))
        elif mtype == "direct_side":
            # Caller-side bookkeeping for direct calls (the worker/client
            # mirror of the driver's dpost drain): return-slot
            # placeholders + arg pins at submit, seals/nested/unpins at
            # completion — one coalesced frame per burst.
            for oid in msg.get("returns", ()):
                self.directory.add(oid, _RETURN_PLACEHOLDER,
                                   initial_refs=0)
            for oid in msg.get("pins", ()):
                self._pin_ref_bg(oid)
            for oid, loc in msg.get("seals", ()):
                self._seal_object(oid, loc)
            for roid, inner in msg.get("nested", ()):
                self._register_nested(roid, inner)
            for oid, count in (msg.get("unpin") or {}).items():
                self._remove_ref(oid, count)
        elif mtype == "direct_done_batch":
            await self._on_direct_done_batch(w, msg)
        elif mtype == "actor_exit":
            await self._on_actor_graceful_exit(w, msg)
        elif mtype == "kill_actor":
            await self.kill_actor(msg["actor_id"], msg.get("no_restart", True))
        elif mtype == "cancel_task":
            await self.cancel_task(msg["task_id"], msg.get("force", False))
        elif mtype == "get_named_actor":
            spec = await self.get_named_actor(msg["name"])
            await self._send_reply(
                clock, w,
                {"type": "reply", "msg_id": msg["msg_id"], "spec": spec}
            )
        elif mtype == "state":
            state = await self.cluster_state()
            await self._send_reply(
                clock, w,
                {"type": "reply", "msg_id": msg["msg_id"], "state": state}
            )
        elif mtype == "events":
            # Head-store query; the long-path RPC must not stall this
            # worker's message loop.
            self._bg_op(clock, self._handle_events_query(w, msg))
        elif mtype == "timeseries":
            self._bg_op(clock, self._handle_timeseries_query(w, msg))
        elif mtype == "slo":
            self._bg_op(clock, self._handle_slo_query(w, msg))
        elif mtype in ("stack_reply", "profile_reply"):
            # A worker answering our stack_dump/profile fan-out.
            fut = self._profile_pending.pop(msg.get("req_id"), None)
            if fut is not None and not fut.done():
                fut.set_result(msg)
        elif mtype == "profile":
            # Cluster stacks/profile query from a worker or thin client;
            # the fan-out blocks on timeouts, so never inline it here.
            self._bg_op(clock, self._handle_profile_query(w, msg))
        elif mtype == "pull_object":
            # Client-mode read rides the SAME chunked, admission-
            # controlled transfer plane nodes use (small objects answer
            # inline; large ones advertise chunking — no multi-GB frames,
            # no event-loop-sized pickles).
            reply = await self._transfer.serve_pull(msg)
            reply.update({"type": "reply", "msg_id": msg["msg_id"]})
            await self._send_reply(clock, w, reply)
        elif mtype == "pull_chunk":
            reply = await self._transfer.serve_chunk(msg)
            reply.update({"type": "reply", "msg_id": msg["msg_id"]})
            await self._send_reply(clock, w, reply)
        elif mtype == "put_begin":
            # Client-mode put: a chunked writer into THIS node's store.
            try:
                writer = await self._loop.run_in_executor(
                    None, self.local_store.create_writer,
                    msg["object_id"], int(msg["size"]),
                )
                w.client_writers[msg["object_id"]] = writer
                reply = {"ok": True}
            # Reply-carried: the client sees and raises the error.
            except Exception as e:  # rtlint: disable=swallowed-failure
                reply = {"ok": False, "error": str(e)}
            reply.update({"type": "reply", "msg_id": msg["msg_id"]})
            await self._send_reply(clock, w, reply)
        elif mtype == "put_chunk":
            writer = w.client_writers.get(msg["object_id"])
            try:
                if writer is None:
                    raise RuntimeError("no open writer (put_begin missing)")
                await self._loop.run_in_executor(
                    None, writer.write, int(msg["offset"]), msg["data"]
                )
                reply = {"ok": True}
            # Reply-carried: the client sees and raises the error.
            except Exception as e:  # rtlint: disable=swallowed-failure
                reply = {"ok": False, "error": str(e)}
            reply.update({"type": "reply", "msg_id": msg["msg_id"]})
            await self._send_reply(clock, w, reply)
        elif mtype == "put_abort":
            # Client-side failure mid-put: free the reserved block now
            # instead of holding it until the connection drops.
            writer = w.client_writers.pop(msg["object_id"], None)
            if writer is not None:
                try:
                    await self._loop.run_in_executor(None, writer.abort)
                except Exception:
                    pass
            await self._send_reply(
                clock, w,
                {"type": "reply", "msg_id": msg["msg_id"], "ok": True}
            )
        elif mtype == "put_end":
            writer = w.client_writers.pop(msg["object_id"], None)
            finalized = False
            try:
                if writer is None:
                    raise RuntimeError("no open writer (put_begin missing)")
                loc = await self._loop.run_in_executor(
                    None, writer.finalize
                )
                finalized = True
                await self.put_object(msg["object_id"], loc, refs=0)
                reply = {"loc": loc}
            # Reply-carried: the client sees and raises the error.
            except Exception as e:  # rtlint: disable=swallowed-failure
                # The writer left client_writers above, so nothing else
                # can ever free its block — abort it here (only when
                # finalize itself failed: after a successful seal, abort
                # would free a block another path may already reference).
                if writer is not None and not finalized:
                    try:
                        await self._loop.run_in_executor(None, writer.abort)
                    except Exception:
                        pass
                reply = {"loc": None, "error": str(e)}
            reply.update({"type": "reply", "msg_id": msg["msg_id"]})
            await self._send_reply(clock, w, reply)
        elif mtype == "ping":
            await self._send_reply(
                clock, w, {"type": "reply", "msg_id": msg["msg_id"]})
        else:
            raise RuntimeError(f"unknown message type {mtype}")

    async def _on_worker_death(self, w: WorkerHandle):
        if w.state == "dead":
            return
        prev_state = w.state
        w.state = "dead"
        self._workers.pop(w.worker_id, None)
        exit_code = w.proc.poll() if w.proc is not None else None
        if exit_code is None and w.proc is not None and not self._shutdown:
            # The socket closes BEFORE the kernel finishes the exit, so
            # an immediate poll() often races to None and a real crash
            # classifies as a routine lifecycle event (the PR 14 tier-1
            # flake). Reap off the loop for a bounded window so the
            # exit code (or signal class) is actually captured.
            def _reap():
                try:
                    return w.proc.wait(timeout=2.0)
                # Still running past the window (or already reaped):
                # fall back to the None classification below.
                except Exception:  # rtlint: disable=swallowed-failure
                    return w.proc.poll()

            exit_code = await self._loop.run_in_executor(None, _reap)
        # Intentional kills (ray_tpu.kill(actor), force task-cancel) are
        # routine API usage, not crashes: keep them out of the ERROR view.
        graceful = (getattr(w, "_graceful_exit", False)
                    or getattr(w, "_intentional_kill", False))
        if w.worker_type == "client":
            pass  # thin-client disconnects are not worker lifecycle
        elif graceful or self._shutdown or exit_code in (0, None):
            # Clean exit / idle reap / node shutdown: routine lifecycle.
            cluster_events.emit(
                cluster_events.INFO, cluster_events.WORKER,
                f"worker {w.worker_id.hex()[:8]} exited"
                + (f" (code {exit_code})" if exit_code is not None else ""),
                node_id=self.node_id.hex(),
                actor_id=w.actor_id.hex() if w.actor_id else None,
                custom_fields={"exit_code": exit_code,
                               "graceful": graceful},
            )
        else:
            oom = getattr(w, "_oom_killed", False)
            cluster_events.emit(
                cluster_events.ERROR, cluster_events.WORKER,
                f"worker {w.worker_id.hex()[:8]} crashed "
                f"(exit code {exit_code})"
                + (" [killed by memory monitor]" if oom else ""),
                node_id=self.node_id.hex(),
                actor_id=w.actor_id.hex() if w.actor_id else None,
                custom_fields={
                    "exit_code": exit_code,
                    "oom_killed": oom,
                    "running_task": (w.current.spec.name
                                     if w.current is not None else None),
                },
            )
        for writer in w.client_writers.values():
            try:
                writer.abort()  # client died mid-put: free the block
            except Exception:
                pass
        w.client_writers.clear()
        pool = self._idle.get(w.worker_type)
        if pool is not None:  # "client" handles have no idle pool
            try:
                pool.remove(w.worker_id)
            except ValueError:
                pass
        if w.actor_id is not None:
            await self._on_actor_worker_death(w)
        elif w.current is not None or w.pending:
            running = w.current
            queued = list(w.pending)
            w.current = None
            w.pending.clear()
            if running is not None:
                self._release_task_resources(running)
                if running.state == "cancelled":
                    pass
                elif running.spec.retries_left > 0:
                    running.spec.retries_left -= 1
                    running.state = "ready"
                    running.worker_id = None
                    self._stats["tasks_retried"] += 1
                    self._ready.append(running)
                else:
                    detail = (
                        "killed by the node memory monitor (out of memory)"
                        if getattr(w, "_oom_killed", False)
                        else ""
                    )
                    self._fail_task(
                        running, WorkerCrashedError(running.spec.name, detail)
                    )
            for record in queued:
                # Pipelined frames never STARTED on this worker — requeue
                # them without charging a retry (a neighbor's death is not
                # this task's failure).
                self._release_task_resources(record)
                if record.state != "cancelled":
                    record.state = "ready"
                    record.worker_id = None
                    self._ready.append(record)
        elif prev_state in ("busy", "blocked"):
            pass
        if w.proc is not None and w.proc.poll() is None:
            try:
                w.proc.terminate()
            except Exception:
                pass
        self._schedule()

    def _spawn_bg(self, coro) -> asyncio.Task:
        """Run a cleanup coroutine with a strong reference held until done;
        shutdown() drains these so best-effort cleanups actually happen."""
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    def _bg_op(self, clock, coro) -> asyncio.Task:
        """ensure_future for a deferred frame op, keeping its stage
        clock honest: the clock re-stamps start when the background
        handler actually runs (so loop scheduling delay lands in
        queue_wait, not handler) and closes when it finishes."""
        if clock is None:
            return asyncio.ensure_future(coro)
        clock.deferred = True

        async def _run():
            clock.start()
            try:
                await coro
            finally:
                clock.done()

        return asyncio.ensure_future(_run())

    # ------------------------------------------------------------ peer plane

    async def _handle_peer_connection(self, reader, writer):
        framed = AioFramedWriter(writer)
        peer_hex = None
        try:
            hello = await aio_read_frame(reader)
            expected = self.config.session_token
            if expected and hello.get("token") != expected:
                framed.close()
                return
            if hello.get("type") == "client_hello":
                # Remote thin driver (ref: util/client proxier): serve
                # the worker protocol over this TCP connection; the
                # handle stays OUT of the schedulable pools.
                await self._serve_client(reader, framed)
                return
            if hello.get("type") != "peer_hello":
                framed.close()
                return
            peer_hex = hello["node_id"]
            if peer_hex in self._fenced_nodes:
                # Fenced incarnation dialing in: refuse — its frames
                # (task results, locates, seal pushes) name state the
                # cluster already recovered away from. A fresh
                # incarnation is unfenced at re-registration.
                _fencing.EVENT_PEER_REFUSED.inc()
                framed.close()
                return
            while True:
                msg = await aio_read_frame(reader)
                if peer_hex in self._fenced_nodes:
                    # Fenced mid-connection: drop the frame and the
                    # channel (the zombie's healthy socket must not
                    # keep feeding us stale results/locates).
                    _fencing.EVENT_PEER_REFUSED.inc()
                    break
                recv_ts = time.monotonic()
                clock = dispatch_obs.op_clock("peer", msg.get("type"),
                                              recv_ts)
                if msg.get("type") in ("stacks_dump", "profile_run",
                                       "traces_dump", "objects_census",
                                       "get_actor_direct_peer",
                                       "drain", "replicate_object"):
                    # Long-running introspection/resolution must not
                    # head-of-line block this channel's read loop (a 15s
                    # profile or a direct-endpoint drain wait would stall
                    # every state_snapshot/pg frame behind it); replies
                    # match by msg_id, so order doesn't matter.
                    self._bg_op(clock, self._peer_reply_async(
                        peer_hex, msg, framed, clock
                    ))
                    continue
                if clock is not None:
                    clock.start()
                try:
                    reply = await self._dispatch_peer(peer_hex, msg,
                                                      clock)
                    if reply is not None:
                        if clock is not None:
                            clock.handler_done()
                        reply["type"] = "reply"
                        reply["msg_id"] = msg.get("msg_id")
                        await framed.send(reply)
                finally:
                    if clock is not None:
                        clock.done()
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            framed.close()

    async def _peer_reply_async(self, peer_hex: str, msg, framed,
                                clock=None):
        """Dispatch a slow peer request off the channel's read loop and
        ship the reply when it completes."""
        try:
            reply = await self._dispatch_peer(peer_hex, msg)
        # Reply-carried: the requesting peer sees and handles the error.
        except Exception as e:  # rtlint: disable=swallowed-failure
            reply = {"error": str(e)}
        if reply is None:
            return
        if clock is not None:
            clock.handler_done()
        reply["type"] = "reply"
        reply["msg_id"] = msg.get("msg_id")
        try:
            await framed.send(reply)
        except Exception:
            pass

    async def _serve_client(self, reader, framed):
        handle: Optional[WorkerHandle] = None
        try:
            msg = await aio_read_frame(reader)
            if msg.get("type") != "register":
                return
            handle = WorkerHandle(
                worker_id=WorkerID.from_hex(msg["worker_id"]),
                writer=framed, worker_type="client", state="client",
            )
            await framed.send(
                {"type": "registered", "node_id": self.node_id.hex()}
            )
            while True:
                msg = await aio_read_frame(reader)
                await self._dispatch_message(handle, msg,
                                             time.monotonic())
        except (asyncio.IncompleteReadError, ConnectionResetError,
                OSError):
            pass
        finally:
            if handle is not None:
                await self._on_worker_death(handle)
            framed.close()

    async def _dispatch_peer(
        self, peer_hex: str, msg: Dict[str, Any], clock=None
    ) -> Optional[Dict[str, Any]]:
        mtype = msg["type"]
        if mtype == "forward_task":
            await self._on_forward_task(peer_hex, msg["spec"], msg["dep_locs"])
            return None
        if mtype == "task_result":
            self._on_remote_task_result(msg)
            return None
        if mtype in ("pull_object", "pull_chunk"):
            # Typed boundary: the transfer service's schemas validate the
            # frame before the handler runs (rpc.py ServiceRegistry). A
            # malformed frame fails THIS request with an error reply —
            # never the whole shared peer channel.
            try:
                return await self._transfer.rpc.dispatch(
                    peer_hex, mtype, msg, clock=clock
                )
            except RpcError as e:
                return {"data": None, "error": str(e)}
        if mtype == "free_object":
            self._remove_ref(msg["object_id"])
            return None
        if mtype == "register_borrow":
            # Owner side: a peer node holds live refs to our object; keep
            # it (and its lineage) until the peer releases the borrow.
            return {"ok": self.directory.add_borrower(
                msg["object_id"], msg["borrower"]
            )}
        if mtype == "release_borrow":
            self.directory.remove_borrower(
                msg["object_id"], msg["borrower"]
            )
            return None
        if mtype == "kill_actor_peer":
            await self.kill_actor(msg["actor_id"], msg.get("no_restart", True))
            return None
        if mtype == "cancel_task_peer":
            await self.cancel_task(msg["task_id"], msg.get("force", False))
            return None
        if mtype == "prepare_bundle":
            return {"ok": self._prepare_bundle(
                msg["pg_id"], msg["index"], msg["resources"]
            )}
        if mtype == "commit_bundle":
            bundle = self._bundles.get((msg["pg_id"], msg["index"]))
            if bundle is not None:
                bundle.state = "committed"
            self._schedule()
            return None
        if mtype == "release_bundle":
            self._release_bundle(msg["pg_id"], msg["index"])
            return None
        if mtype == "get_actor_direct_peer":
            # A remote caller resolving one of our actors' direct
            # endpoints (the UDS path is useless off-node, but the
            # caller filters by node id; the TCP addr is the payload).
            return {"direct": await self.get_actor_direct(
                msg["actor_id"], timeout=msg.get("timeout", 30.0)
            )}
        if mtype == "replicate_object":
            # Drain rider: the draining node asks us to adopt a primary
            # copy before it exits; we pull it over the normal transfer
            # plane and publish the new location.
            return await self._replicate_in(peer_hex, msg["object_id"])
        if mtype == "drain":
            return await self._handle_drain_request(
                msg.get("timeout") or self.config.drain_timeout_s
            )
        if mtype == "state_snapshot":
            return {"state": self._local_state_snapshot()}
        if mtype == "stacks_dump":
            # GCS ProfileService fan-out: this node's dump (head NM
            # included — the GCS reaches its own host over the same
            # peer channel it uses for every other node).
            return {"result": await self.stacks_dump(
                timeout=msg.get("timeout", 5.0)
            )}
        if mtype == "profile_run":
            return {"result": await self.profile_run(
                seconds=msg.get("seconds", 2.0), hz=msg.get("hz", 100)
            )}
        if mtype == "traces_dump":
            # GCS ProfileService fan-out: this node's flight-recorder
            # ring (same reach discipline as stacks_dump).
            return {"result": self.traces_dump(
                reason=msg.get("reason") or None,
                limit=msg.get("limit", 200),
            )}
        if mtype == "objects_census":
            # GCS ObjectService fan-out: this node's bounded object
            # index + store/spill totals (same reach discipline).
            return {"result": self.objects_census(
                limit=msg.get("limit", 500)
            )}
        raise RuntimeError(f"unknown peer message {mtype}")

    # ------------------------------------------------------ bundle resources

    def _prepare_bundle(self, pg_id: str, index: int, resources) -> bool:
        """Reserve a bundle's resources from the node pool (ref:
        PlacementGroupResourceManager::PrepareBundle)."""
        key = (pg_id, index)
        if key in self._bundles:
            return True  # idempotent retry
        req = ResourceSet(resources)
        if not self.node_resources.acquire(req):
            return False
        self._bundles[key] = BundleState(
            pg_id=pg_id,
            index=index,
            resources=req,
            available=ResourceSet(_fixed=dict(req._amounts)),
        )
        return True

    def _release_bundle(self, pg_id: str, index: int):
        """Return a bundle's unused reservation to the node pool; resources
        of still-running bundle tasks flow back on their completion (ref:
        PlacementGroupResourceManager::ReturnBundle)."""
        bundle = self._bundles.pop((pg_id, index), None)
        if bundle is not None:
            self.node_resources.release(bundle.available)
        self._pg_nodes.pop(pg_id, None)
        self._schedule()

    def _invalidate_pgs(self, pg_ids: List[str]):
        """A node death sent these groups back to pending: drop routing
        caches and local bundle reservations so the GCS can re-place them
        with fresh prepares; parked/queued tasks re-resolve via the GCS
        instead of forwarding to a stale node (advisor finding r1)."""
        for pg_id in pg_ids:
            self._pg_nodes.pop(pg_id, None)
            for key in [k for k in self._bundles if k[0] == pg_id]:
                self._release_bundle(*key)

    def _find_local_bundle(
        self, strategy: PlacementGroupSchedulingStrategy, req: ResourceSet
    ) -> Optional[BundleState]:
        idx = strategy.placement_group_bundle_index
        if idx >= 0:
            bundle = self._bundles.get((strategy.pg_id, idx))
            if (
                bundle is not None
                and bundle.state == "committed"
                and req.is_subset_of(bundle.available)
            ):
                return bundle
            return None
        for (pg_id, _i), bundle in sorted(self._bundles.items()):
            if (
                pg_id == strategy.pg_id
                and bundle.state == "committed"
                and req.is_subset_of(bundle.available)
            ):
                return bundle
        return None

    def _acquire_for_record(self, record: TaskRecord) -> bool:
        """Bundle-aware resource acquisition; sets record.bundle_key."""
        strategy = record.spec.scheduling_strategy
        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            bundle = self._find_local_bundle(strategy, record.spec.resources)
            if bundle is None:
                return False
            bundle.available = bundle.available - record.spec.resources
            record.bundle_key = (bundle.pg_id, bundle.index)
            return True
        return self.node_resources.acquire(record.spec.resources)

    def _release_task_resources(self, record: TaskRecord):
        if not record.resources_held:
            return
        record.resources_held = False
        res = record.spec.resources
        if record.bundle_key is not None:
            bundle = self._bundles.get(record.bundle_key)
            if bundle is not None:
                bundle.available = bundle.available + res
                return
            # Bundle released while the task ran: its reservation already
            # excluded these resources, so they rejoin the node pool.
        self.node_resources.release(res)

    def _pg_targets(
        self, strategy: PlacementGroupSchedulingStrategy
    ) -> Optional[List[str]]:
        mapping = self._pg_nodes.get(strategy.pg_id)
        if mapping is None:
            return None
        idx = strategy.placement_group_bundle_index
        if idx >= 0:
            node = mapping.get(idx)
            return [node] if node else []
        return list(dict.fromkeys(mapping.values()))

    def _queue_pg_resolve(self, record: TaskRecord):
        """Park the record on this pg's (single) in-flight map resolution."""
        pg_id = record.spec.scheduling_strategy.pg_id
        waiters = self._pg_waiters.setdefault(pg_id, [])
        waiters.append(record)
        if len(waiters) == 1:
            asyncio.ensure_future(self._resolve_pg(pg_id))

    async def _resolve_pg(self, pg_id: str):
        """Fetch the bundle->node map from the GCS, then re-place every
        record parked on it. A still-*pending* group keeps its records
        parked (the reference queues tasks until the PG is placed or
        removed, it never times them out); only a removed/unknown group
        fails them."""
        ok = False
        while not self._shutdown:
            state = "unknown"
            if self._gcs is None:
                break
            try:
                ok = await self._gcs.pg_wait(
                    pg_id, self.config.object_locate_timeout_s
                )
                info = await self._gcs.pg_get(pg_id)
                state = info.get("state", "unknown")
                nodes = info.get("bundle_nodes")
                if ok and state == "created" and nodes:
                    self._pg_nodes[pg_id] = {int(k): v for k, v in nodes.items()}
                else:
                    ok = False
            except Exception:
                ok = False
            if ok or state in ("removed", "unknown"):
                break
            # Group exists but is still pending: poll again, keeping the
            # records parked.
            await asyncio.sleep(0.2)
        for record in self._pg_waiters.pop(pg_id, []):
            if record.state == "cancelled":
                continue
            if ok:
                record.spillbacks = 0  # fresh map: forwarding budget resets
                self._task_ready(record)
            else:
                self._fail_task(
                    record,
                    TaskError(
                        None,
                        record.spec.name,
                        f"placement group {pg_id[:8]} was removed or is "
                        "unknown",
                    ),
                )

    def _pg_unservable(
        self, strategy: PlacementGroupSchedulingStrategy, req: ResourceSet
    ) -> Optional[str]:
        """A locally-routed PG request that can never be served: request
        exceeds every candidate bundle's total, or the bundles are gone
        (group removed). None means 'may fit later, keep waiting'."""
        idx = strategy.placement_group_bundle_index
        local = [
            b for (pg, i), b in self._bundles.items()
            if pg == strategy.pg_id and (idx < 0 or i == idx)
        ]
        if not local:
            return (
                f"placement group {strategy.pg_id[:8]} has no bundles on "
                "this node (removed?)"
            )
        if all(not req.is_subset_of(b.resources) for b in local):
            return (
                f"request {req.to_dict()} exceeds placement group bundle "
                f"resources"
            )
        return None

    async def _get_peer(self, peer_hex: str) -> PeerClient:
        if peer_hex in self._fenced_nodes:
            raise ConnectionError(
                f"node {peer_hex[:8]} fenced at epoch "
                f"{self._fenced_nodes[peer_hex]}"
            )
        peer = self._peers.get(peer_hex)
        if isinstance(peer, asyncio.Future):
            # A concurrent caller is connecting: share its connection so
            # message order over one socket is preserved.
            return await asyncio.shield(peer)
        if peer is not None and not peer.closed:
            return peer
        view = self._cluster_view.get(peer_hex)
        if view is None:
            raise ConnectionError(f"node {peer_hex[:8]} not in cluster view")
        fut: asyncio.Future = self._loop.create_future()
        self._peers[peer_hex] = fut
        try:
            peer = PeerClient(
                peer_hex, view["host"], view["peer_port"], self.node_id.hex()
            )
            await peer.connect()
        except Exception as e:
            self._peers.pop(peer_hex, None)
            if not fut.done():
                fut.set_exception(e)
                # Consume if nobody awaited, to silence the loop warning.
                fut.exception()
            raise
        self._peers[peer_hex] = peer
        if not fut.done():
            fut.set_result(peer)
        return peer

    def _build_dep_locs(self, spec: TaskSpec) -> Dict[ObjectID, Location]:
        """Location hints shipped with a forwarded task so the target can
        pull arguments without a directory round-trip (ref analogue: the
        lease response's resolved dependency locations)."""
        dep_locs: Dict[ObjectID, Location] = {}
        for oid in spec.dependency_ids():
            loc = self.directory.lookup(oid)
            if loc is None:
                continue
            if isinstance(loc, (InlineLocation, RemoteLocation)):
                dep_locs[oid] = loc
            else:
                dep_locs[oid] = RemoteLocation(self.node_id.hex(), loc.size)
        return dep_locs

    def _forward_record(self, record: TaskRecord, target_hex: str):
        record.state = "forwarded"
        record.target = target_hex
        # The grace window measures CONTINUOUS infeasibility: a task
        # that found a target is feasible again, so a later requeue
        # (forward failure, peer partition) restarts the clock instead
        # of inheriting an already-expired one.
        record.infeasible_since = None
        record.spillbacks += 1
        self._forwarded[record.spec.task_id] = record
        dep_locs = self._build_dep_locs(record.spec)
        asyncio.ensure_future(self._forward_send(record, target_hex, dep_locs))

    async def _forward_send(self, record, target_hex, dep_locs):
        try:
            peer = await self._get_peer(target_hex)
            await peer.notify(
                {
                    "type": "forward_task",
                    "spec": record.spec,
                    "dep_locs": dep_locs,
                }
            )
        except Exception:
            # Target unreachable: treat like a node death for this record.
            self._forwarded.pop(record.spec.task_id, None)
            self._cluster_view.pop(target_hex, None)
            self._requeue_forwarded(record, target_hex)

    def _requeue_forwarded(self, record: TaskRecord, dead_hex: str):
        """Re-place a record whose forward target is gone, respecting the
        task type (an actor task must re-route via the actor directory, not
        the normal ready queue)."""
        record.state = "ready"
        record.target = None
        spec = record.spec
        if spec.task_type == TaskType.ACTOR_TASK:
            if self._actor_homes.get(spec.actor_id) == dead_hex:
                self._actor_homes[spec.actor_id] = "dead"
            self._route_actor_task_cluster(record)
        elif spec.task_type == TaskType.ACTOR_CREATION_TASK:
            if self._actor_homes.get(spec.actor_id) == dead_hex:
                self._actor_homes.pop(spec.actor_id, None)
            self._task_ready(record)
        else:
            self._task_ready(record)

    async def _on_forward_task(self, origin_hex, spec: TaskSpec, dep_locs):
        for oid, loc in dep_locs.items():
            # Only adopt the hint when the object is unknown here; a local
            # placeholder means this node is itself producing it, and the
            # local seal path must win (and will wake waiters).
            if self.directory.lookup(oid) is None:
                self._seal_object(oid, loc)
        await self.submit_task(spec, origin=origin_hex)
        # Hold the return slots on behalf of the origin until it frees them
        # (the origin's directory entry maps here via RemoteLocation).
        for oid in spec.return_ids():
            self.directory.add_ref(oid)

    def _notify_origin(self, record: TaskRecord, failed: bool):
        """Push a forwarded task's results back to the node that sent it."""
        results = []
        for oid in record.spec.return_ids():
            loc = self.directory.lookup(oid)
            if loc is None:
                continue
            if isinstance(loc, (InlineLocation, RemoteLocation)):
                results.append((oid, loc))
                # Inline bytes travel with the message: the origin needs no
                # hold on our copy, so release the one _on_forward_task took.
                self.directory.remove_ref(oid)
                if isinstance(loc, RemoteLocation) and loc.held:
                    # The third-party hold transfers to the origin; clear our
                    # copy's flag so our GC doesn't also free it.
                    self.directory.replace_location(
                        oid, RemoteLocation(loc.node_id, loc.size, held=False)
                    )
            else:
                results.append(
                    (oid, RemoteLocation(self.node_id.hex(), loc.size, held=True))
                )
        origin = record.origin

        async def _send():
            try:
                peer = await self._get_peer(origin)
                await peer.notify(
                    {
                        "type": "task_result",
                        "task_id": record.spec.task_id,
                        "results": results,
                        "failed": failed,
                    }
                )
            except Exception:
                pass  # origin died; its successor will never ask

        asyncio.ensure_future(_send())

    def _on_remote_task_result(self, msg: Dict[str, Any]):
        record = self._forwarded.pop(msg["task_id"], None)
        if record is None:
            return
        for oid, loc in msg["results"]:
            self._seal_object(oid, loc)
        if msg.get("failed"):
            record.state = "failed"
            self._stats["tasks_failed"] += 1
        else:
            record.state = "finished"
            self._stats["tasks_finished"] += 1
        if record.spec.task_type != TaskType.ACTOR_CREATION_TASK:
            self._unpin_deps(record)
            # No history row here: the EXECUTING node already retained
            # the terminal record (with duration + error detail) in its
            # own _on_task_done/_fail_task — a second row at the origin
            # would double-count the task cluster-wide.
            self._tasks.pop(record.spec.task_id, None)

    async def _on_node_dead_hex(self, node_hex: str, dead_actors=None):
        """A peer died: fail/retry work bound to it (ref analogue:
        NodeManager::NodeRemoved + TaskManager retry on node failure)."""
        self._cluster_view.pop(node_hex, None)
        peer = self._peers.pop(node_hex, None)
        if isinstance(peer, PeerClient):
            peer.close()
        elif peer is not None:
            peer.cancel()
        # Its data channels are dead sockets: close them so in-flight
        # stripe reads error out now instead of at the io timeout.
        self._transfer.drop_peer(node_hex)
        # Borrows die with the node: void its registrations in our
        # borrower sets (owner side) and forget owners that vanished
        # (borrower side — releases to a ghost would just error).
        self.directory.drop_borrower_node(node_hex)
        for oid in [o for o, h in self._borrowed_from.items()
                    if h == node_hex]:
            self._borrowed_from.pop(oid, None)
        # Remote actors homed there are gone (mark before requeueing so
        # re-routed actor tasks fail with ActorDiedError, not a plain-worker
        # dispatch). Restartable creations this node owns re-place on a
        # surviving node below (_restart_actor_elsewhere); creations
        # still in flight also retry elsewhere.
        if dead_actors is None:
            dead_actors = [
                aid.hex() for aid, h in self._actor_homes.items() if h == node_hex
            ]
        for aid_hex in dead_actors:
            aid = ActorID.from_hex(aid_hex)
            if self._actor_homes.get(aid) == node_hex:
                self._actor_homes[aid] = "dead"
        # Restart-elsewhere: creations this node owns whose home was
        # just fenced re-place on a surviving node, within the pinned
        # restart budget (ref analogue: GcsActorManager::OnNodeDead
        # rescheduling dead actors onto live raylets).
        for aid_hex in dead_actors:
            aid = ActorID.from_hex(aid_hex)
            if aid in self._actor_creations:
                self._spawn_bg(self._restart_actor_elsewhere(aid))
        # Objects whose only known copy was on the dead node: unseal the
        # ones whose lineage we own so the next consumer (or a dependency
        # resolution) re-executes the creating task instead of pulling from
        # a ghost. Borrowed objects (no lineage here) keep their stale
        # location and fail fast at pull with recovery via the GCS replica
        # set (ref analogue: ObjectRecoveryManager on node removal).
        for oid in self.directory.remote_entries(node_hex):
            if oid in self._lineage:
                self._sealed.discard(oid)
                if oid in self._dep_index or oid in self._seal_events:
                    # Consumers are already parked on this object: kick the
                    # re-execution now, their seal waits stay valid.
                    self._spawn_bg(self._reconstruct_object(oid))
        # Forwarded tasks: retry elsewhere or fail.
        for task_id, record in list(self._forwarded.items()):
            if record.target != node_hex:
                continue
            del self._forwarded[task_id]
            if record.spec.task_type == TaskType.ACTOR_TASK:
                # The actor died with its node; retries can't help.
                self._fail_task(
                    record,
                    ActorDiedError(
                        record.spec.name, f"node {node_hex[:8]} died"
                    ),
                )
            elif record.spec.retries_left > 0:
                record.spec.retries_left -= 1
                self._stats["tasks_retried"] += 1
                self._requeue_forwarded(record, node_hex)
            else:
                self._fail_task(
                    record,
                    WorkerCrashedError(
                        f"{record.spec.name} (node {node_hex[:8]} died)"
                    ),
                )
        self._schedule()

    async def _restart_actor_elsewhere(self, aid: ActorID):
        """Re-place an owned restartable actor whose home node was
        fenced: re-submit the pinned creation spec so the scheduler
        picks a surviving node, under the pinned restart budget. The
        fresh placement gets a NEW GCS-assigned incarnation, so any
        caller still holding the fenced incarnation's endpoint is
        refused at the hello and re-resolves. Calls parked on the
        "dead" home re-route via _route_actor_via_gcs once the new home
        registers; direct-replay calls bound to the fenced incarnation
        stay REFUSED (a restarted actor has no replay-dedup cache —
        executing them could double-execute)."""
        spec = self._actor_creations.get(aid)
        if spec is None:
            return
        if self._actor_homes.get(aid) != "dead":
            return  # recovered (or restarted) already
        budget = self._actor_restart_budget.get(aid, 0)
        if budget == 0:
            cluster_events.emit(
                cluster_events.ERROR, cluster_events.ACTOR,
                f"actor {aid.hex()[:8]} ({spec.class_name}) died with "
                f"its fenced node and has no restarts left",
                node_id=self.node_id.hex(), actor_id=aid.hex(),
            )
            return
        if budget > 0:
            self._actor_restart_budget[aid] = budget - 1
        cluster_events.emit(
            cluster_events.WARNING, cluster_events.ACTOR,
            f"actor {aid.hex()[:8]} ({spec.class_name}) restarting on a "
            f"surviving node after its home was fenced "
            f"({'unlimited' if budget < 0 else budget - 1} restart(s) "
            f"left)",
            node_id=self.node_id.hex(), actor_id=aid.hex(),
            custom_fields={"class_name": spec.class_name},
        )
        oid = spec.return_ids()[0]
        ev = self._seal_events.get(oid)
        if ev is not None:
            ev.clear()
        self._sealed.discard(oid)
        self._actor_homes.pop(aid, None)
        await self.submit_task(spec)

    # ------------------------------------------------------------------ drain

    async def _handle_drain_request(self, timeout: float) -> Dict[str, Any]:
        """Drain state machine (ref analogue: DrainRaylet +
        local_object_manager spill-before-exit). By the time this runs,
        phase "begin" already made the node unschedulable cluster-wide
        (peers mark the view draining; serve replicas were migrated by
        the controller). Here: (1) let in-flight local work finish,
        bounded by ``timeout`` — whatever misses the window replays via
        lineage after the death broadcast; (2) replicate primary object
        copies to surviving nodes so consumers re-locate instead of
        reconstructing; (3) ack, flush events, and fire
        ``on_drain_complete`` so the host process exits cleanly."""
        self._draining = True
        # Idempotent re-signal: a phase="finish"-only caller (or a lost
        # begin-phase frame) must still give cooperative tenants their
        # preemption window before the in-flight wait below starts.
        await self._broadcast_drain_to_workers(True)
        cluster_events.emit(
            cluster_events.INFO, cluster_events.RAYLET,
            f"node {self.node_id.hex()[:8]} drain started "
            f"(timeout {timeout:.0f}s)",
            node_id=self.node_id.hex(),
        )
        loop = self._loop
        deadline = loop.time() + max(1.0, float(timeout))
        wait = Backoff(base=0.05, factor=1.3, max_delay=0.5, jitter=0.0)
        while loop.time() < deadline:
            # In-flight work: queued/running tasks, plus RUNNING actor
            # methods (w.current on an actor worker) — a preempted train
            # gang is mid-checkpoint inside one of those; killing it at
            # the first sweep would waste the cooperative window the
            # node_draining broadcast just opened. Queued-but-unstarted
            # actor calls are NOT waited for (the actor dies with the
            # node either way).
            busy = bool(self._ready) or any(
                (w.current is not None
                 or (w.pending and w.actor_id is None))
                for w in self._workers.values()
                if w.state != "dead" and w.worker_type != "client"
            )
            if not busy:
                break
            await asyncio.sleep(wait.next_delay())
        replicated = await self._replicate_for_drain(deadline)
        leftover = [
            info for info in self._actors.values()
            if info.state in ("alive", "pending", "restarting")
        ]
        if leftover:
            cluster_events.emit(
                cluster_events.WARNING, cluster_events.RAYLET,
                f"node {self.node_id.hex()[:8]} draining with "
                f"{len(leftover)} live actor(s) — they die with the "
                f"node (callers see ActorDiedError)",
                node_id=self.node_id.hex(),
                custom_fields={"leftover_actors": len(leftover)},
            )
        cluster_events.emit(
            cluster_events.INFO, cluster_events.RAYLET,
            f"node {self.node_id.hex()[:8]} drained: replicated "
            f"{replicated} object(s), {len(leftover)} actor(s) left",
            node_id=self.node_id.hex(),
            custom_fields={"replicated": replicated,
                           "leftover_actors": len(leftover)},
        )
        # Ship the tail of the event ring while the transport is up —
        # the process exits right after the ack.
        try:
            cluster_events.flush()
        except Exception:
            pass
        if self.on_drain_complete is not None:
            # After the ack frame is on the wire (the reply is sent by
            # the peer handler right after this returns).
            loop.call_later(0.5, self._fire_drain_complete)
        return {"ok": True, "replicated": replicated,
                "leftover_actors": len(leftover), "error": ""}

    def _fire_drain_complete(self):
        if not self._draining:
            # The drain was aborted between our ack and this timer (ack
            # reply lost → GCS reported failure → phase="abort" rolled
            # us back to alive): exiting now would kill a node the
            # operator was just told is back in service.
            return
        try:
            if self.on_drain_complete is not None:
                self.on_drain_complete()
        except Exception:
            pass

    async def _replicate_for_drain(self, deadline: float) -> int:
        """Push every primary (locally-stored, sealed) object copy to a
        surviving node before exit (ref analogue: the reference's
        drain-time object spilling; here the replica is re-homed into a
        peer's store and published, so borrowers re-locate through the
        GCS instead of pulling from a ghost)."""
        me = self.node_id.hex()
        # Only durable nodes may adopt primary copies: a 0-resource
        # view is an ephemeral attach driver (the `rtpu drain` CLI
        # itself registers one and shuts it down right after the
        # drain) — re-homing an object's only copy there loses it.
        targets = [
            h for h, v in self._cluster_view.items()
            if h != me and v.get("state", "alive") == "alive"
            and any(amt > 0 for amt in
                    (v.get("resources_total") or {}).values())
        ]
        if not targets:
            return 0
        # Fan out with a bounded window: sequential one-request-at-a-
        # time replication caps throughput at one object per round trip
        # and an object-heavy node blows the drain deadline with most
        # of its store abandoned to lineage re-execution. The target
        # side already spawns replicate_object off its dispatch loop,
        # so a window of pulls overlaps cleanly.
        sem = asyncio.Semaphore(8)
        count = 0
        cut_off = 0
        failed = 0

        async def _push(oid: ObjectID, first: int) -> None:
            nonlocal count, cut_off, failed
            async with sem:
                # One retry on the next target: a single full/flaky
                # peer must not silently strand every object that
                # round-robin happened to assign to it.
                for attempt in range(2):
                    if self._loop.time() >= deadline:
                        cut_off += 1
                        return
                    target = targets[(first + attempt) % len(targets)]
                    try:
                        peer = await self._get_peer(target)
                        reply = await peer.request(
                            {"type": "replicate_object",
                             "object_id": oid},
                            timeout=min(30.0, max(
                                5.0, deadline - self._loop.time()
                            )),
                        )
                        if reply.get("ok"):
                            count += 1
                            return
                    except Exception:
                        continue
                failed += 1

        pushes = []
        i = 0
        for oid in list(self._sealed):
            loc = self.directory.lookup(oid)
            if loc is None or isinstance(loc, RemoteLocation):
                continue
            pushes.append(_push(oid, i))
            i += 1
        if pushes:
            await asyncio.gather(*pushes)
        if cut_off or failed:
            cluster_events.emit(
                cluster_events.WARNING, cluster_events.RAYLET,
                f"drain replication incomplete: {count} object(s) "
                f"replicated, {failed} failed on every target, "
                f"{cut_off} abandoned at the deadline; lineage covers "
                f"the rest",
                node_id=me,
            )
        return count

    async def _replicate_in(self, source_hex: str,
                            oid: ObjectID) -> Dict[str, Any]:
        """Adopt a primary copy from a draining peer: pull it over the
        normal transfer plane (data-plane stripes, chunk fallback) and
        publish the new location."""
        loc = self.directory.lookup(oid)
        if loc is not None and not isinstance(loc, RemoteLocation):
            return {"ok": True}
        if loc is None:
            self.directory.add(
                oid, RemoteLocation(source_hex, 0), initial_refs=0,
                owner="replica",
            )
            loc = self.directory.lookup(oid)
        try:
            new_loc = await self._ensure_local(oid, loc)
            self._seal_object(oid, new_loc)
            return {"ok": True}
        # Reply-carried: the drainer counts this object as failed and
        # reports it in the drain WARNING.
        except Exception as e:  # rtlint: disable=swallowed-failure
            return {"ok": False, "error": str(e) or type(e).__name__}

    # ------------------------------------------------------------- scheduling

    async def submit_task(self, spec: TaskSpec, origin: Optional[str] = None):
        self.submit_task_sync(spec, origin)

    def submit_task_sync(self, spec: TaskSpec, origin: Optional[str] = None):
        """Entry point for driver, nested worker, and peer-forwarded
        submissions (ref analogue: ClusterTaskManager::QueueAndScheduleTask).
        Never awaits — the driver's batched submit drain calls it straight
        from a loop callback."""
        self._stats["tasks_submitted"] += 1
        # Unpickled specs carry fresh copies of descriptors every call of
        # a function repeats; intern them so a deep queue stores each once.
        intern_spec(spec)
        record = TaskRecord(spec=spec, origin=origin)
        self._tasks[spec.task_id] = record
        for oid in spec.return_ids():
            # Return slots exist in the directory from submission time so
            # consumers can hold refs before the task runs. One shared
            # placeholder instance — a 1M-deep queue creates 1M slots,
            # and the location is frozen anyway.
            self.directory.add(oid, _RETURN_PLACEHOLDER, initial_refs=0,
                               owner=getattr(spec, "name", "") or "task")
        if (
            origin is None
            and spec.task_type == TaskType.NORMAL_TASK
            and self.config.enable_lineage_reconstruction
        ):
            # This node owns the task: pin its spec so lost return objects
            # can be rebuilt by re-execution (normal tasks only — actor
            # state is recovered by actor restart, not task replay).
            for oid in spec.return_ids():
                self._lineage[oid] = spec
        # Pin dependencies AND refs smuggled inside argument values for
        # the task's lifetime so owners dropping their refs mid-flight
        # cannot free an argument (ref analogue: submitted task references
        # + nested ids in ReferenceCounter).
        for oid in spec.pinned_ids():
            self._pin_ref_bg(oid)
        if spec.task_type == TaskType.ACTOR_CREATION_TASK:
            # Register the pending actor synchronously so method calls that
            # land during async placement queue instead of failing (ref
            # analogue: RegisterActor before CreateActor,
            # gcs_actor_manager.cc:255).
            self._pre_register_actor(spec)
            if origin is None and spec.max_restarts != 0:
                # This node OWNS a restartable creation: pin the spec +
                # a restart budget so a fenced home node re-places the
                # actor on a survivor (setdefault: a restart
                # re-submission must not refill the budget).
                self._actor_creations[spec.actor_id] = spec
                self._actor_restart_budget.setdefault(
                    spec.actor_id, spec.max_restarts
                )
        if spec.task_type == TaskType.ACTOR_TASK:
            # Actor tasks never wait for deps here: the actor's worker
            # resolves arguments at execution, which preserves per-caller
            # submission order (ref analogue: sequential_actor_submit_queue).
            self._route_actor_task_cluster(record)
            return
        missing = {oid for oid in spec.dependency_ids() if oid not in self._sealed}
        if missing:
            record.state = "waiting"
            self._waiting[spec.task_id] = (record, missing)
            for oid in missing:
                self._dep_index.setdefault(oid, set()).add(spec.task_id)
                if (self.directory.lookup(oid) is None
                        or oid in self._borrow_stubs):
                    # Unknown here — or only a count-only borrow stub
                    # (the pin above created one): find the real copy.
                    asyncio.ensure_future(self._locate_missing(oid))
                elif oid in self._lineage:
                    # Entry exists but is unsealed: either its creating task
                    # is in flight (no-op) or its copy died with a node —
                    # re-execute from lineage.
                    self._spawn_bg(self._reconstruct_object(oid))
        else:
            self._task_ready(record)

    def _task_ready(self, record: TaskRecord):
        """Dependencies are available: place the task (ref analogue: the
        hand-off from DependencyManager to ClusterTaskManager dispatch)."""
        spec = record.spec
        if spec.task_type == TaskType.ACTOR_CREATION_TASK:
            self._place_creation(record)
            return
        record.state = "ready"
        self._ready.append(record)
        self._schedule()

    def _place_creation(self, record: TaskRecord):
        """Pick a node for an actor (ref analogue: GcsActorScheduler
        ScheduleByRaylet picking a forward target)."""
        spec = record.spec
        raw_strategy = getattr(spec, "scheduling_strategy", None)
        if isinstance(raw_strategy, PlacementGroupSchedulingStrategy):
            targets = self._pg_targets(raw_strategy)
            if targets is None:
                self._queue_pg_resolve(record)
                return
            if not targets:
                self._fail_task(
                    record,
                    TaskError(
                        None, spec.name,
                        "placement group bundle index out of range",
                    ),
                )
                return
            if self.node_id.hex() in targets or record.origin is not None:
                self._register_actor(record)
            elif record.spillbacks >= self.config.max_task_spillback:
                # Stale routing cache: re-resolve through the GCS.
                self._pg_nodes.pop(raw_strategy.pg_id, None)
                self._queue_pg_resolve(record)
            else:
                self._actor_homes[spec.actor_id] = targets[0]
                info = self._actors.pop(spec.actor_id, None)
                self._forward_record(record, targets[0])
                if info is not None:
                    while info.queued:
                        qspec = info.queued.popleft()
                        qrec = self._tasks.get(qspec.task_id)
                        if qrec is not None and qrec.state != "cancelled":
                            self._forward_record(qrec, targets[0])
            return
        strategy = raw_strategy or "DEFAULT"
        if (
            record.origin is None
            and self._multi_node
            and record.spillbacks < self.config.max_task_spillback
        ):
            self._cluster_view[self.node_id.hex()] = self._local_view()
            target = pick_node(
                spec.resources,
                strategy,
                self.node_id.hex(),
                list(self._cluster_view.values()),
                spread_threshold=self.config.scheduler_spread_threshold,
            )
            if target is None:
                self._fail_task(
                    record,
                    TaskError(
                        None,
                        spec.name,
                        f"infeasible actor resources {spec.resources.to_dict()} "
                        f"on every node in the cluster",
                    ),
                )
                info = self._actors.get(spec.actor_id)
                if info is not None and info.state == "pending":
                    info.state = "dead"
                    info.death_cause = "infeasible actor resources"
                    self._fail_actor_queue(info, info.death_cause)
                return
            if target != self.node_id.hex():
                self._actor_homes[spec.actor_id] = target
                # Calls that queued on the pending pre-registration follow
                # the creation to its home.
                info = self._actors.pop(spec.actor_id, None)
                self._forward_record(record, target)
                if info is not None:
                    while info.queued:
                        qspec = info.queued.popleft()
                        qrec = self._tasks.get(qspec.task_id)
                        if qrec is not None and qrec.state != "cancelled":
                            self._forward_record(qrec, target)
                return
        self._register_actor(record)

    def _route_actor_task_cluster(self, record: TaskRecord):
        """Route an actor call to wherever the actor lives."""
        spec = record.spec
        parked = self._fence_parked.get(spec.actor_id)
        if parked is not None and not getattr(spec, "direct_replay",
                                              False):
            # A restart-elsewhere drain is pending for this actor:
            # queue behind the already-parked calls so per-caller order
            # survives the fence window (routing directly would let
            # this call overtake them).
            parked.append(record)
            record.state = "queued"
            return
        info = self._actors.get(spec.actor_id)
        if info is not None:
            self._route_actor_task(record)
            return
        home = self._actor_homes.get(spec.actor_id)
        if home == "dead":
            if getattr(spec, "direct_replay", False):
                # A direct-channel call parked by the fence: the old
                # incarnation may have executed it (reply lost in the
                # partition) and the restarted incarnation has no
                # replay-dedup record of it — REFUSE rather than risk a
                # double execution on the new incarnation.
                _fencing.REFUSED_REPLAY.inc()
                self._fail_task(
                    record,
                    ActorDiedError(
                        spec.name,
                        "fenced: direct-call replay bound to a dead "
                        "incarnation refused",
                    ),
                )
                return
            if (spec.actor_id in self._actor_creations
                    and self._actor_restart_budget.get(spec.actor_id, 0)
                    != 0):
                # Restart-elsewhere is in flight (kicked by the fence):
                # park the call in the actor's ordered queue; the drain
                # re-routes the whole queue FIFO once the new home
                # resolves.
                record.state = "queued"
                q = self._fence_parked.setdefault(spec.actor_id, [])
                q.append(record)
                if len(q) == 1:
                    self._spawn_bg(
                        self._drain_fence_parked(spec.actor_id)
                    )
                return
            self._fail_task(
                record, ActorDiedError(spec.name, "actor's node died")
            )
            return
        if home is not None:
            self._forward_record(record, home)
            return
        if record.origin is not None or self._gcs is None:
            self._fail_task(
                record, ActorDiedError(spec.name, "actor not found")
            )
            return
        asyncio.ensure_future(self._route_actor_via_gcs(record))

    async def _drain_fence_parked(self, aid: ActorID):
        """Resolve the restarted actor's new home and re-route the
        parked queue FIFO (one drain task per actor; new calls keep
        appending to the queue until it empties, so nothing overtakes).
        The final drain is synchronous — no await between forwards —
        so a call routed right after cannot interleave."""
        deadline = time.monotonic() + self.config.object_locate_timeout_s
        while True:
            if self._shutdown:
                self._fence_parked.pop(aid, None)
                return
            if self._actors.get(aid) is not None:
                for rec in self._fence_parked.pop(aid, []):
                    if rec.state != "cancelled":
                        self._route_actor_task(rec)
                return
            home = self._actor_homes.get(aid)
            if home is not None and home != "dead":
                for rec in self._fence_parked.pop(aid, []):
                    if rec.state != "cancelled":
                        self._forward_record(rec, home)
                return
            nid = None
            if self._gcs is not None:
                try:
                    nid = await self._gcs.get_actor_node(aid)
                # Poll loop IS the handler (GCS blip -> next round).
                except Exception:  # rtlint: disable=swallowed-failure
                    nid = None
            if (nid is not None and nid != self.node_id
                    and nid.hex() not in self._fenced_nodes):
                if self._actor_homes.get(aid) in (None, "dead"):
                    self._actor_homes[aid] = nid.hex()
                continue  # drained via the home branch next iteration
            if time.monotonic() > deadline:
                for rec in self._fence_parked.pop(aid, []):
                    if rec.state != "cancelled":
                        self._fail_task(
                            rec,
                            ActorDiedError(
                                rec.spec.name,
                                "actor not found after fence restart",
                            ),
                        )
                return
            await asyncio.sleep(0.05)

    async def _route_actor_via_gcs(self, record: TaskRecord):
        """Handle deserialized on a node that has never seen this actor:
        resolve its home through the GCS actor directory, polling briefly in
        case creation is still in flight elsewhere."""
        spec = record.spec
        deadline = time.monotonic() + self.config.object_locate_timeout_s
        while True:
            try:
                nid = await self._gcs.get_actor_node(spec.actor_id)
            except Exception:
                nid = None
            if nid is not None:
                if nid == self.node_id:
                    if self._actors.get(spec.actor_id) is not None:
                        self._route_actor_task(record)
                        return
                else:
                    self._actor_homes[spec.actor_id] = nid.hex()
                    self._forward_record(record, nid.hex())
                    return
            if time.monotonic() > deadline:
                self._fail_task(
                    record, ActorDiedError(spec.name, "actor not found")
                )
                return
            await asyncio.sleep(0.05)

    async def _locate_missing(self, oid: ObjectID):
        """A dependency unknown to this node: find it through the GCS object
        directory, re-execute its creating task if we own the lineage, or
        fail the tasks waiting on it loudly."""
        found = await self._locate_via_gcs(oid)
        if found:
            return  # _locate_via_gcs sealed it; waiters have been woken.
        if await self._reconstruct_object(oid):
            return  # waiters stay parked; the re-executed task's seal wakes them
        waiters = self._dep_index.pop(oid, set())
        for tid in waiters:
            entry = self._waiting.pop(tid, None)
            if entry is None:
                continue
            rec, _missing = entry
            self._fail_task(
                rec,
                TaskError(
                    None,
                    rec.spec.name,
                    f"argument object {oid.hex()} is unknown or has been "
                    "freed; keep a live ObjectRef to it",
                ),
            )

    async def _locate_via_gcs(self, oid: ObjectID) -> bool:
        if self._gcs is None or not self._multi_node:
            return False
        try:
            nid = await self._gcs.locate_object(
                oid, timeout=self.config.object_locate_timeout_s
            )
        except Exception:
            return False
        if nid is None or nid == self.node_id:
            return False
        self._seal_object(oid, RemoteLocation(nid.hex(), 0))
        # Any entry for a remotely-owned object is a borrow this node
        # must register with the owner (owner already resolved — pass it
        # through instead of repeating the locate RPC). The holder's +1
        # delta lands BEFORE the blocking lookup that triggered this
        # (runtimes flush ref deltas ahead of blocking requests on the
        # same connection), so the count here is already the holder's —
        # no compensating pin (the old interim scheme's) is needed.
        self._borrow_stubs.add(oid)
        await self._register_borrow(oid, owner_hex=nid.hex())
        return True

    def _infeasible_may_wait(self, record: TaskRecord) -> bool:
        """Whether a cluster-wide-infeasible task should stay queued
        (``infeasible_grace_s`` window) so an autoscaler can provision a
        fitting node, instead of failing fast. Schedules a re-check at
        grace expiry so the eventual failure does not need an event."""
        grace = self.config.infeasible_grace_s
        if grace <= 0:
            return False
        now = time.monotonic()
        if record.infeasible_since is None:
            record.infeasible_since = now
            try:
                loop = asyncio.get_event_loop()
                loop.call_later(grace + 0.05, self._schedule)
            except Exception:
                pass
            return True
        return (now - record.infeasible_since) < grace

    def _sched_class(self, record: TaskRecord) -> Tuple:
        """Scheduling-class key (ref analogue: SchedulingClassDescriptor —
        task_spec.h GetSchedulingClass): tasks with the same resource
        shape, strategy, and worker type hit identical capacity walls, so
        one representative's failure defers the whole class this pass."""
        if record.sched_class is None:
            spec = record.spec
            strat = getattr(spec, "scheduling_strategy", None)
            if isinstance(strat, PlacementGroupSchedulingStrategy):
                skey = ("pg", strat.pg_id, getattr(strat, "bundle_index", -1))
            elif strat is None or isinstance(strat, str):
                skey = ("s", strat)
            else:
                # Unknown strategy object: never group (unique per record).
                skey = ("u", id(record))
            record.sched_class = (
                skey,
                tuple(sorted(spec.resources.to_dict().items())),
                _task_worker_type(spec),
                # Forwarded records route differently from locally-owned
                # ones — never let one block the other's class.
                record.origin is None,
            )
        return record.sched_class

    def _schedule(self):
        """Request a dispatch pass. Debounced: any number of triggers in
        one loop iteration (a burst of submits or completions) coalesce
        into ONE pass on the next callback slot."""
        if self._sched_pending or self._shutdown:
            return
        self._sched_pending = True
        self._loop.call_soon(self._schedule_pass)

    def _schedule_pass(self):
        """Dispatch ready tasks to idle workers while resources allow
        (ref analogue: LocalTaskManager::DispatchScheduledTasksToWorkers).
        Visits each scheduling class once, dispatching from its head until
        the class hits a capacity wall — a deep homogeneous queue costs
        O(#classes + #dispatched), not O(#queued)."""
        self._sched_pending = False
        if self._shutdown:
            return
        spawn_needed: Set[str] = set()
        if self._multi_node:
            self._cluster_view[self.node_id.hex()] = self._local_view()
        ready = self._ready
        for cls in list(ready.classes.keys()):
            while True:
                q = ready.classes.get(cls)
                if q is None:
                    break  # class drained (deque deleted by remove_head)
                record = q[0]
                if self._dispatch_record(record, spawn_needed):
                    ready.remove_head(cls)
                else:
                    break  # head blocked on capacity: skip rest of class
        for wtype in spawn_needed:
            self._maybe_spawn_worker(wtype)

    def _dispatch_record(self, record: TaskRecord,
                         spawn_needed: Set[str]) -> bool:
        """Try to place one ready record. True = record consumed (it was
        dispatched, forwarded, failed, or re-queued elsewhere) — remove it
        from its class queue; False = blocked on capacity, leave it at the
        head and skip the rest of its class this pass."""
        if record.state == "cancelled":
            return True
        spec = record.spec
        raw_strategy = getattr(spec, "scheduling_strategy", None)
        if isinstance(raw_strategy, PlacementGroupSchedulingStrategy):
            # Placement-group routing: the bundle map decides the node;
            # resources come from the bundle reservation.
            targets = self._pg_targets(raw_strategy)
            if targets is None:
                record.state = "pg_resolving"
                self._queue_pg_resolve(record)
                return True
            if not targets:
                self._fail_task(
                    record,
                    TaskError(
                        None, spec.name,
                        "placement group bundle index out of range",
                    ),
                )
                return True
            if self.node_id.hex() not in targets:
                if record.spillbacks >= self.config.max_task_spillback:
                    # Routing cache may be stale (group re-placed after a
                    # node death): drop it and re-resolve via the GCS
                    # instead of spinning forward/requeue (advisor r1).
                    self._pg_nodes.pop(raw_strategy.pg_id, None)
                    record.state = "pg_resolving"
                    self._queue_pg_resolve(record)
                    return True
                if record.origin is None:
                    self._forward_record(record, targets[0])
                    return True
                return False
            if self._find_local_bundle(raw_strategy, spec.resources) is None:
                reason = self._pg_unservable(raw_strategy, spec.resources)
                if reason is not None:
                    self._fail_task(
                        record, TaskError(None, spec.name, reason)
                    )
                    return True
                return False  # bundle busy, wait
        else:
            strategy = raw_strategy or "DEFAULT"
            if (
                record.origin is None
                and self._multi_node
                and record.spillbacks < self.config.max_task_spillback
                and (
                    strategy != "DEFAULT"
                    or not self.node_resources.can_fit(spec.resources)
                )
            ):
                target = pick_node(
                    spec.resources,
                    strategy,
                    self.node_id.hex(),
                    list(self._cluster_view.values()),
                    spread_threshold=self.config.scheduler_spread_threshold,
                )
                if target is None:
                    if self._infeasible_may_wait(record):
                        return False
                    self._fail_task(
                        record,
                        TaskError(
                            None,
                            spec.name,
                            f"infeasible resource request "
                            f"{spec.resources.to_dict()} on every node in "
                            f"the cluster",
                        ),
                    )
                    return True
                if target != self.node_id.hex():
                    self._forward_record(record, target)
                    return True
            if not self.node_resources.can_fit(spec.resources):
                if not self.node_resources.is_feasible(spec.resources):
                    if self._infeasible_may_wait(record):
                        return False
                    self._fail_task(
                        record,
                        TaskError(
                            None,
                            spec.name,
                            f"infeasible resource request "
                            f"{spec.resources.to_dict()} on node with "
                            f"{self.node_resources.total.to_dict()}",
                        ),
                    )
                    return True
                # Node full: ride an existing same-shape hold instead of
                # blocking — this is what keeps a saturated node streaming
                # batches of small tasks through its workers.
                rider = self._pipeline_candidate(
                    _task_worker_type(spec), spec
                )
                if rider is not None:
                    return self._dispatch_as_rider(record, rider)
                return False
        wtype = _task_worker_type(spec)
        worker = self._take_idle_worker(wtype)
        if worker is None:
            # Prefer a NEW worker while the pool can still grow (pipelining
            # onto a busy worker would serialize tasks with CPUs free);
            # ride a busy worker's hold only once the pool is saturated.
            if not self._can_grow_pool(wtype):
                rider = self._pipeline_candidate(wtype, spec)
                if rider is not None:
                    return self._dispatch_as_rider(record, rider)
            spawn_needed.add(wtype)
            return False
        if not self._acquire_for_record(record):
            # Lost the race (bundle drained between check and acquire).
            self._idle[worker.worker_type].appendleft(worker.worker_id)
            return False
        record.resources_held = True
        record.state = "running"
        record.worker_id = worker.worker_id
        record.dispatched = time.monotonic()
        record.hang_warned = False  # fresh run: the detector re-arms
        worker.state = "busy"
        worker.current = record
        self._send_execute_to(worker, spec)
        return True

    def _can_grow_pool(self, wtype: str) -> bool:
        """Whether another worker process could still be added and used
        (mirrors _maybe_spawn_worker's bound: dispatchable slots = CPUs
        plus blocked workers, capped by max_workers)."""
        if len(self._workers) + self._num_starting() >= self.config.max_workers:
            return False
        cpu_total = max(1, int(self.node_resources.total.get(CPU)))
        n_blocked = sum(
            1 for w in self._workers.values() if w.state == "blocked"
        )
        active = sum(
            1 for w in self._workers.values() if w.state != "dead"
        )
        return active + self._num_starting() < cpu_total + n_blocked

    def _pipeline_candidate(
        self, wtype: str, spec: TaskSpec
    ) -> Optional[WorkerHandle]:
        """A busy (non-actor, non-blocked) worker whose CURRENT task holds
        the same resource shape: the next task rides that worker's
        existing resource hold ("lease") and its socket buffer — no
        per-task acquire/release, no dispatch round-trip (ref analogue:
        direct_task_transport.cc OnWorkerIdle reusing the leased worker
        for queued tasks of the same scheduling class)."""
        depth = self.config.worker_pipeline_depth
        if depth <= 1 or spec.task_type != TaskType.NORMAL_TASK:
            return None
        if isinstance(
            getattr(spec, "scheduling_strategy", None),
            PlacementGroupSchedulingStrategy,
        ):
            # PG tasks must go through bundle acquisition — a rider would
            # bypass the bundle's reservation and break PG isolation.
            return None
        shape = spec.resources.to_dict()
        best = None
        for w in self._workers.values():
            if (
                w.state == "busy"
                and w.worker_type == wtype
                and w.actor_id is None
                and w.current is not None
                and w.current.bundle_key is None
                and w.current.spec.task_type == TaskType.NORMAL_TASK
                and len(w.pending) < depth - 1
                and w.current.spec.resources.to_dict() == shape
            ):
                if best is None or len(w.pending) < len(best.pending):
                    best = w
        return best

    def _dispatch_as_rider(
        self, record: TaskRecord, worker: WorkerHandle
    ) -> bool:
        """Queue a record onto a busy worker under that worker's existing
        resource hold. Riders never hold resources themselves; the hold
        is transferred head-to-head as tasks complete (_on_task_done)."""
        record.resources_held = False
        record.state = "running"
        record.worker_id = worker.worker_id
        record.dispatched = time.monotonic()
        record.hang_warned = False  # fresh run: the detector re-arms
        worker.pending.append(record)
        self._send_execute_to(worker, record.spec)
        return True

    def _take_idle_worker(self, worker_type: str = "cpu") -> Optional[WorkerHandle]:
        pool = self._idle[worker_type]
        while pool:
            wid = pool.popleft()
            w = self._workers.get(wid)
            if w is not None and w.state == "idle":
                return w
        return None

    def _num_starting(self) -> int:
        return sum(self._starting_workers.values())

    def _maybe_spawn_worker(self, worker_type: str = "cpu"):
        """Spawn workers demand-driven but bounded by schedulable slots:
        more worker processes than CPU slots can dispatch is pure thrash
        (ref analogue: worker_pool.h PopWorker-triggered starts bounded by
        maximum_startup_concurrency)."""
        demand = self._ready.count_worker_type(worker_type)
        if demand == 0:
            return
        capacity = len(self._workers) + self._num_starting()
        if capacity >= self.config.max_workers:
            return
        cpu_total = max(1, int(self.node_resources.total.get(CPU)))
        n_blocked = sum(1 for w in self._workers.values() if w.state == "blocked")
        # Blocked workers released their CPU, so extra tasks may run.
        want = min(demand, cpu_total + n_blocked)
        n_idle = len(self._idle[worker_type])
        usable = n_idle + self._starting_workers[worker_type]
        if usable < want:
            self._spawn_worker(worker_type)

    async def _send_execute(self, worker: WorkerHandle, spec: TaskSpec):
        blob = None
        if spec.function_id not in worker.known_functions:
            blob = await self._function_blob(spec.function_id)
            worker.known_functions.add(spec.function_id)
        try:
            await worker.writer.send(
                {"type": "execute", "spec": spec, "function_blob": blob}
            )
        except Exception:
            await self._on_worker_death(worker)

    def _send_execute_to(self, worker: WorkerHandle, spec: TaskSpec):
        """Ship one execute frame, preserving per-worker frame order: the
        synchronous fast path only runs while no async send (blob fetch)
        is still in flight, else a later frame could overtake it. Fast
        frames are coalesced per loop iteration and flushed as ONE
        socket write per worker (_flush_execute_bufs)."""
        if (
            spec.function_id in worker.known_functions
            and worker.slow_sends == 0
        ):
            if not worker.exec_buf and not self._exec_dirty:
                self._loop.call_soon(self._flush_execute_bufs)
            if not worker.exec_buf:
                self._exec_dirty.append(worker)
            worker.exec_buf.append(
                {"spec": spec, "function_blob": None}
            )
            return
        # Slow path (blob fetch): flush this worker's buffered fast
        # frames NOW so the async frame cannot overtake them.
        self._flush_worker_exec_buf(worker)

        async def _ordered():
            # The lock is taken before the first await inside, and tasks
            # start in ensure_future order, so frames go out in submission
            # order even when blob fetches finish out of order.
            async with worker.send_lock:
                try:
                    await self._send_execute(worker, spec)
                finally:
                    worker.slow_sends -= 1

        worker.slow_sends += 1
        asyncio.ensure_future(_ordered())

    def _flush_worker_exec_buf(self, worker: WorkerHandle):
        buf = worker.exec_buf
        if not buf:
            return
        worker.exec_buf = []
        msg = (
            {"type": "execute", **buf[0]}
            if len(buf) == 1
            else {"type": "execute_batch", "items": buf}
        )
        try:
            worker.writer.send_nowait(msg)
        except Exception:
            asyncio.ensure_future(self._on_worker_death(worker))

    def _flush_execute_bufs(self):
        dirty = self._exec_dirty
        self._exec_dirty = []
        for worker in dirty:
            self._flush_worker_exec_buf(worker)

    def _advance_worker_pipeline(
        self, w: WorkerHandle, task_id: TaskID,
        record: Optional[TaskRecord],
    ):
        """Advance current/pending past a completed non-actor task and
        move the resource hold: the worker's chain rides ONE hold, passed
        head-to-head so completion costs no release/acquire round trip
        (ref analogue: direct_task_transport.cc worker-lease reuse)."""
        if w.current is not None and w.current.spec.task_id == task_id:
            fin = w.current
            nxt = w.pending.popleft() if w.pending else None
            if (
                fin.resources_held
                and nxt is not None
                and not nxt.resources_held
            ):
                fin.resources_held = False
                nxt.resources_held = True
                nxt.bundle_key = fin.bundle_key
            else:
                self._release_task_resources(fin)
            w.current = nxt
        elif record is not None:
            # Out-of-order completion (reclaim/cancel races): drop by
            # identity; riders hold nothing so release is a no-op.
            self._release_task_resources(record)
            try:
                w.pending.remove(record)
            except ValueError:
                w.current = None
        else:
            for r in list(w.pending):
                if r.spec.task_id == task_id:
                    self._release_task_resources(r)
                    w.pending.remove(r)
                    break
        if w.current is None and w.state != "dead":
            w.state = "idle"
            self._idle[w.worker_type].append(w.worker_id)

    async def _on_task_done(self, w: WorkerHandle, msg: Dict[str, Any]):
        task_id: TaskID = msg["task_id"]
        record = self._tasks.get(task_id)
        results: List[Tuple[ObjectID, Location]] = msg["results"]
        # Apply the worker's ref deltas FIRST — even for a record already
        # dropped by cancellation/failure: drain() removed them from the
        # worker's table, so this frame is their only carrier; dropping
        # them would desynchronize counts permanently.
        deltas = msg.get("ref_deltas")
        if deltas:
            await self._apply_ref_deltas(deltas)
        if record is None:
            # Cancelled/failed while the done frame was in flight: the
            # seals already happened (_fail_task), but the worker's
            # pipeline bookkeeping must still advance or its hold leaks.
            if w.actor_id is None:
                self._advance_worker_pipeline(w, task_id, None)
                self._schedule()
            return
        for oid, loc in results:
            self._seal_object(oid, loc)
        # Returns' contained refs BEFORE dropping the task's pins /
        # notifying the origin: a ref returned inside a container must be
        # pinned — and any cross-node borrow registered with its owner —
        # while the submission-time pin still protects the object.
        for roid, nested in (msg.get("nested") or ()):
            self._register_nested(roid, nested)
        # A "duplicate" completion is an NM-path replay of a direct call
        # the worker already executed (and already reported through its
        # direct_done_batch notification): the record still finishes,
        # but stats/duration/history were counted once already.
        duplicate = bool(msg.get("duplicate"))
        if msg.get("failed"):
            if not duplicate:
                self._stats["tasks_failed"] += 1
            record.state = "failed"
        else:
            if not duplicate:
                self._stats["tasks_finished"] += 1
            record.state = "finished"
        if record.dispatched is not None and not duplicate:
            self._observe_task_duration(
                time.monotonic() - record.dispatched
            )
        if record.origin is not None:
            self._notify_origin(record, failed=bool(msg.get("failed")))
        # Creation-task deps stay pinned while the actor may restart (the
        # creation spec re-executes with the same arguments). Terminal
        # normal/actor-task records are dropped to keep the head's memory
        # bounded (the spec holds serialized args) — their outcome is
        # retained in the bounded failure history instead.
        if record.spec.task_type != TaskType.ACTOR_CREATION_TASK:
            self._unpin_deps(record)
            if not duplicate:
                self._record_terminal_task(
                    record,
                    error_type=msg.get("error_type"),
                    error_message=msg.get("error_message"),
                    resource_usage=msg.get("resource_usage"),
                )
            self._tasks.pop(task_id, None)
        elif msg.get("failed"):
            self._unpin_deps(record)
        if w.actor_id is not None:
            info = self._actors.get(w.actor_id)
            if info is not None:
                info.inflight.pop(task_id, None)
                if record.spec.task_type == TaskType.ACTOR_CREATION_TASK:
                    if msg.get("failed"):
                        info.state = "dead"
                        info.death_cause = "actor constructor failed"
                        info.restarts_left = 0
                        cluster_events.emit(
                            cluster_events.ERROR, cluster_events.ACTOR,
                            f"actor {info.actor_id.hex()[:8]} "
                            f"({record.spec.class_name}) constructor "
                            f"failed: "
                            f"{msg.get('error_type') or 'Exception'}",
                            node_id=self.node_id.hex(),
                            actor_id=info.actor_id.hex(),
                            custom_fields={
                                "error_type": msg.get("error_type"),
                                "cause": "constructor failed",
                            },
                        )
                        self._fail_actor_queue(info)
                        if info.name:
                            self._named_actors.pop(info.name, None)
                        await self.kill_actor(info.actor_id)
                    else:
                        info.state = "alive"
                        self._flush_actor_queue(info)
        else:
            self._advance_worker_pipeline(w, task_id, record)
        self._schedule()

    async def _on_direct_done_batch(self, w: WorkerHandle, msg):
        """Completion notifications for calls executed over the direct
        actor-call plane (the worker already replied to the caller
        inline): the NM-side _on_task_done bookkeeping still fires here
        — ref deltas, seals for third-party consumers, holds for remote
        callers' RemoteLocation entries, duration telemetry and the
        terminal task history — one debounced batch frame per burst
        (see worker_main._note_direct_done)."""
        items = msg.get("items", ())
        self._stats["direct_done_batches"] += 1
        self._stats["direct_calls_done"] += len(items)
        for item in items:
            deltas = item.get("ref_deltas")
            if deltas:
                await self._apply_ref_deltas(deltas)
            held = item.get("held")
            for oid, loc in item["results"]:
                self._seal_object(oid, loc)
                if held and not isinstance(loc, InlineLocation):
                    # The caller's node sealed a held RemoteLocation for
                    # this result; keep our copy until it frees it.
                    self.directory.add_ref(oid)
            dur = item.get("duration_s")
            if dur is not None:
                self._observe_task_duration(dur)
            if item.get("failed"):
                self._stats["tasks_failed"] += 1
            else:
                self._stats["tasks_finished"] += 1
            self._task_history.append({
                "task_id": item["task_id"].hex(),
                "name": item.get("name") or "task",
                "state": "failed" if item.get("failed") else "finished",
                "type": "ACTOR_TASK",
                "via": "direct",
                "node_id": self.node_id.hex(),
                "actor_id": item.get("actor_id"),
                "duration_s": round(dur, 6) if dur is not None else None,
                "error_type": item.get("error_type"),
                "error_message": (item.get("error_message") or "")[:500]
                                 or None,
                "cpu_time_s": None,
                "max_rss_bytes": None,
                "retry_count": 0,
                "retries_left": 0,
                "end_ts": time.time(),
                "retained": True,
            })
        self._schedule()

    def _seal_object(self, oid: ObjectID, loc: Location):
        existing = self.directory.lookup(oid)
        if existing is not None and oid in self._sealed:
            return
        if existing is None:
            self.directory.add(oid, loc, initial_refs=0)
        else:
            self.directory.seal_over_placeholder(oid, loc)
        self._sealed.add(oid)
        self._maybe_spill()
        ev = self._seal_events.pop(oid, None)
        if ev is not None:
            ev.set()
        waiters = self._dep_index.pop(oid, None)
        if waiters:
            for tid in waiters:
                entry = self._waiting.get(tid)
                if entry is None:
                    continue
                rec, missing = entry
                missing.discard(oid)
                if not missing:
                    del self._waiting[tid]
                    self._task_ready(rec)
        if self._gcs is not None and (self._multi_node or not self.is_head) \
                and not isinstance(loc, RemoteLocation):
            asyncio.ensure_future(self._publish_seal(oid))

    async def _publish_seal(self, oid: ObjectID):
        try:
            await self._gcs.publish_object(oid, self.node_id)
        except Exception:
            pass

    def _unpin_deps(self, record: TaskRecord):
        if record.deps_unpinned:
            return
        record.deps_unpinned = True
        for oid in record.spec.pinned_ids():
            self.directory.remove_ref(oid)

    def _record_terminal_task(self, record: TaskRecord, *,
                              error_type: Optional[str] = None,
                              error_message: Optional[str] = None,
                              resource_usage: Optional[Dict[str, Any]]
                              = None):
        """Retain a terminal task's outcome in the bounded failure
        history (it is about to leave the live table)."""
        spec = record.spec
        dur = (time.monotonic() - record.dispatched
               if record.dispatched is not None else None)
        usage = resource_usage or {}
        self._task_history.append({
            # Worker-side resource sampler deltas (util/profiler
            # TaskResourceSampler): CPU seconds burned and peak RSS.
            "cpu_time_s": usage.get("cpu_s"),
            "max_rss_bytes": usage.get("max_rss_bytes"),
            "task_id": spec.task_id.hex(),
            "name": spec.name or spec.method_name or "task",
            "state": record.state,
            "type": spec.task_type.name,
            "node_id": self.node_id.hex(),
            "actor_id": spec.actor_id.hex() if spec.actor_id else None,
            "duration_s": round(dur, 6) if dur is not None else None,
            "error_type": error_type,
            "error_message": (error_message or "")[:500] or None,
            # retries_left counts DOWN from max_retries as crashes retry:
            # together they answer "did this task exhaust its retries?".
            "retry_count": spec.max_retries - spec.retries_left,
            "retries_left": spec.retries_left,
            "end_ts": time.time(),
            "retained": True,
        })

    def _fail_task(self, record: TaskRecord, error: TaskError):
        cancelled = isinstance(error, TaskCancelledError)
        record.state = "cancelled" if cancelled else "failed"
        self._stats["tasks_failed"] += 1
        self._unpin_deps(record)
        etype = type(error).__name__
        detail = (getattr(error, "traceback_str", "") or str(error)).strip()
        last_line = detail.splitlines()[-1] if detail else ""
        if record.spec.task_type != TaskType.ACTOR_CREATION_TASK:
            self._record_terminal_task(
                record, error_type=etype, error_message=detail
            )
            self._tasks.pop(record.spec.task_id, None)
        if not cancelled:
            # System-level failures (worker crash, actor death, node
            # loss): there is no worker alive to report the traceback, so
            # the control plane records the ERROR event itself.
            cluster_events.emit(
                cluster_events.ERROR, cluster_events.TASK,
                f"task '{record.spec.name or record.spec.method_name}' "
                f"failed: {etype}: {last_line}",
                node_id=self.node_id.hex(),
                task_id=record.spec.task_id.hex(),
                actor_id=(record.spec.actor_id.hex()
                          if record.spec.actor_id else None),
                custom_fields={"error_type": etype},
            )
        try:
            from .serialization import serialize

            blob = serialize(error).to_bytes()
        except Exception:
            from .serialization import serialize

            blob = serialize(
                TaskError(None, record.spec.name, "unserializable failure")
            ).to_bytes()
        for oid in record.spec.return_ids():
            self._seal_object(oid, InlineLocation(blob))
        if record.origin is not None:
            self._notify_origin(record, failed=True)

    # ------------------------------------------------------------------ actors

    def _pre_register_actor(self, spec: TaskSpec):
        if spec.actor_id in self._actors:
            return
        self._actors[spec.actor_id] = ActorInfo(
            actor_id=spec.actor_id,
            creation_spec=spec,
            restarts_left=spec.max_restarts,
            name=spec.name,
        )

    def _register_actor(self, record: TaskRecord):
        spec = record.spec
        info = self._actors.get(spec.actor_id)
        if info is None:
            info = ActorInfo(
                actor_id=spec.actor_id,
                creation_spec=spec,
                restarts_left=spec.max_restarts,
                name=spec.name,
            )
            self._actors[spec.actor_id] = info
        # Home + incarnation registration happens inside _place_actor
        # (the GCS assigns the incarnation the creation spec carries to
        # the worker — registering here too would mint a second one).
        asyncio.ensure_future(self._place_actor(info, record))

    async def _claim_actor_name(self, spec: TaskSpec) -> bool:
        """Atomically claim a named-actor slot (ref analogue: the name
        registry in GcsActorManager::HandleRegisterActor)."""
        if self._gcs is not None:
            try:
                return await self._gcs.register_named_actor(
                    spec.name, spec.actor_id, self.node_id, spec
                )
            except Exception:
                return False
        existing = self._named_actors.get(spec.name)
        if existing is not None:
            return existing == spec.actor_id
        self._named_actors[spec.name] = spec.actor_id
        return True

    async def _place_actor(self, info: ActorInfo, record: TaskRecord):
        spec = info.creation_spec
        # Every start/restart gets a GCS-assigned incarnation (the same
        # call records this node as the actor's home). The creation
        # spec carries it to the worker, which refuses direct hellos
        # naming any other incarnation — the fencing half of the direct
        # plane's stale-endpoint discipline.
        if self._gcs is not None:
            try:
                info.incarnation = await self._gcs.register_actor_node(
                    spec.actor_id, self.node_id
                )
            except Exception as e:  # noqa: BLE001
                # GCS unreachable mid-placement: fall back to a local
                # bump so restarts still move forward; the reconnect
                # republish ratchets the GCS counter up to ours.
                info.incarnation = max(1, info.incarnation + 1)
                sys.stderr.write(
                    f"[ray_tpu] actor {spec.actor_id.hex()[:8]} "
                    f"incarnation assignment via GCS failed ({e!r}); "
                    f"using local {info.incarnation}\n"
                )
        else:
            info.incarnation = max(1, info.incarnation + 1)
        spec.actor_incarnation = info.incarnation
        if spec.name:
            if not await self._claim_actor_name(spec):
                self._fail_task(
                    record,
                    TaskError(None, spec.name, f"actor name {spec.name!r} taken"),
                )
                info.state = "dead"
                info.death_cause = "name taken"
                return
            self._named_actors[spec.name] = spec.actor_id
        if not self.node_resources.is_feasible(spec.resources):
            self._fail_task(
                record,
                TaskError(
                    None, spec.name, f"infeasible actor resources "
                    f"{spec.resources.to_dict()}"
                ),
            )
            info.state = "dead"
            return
        wtype = _task_worker_type(spec)
        # Atomically acquire resources (acquire() both checks and takes, so
        # two concurrently-placing actors can't share an exclusive resource),
        # then wait for a worker without blocking the loop. PG-scheduled
        # actors draw from their bundle reservation instead of the pool.
        while not self._acquire_for_record(record):
            strategy = spec.scheduling_strategy
            if isinstance(strategy, PlacementGroupSchedulingStrategy):
                reason = self._pg_unservable(strategy, spec.resources)
                if reason is not None:
                    self._fail_task(record, TaskError(None, spec.name, reason))
                    info.state = "dead"
                    info.death_cause = reason
                    self._fail_actor_queue(info, reason)
                    return
            await asyncio.sleep(0.01)
            if self._shutdown:
                return
        worker = self._take_idle_worker(wtype)
        while worker is None:
            self._maybe_spawn_worker_for_actor(wtype)
            await asyncio.sleep(0.01)
            if self._shutdown:
                record.resources_held = True
                self._release_task_resources(record)
                return
            worker = self._take_idle_worker(wtype)
        worker.state = "actor"
        worker.actor_id = spec.actor_id
        info.worker_id = worker.worker_id
        record.state = "running"
        record.worker_id = worker.worker_id
        record.resources_held = True
        info.inflight[spec.task_id] = record
        self._stats["actors_created"] += 1
        # The actor transitions to "alive" (or "dead") in _on_task_done when
        # the creation task reports back.
        await self._send_execute(worker, spec)

    def _maybe_spawn_worker_for_actor(self, worker_type: str = "cpu"):
        capacity = len(self._workers) + self._num_starting()
        if capacity < self.config.max_workers and not self._idle[worker_type] \
                and self._starting_workers[worker_type] == 0:
            self._spawn_worker(worker_type)

    def _route_actor_task(self, record: TaskRecord):
        spec = record.spec
        info = self._actors.get(spec.actor_id)
        if info is None or info.state == "dead":
            cause = info.death_cause if info else "actor not found"
            self._fail_task(record, ActorDiedError(spec.name, cause))
            return
        if info.state in ("pending", "restarting"):
            if getattr(spec, "direct_replay", False):
                # A direct-channel call interrupted by the actor's death:
                # fails like NM-routed in-flight calls do on restart —
                # replaying it into the restarted actor would re-execute
                # an interrupted (possibly non-idempotent) method.
                self._fail_task(
                    record,
                    ActorDiedError(
                        spec.name,
                        "actor restarting (interrupted direct call)",
                    ),
                )
                return
            info.queued.append(spec)
            record.state = "queued"
            return
        if (getattr(spec, "direct_replay", False)
                and spec.actor_incarnation
                and info.incarnation
                and spec.actor_incarnation != info.incarnation):
            # Replay bound to an EARLIER incarnation of a now-alive
            # actor (restarted before the replay landed): the new
            # incarnation's replay-dedup cache knows nothing of the old
            # channel's calls — refuse instead of double-executing.
            _fencing.REFUSED_REPLAY.inc()
            self._fail_task(
                record,
                ActorDiedError(
                    spec.name,
                    f"fenced: replay bound to incarnation "
                    f"{spec.actor_incarnation}, actor is now "
                    f"incarnation {info.incarnation}",
                ),
            )
            return
        self._forward_actor_task(info, record)

    def _forward_actor_task(self, info: ActorInfo, record: TaskRecord):
        worker = self._workers.get(info.worker_id)
        if worker is None:
            info.queued.append(record.spec)
            return
        record.state = "running"
        record.worker_id = worker.worker_id
        info.inflight[record.spec.task_id] = record
        self._send_execute_to(worker, record.spec)

    def _flush_actor_queue(self, info: ActorInfo):
        while info.queued:
            spec = info.queued.popleft()
            record = self._tasks.get(spec.task_id)
            if record is None or record.state == "cancelled":
                continue
            self._forward_actor_task(info, record)

    def _fail_actor_queue(self, info: ActorInfo, cause: str = "actor died"):
        for spec in info.queued:
            rec = self._tasks.get(spec.task_id)
            if rec is not None:
                self._fail_task(rec, ActorDiedError(spec.name, cause))
        info.queued.clear()

    async def _on_actor_worker_death(self, w: WorkerHandle):
        info = self._actors.get(w.actor_id)
        if info is None:
            return
        creation_record = self._tasks.get(info.creation_spec.task_id)
        if creation_record is not None:
            self._release_task_resources(creation_record)
        graceful = getattr(w, "_graceful_exit", False)
        cause = "graceful exit" if graceful else "actor worker process died"
        inflight = list(info.inflight.values())
        info.inflight.clear()
        # A creation task that never reported back counts as failed.
        creation_pending = any(
            rec.spec.task_type == TaskType.ACTOR_CREATION_TASK for rec in inflight
        )
        if info.state == "dead":
            return
        # Old worker's direct endpoints are gone either way; callers'
        # channels die with the sockets and re-resolve after restart.
        info.direct_path = None
        info.direct_addr = None
        if not graceful and info.restarts_left != 0 and not self._shutdown:
            info.state = "restarting"
            if info.restarts_left > 0:
                info.restarts_left -= 1
            info.restart_count += 1
            cluster_events.emit(
                cluster_events.WARNING, cluster_events.ACTOR,
                f"actor {info.actor_id.hex()[:8]} "
                f"({info.creation_spec.class_name}) restarting "
                f"after worker death (restart #{info.restart_count}, "
                f"{info.restarts_left} left)",
                node_id=self.node_id.hex(),
                actor_id=info.actor_id.hex(),
                custom_fields={"class_name": info.creation_spec.class_name,
                               "restart_count": info.restart_count},
            )
            # Actor tasks are NOT retried by default (ref: max_task_retries=0
            # in the reference); interrupted calls fail with ActorDiedError
            # unless they carry retries, in which case they resubmit in order.
            for rec in reversed(inflight):
                if rec.spec.task_type != TaskType.ACTOR_TASK:
                    continue
                if rec.spec.retries_left > 0:
                    rec.spec.retries_left -= 1
                    info.queued.appendleft(rec.spec)
                else:
                    self._fail_task(
                        rec, ActorDiedError(rec.spec.name, "actor restarting")
                    )
            new_record = TaskRecord(spec=info.creation_spec)
            asyncio.ensure_future(self._restart_actor(info, new_record))
        else:
            info.state = "dead"
            info.death_cause = cause
            intentional = graceful or getattr(w, "_intentional_kill", False)
            cluster_events.emit(
                cluster_events.INFO if intentional else cluster_events.ERROR,
                cluster_events.ACTOR,
                f"actor {info.actor_id.hex()[:8]} "
                f"({info.creation_spec.class_name}) died: "
                + ("killed via ray_tpu.kill" if intentional and not graceful
                   else cause),
                node_id=self.node_id.hex(),
                actor_id=info.actor_id.hex(),
                custom_fields={"class_name": info.creation_spec.class_name,
                               "cause": cause,
                               "restart_count": info.restart_count},
            )
            if creation_pending and creation_record is not None:
                self._fail_task(
                    creation_record, ActorDiedError(info.creation_spec.name, cause)
                )
            for rec in inflight:
                if rec.spec.task_type == TaskType.ACTOR_TASK:
                    self._fail_task(rec, ActorDiedError(rec.spec.name, cause))
            self._fail_actor_queue(info, cause)
            if creation_record is not None:
                self._unpin_deps(creation_record)
            if info.name:
                self._named_actors.pop(info.name, None)
                if self._gcs is not None:
                    self._spawn_bg(
                        self._gcs.drop_named_actor(info.name, info.actor_id)
                    )

    async def _restart_actor(self, info: ActorInfo, record: TaskRecord):
        # Re-run the creation task on a fresh worker (ref analogue:
        # GcsActorManager::RestartActor).
        spec = info.creation_spec
        self._tasks[spec.task_id] = record
        ev = self._seal_events.get(spec.return_ids()[0])
        if ev is not None:
            ev.clear()
        self._sealed.discard(spec.return_ids()[0])
        await self._place_actor(info, record)

    async def _on_actor_graceful_exit(self, w: WorkerHandle, msg):
        w._graceful_exit = True

    async def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        if no_restart:
            # An intentional permanent kill also retires the owner-side
            # restart-elsewhere pin (no fence may resurrect it).
            self._actor_creations.pop(actor_id, None)
            self._actor_restart_budget.pop(actor_id, None)
        info = self._actors.get(actor_id)
        if info is None:
            home = self._actor_homes.get(actor_id)
            if home and home != "dead":
                try:
                    peer = await self._get_peer(home)
                    await peer.notify(
                        {
                            "type": "kill_actor_peer",
                            "actor_id": actor_id,
                            "no_restart": no_restart,
                        }
                    )
                except Exception:
                    pass
            return
        if no_restart:
            info.restarts_left = 0
        worker = self._workers.get(info.worker_id) if info.worker_id else None
        if worker is not None:
            try:
                await worker.writer.send({"type": "kill"})
            except Exception:
                pass
            if worker.proc is not None:
                worker._intentional_kill = True
                try:
                    worker.proc.kill()
                except Exception:
                    pass

    async def get_named_actor(self, name: str) -> Optional[TaskSpec]:
        if self._gcs is not None:
            entry = await self._gcs.get_named_actor(name)
            if entry is None:
                return None
            actor_id, node_id, spec = entry
            if node_id != self.node_id and actor_id not in self._actors:
                self._actor_homes.setdefault(actor_id, node_id.hex())
            return spec
        actor_id = self._named_actors.get(name)
        if actor_id is None:
            return None
        return self._actors[actor_id].creation_spec

    # ---------------------------------------------------------------- objects

    async def put_object(self, object_id: ObjectID, loc: Location,
                         refs: int = 1, *, pin_if_new: bool = False,
                         nested: Optional[List[ObjectID]] = None):
        # pin_if_new: carry ``refs`` only when the directory has no entry
        # yet (streaming re-seal after a retry — a surviving original entry
        # keeps its original pin; adding more would leak it permanently).
        if pin_if_new and self.directory.lookup(object_id) is not None:
            refs = 0
        self.directory.add(object_id, loc, initial_refs=refs, owner="put")
        self._seal_object(object_id, loc)
        if nested:
            # Refs serialized inside the put value stay alive as long as
            # the containing object does (AddNestedObjectIds analogue).
            self._register_nested(object_id, nested)

    async def get_locations(
        self, object_ids: List[ObjectID], timeout: Optional[float] = None
    ) -> List[Tuple[ObjectID, Location]]:
        events = []
        for oid in object_ids:
            if oid not in self._sealed:
                if (self.directory.lookup(oid) is None
                        or oid in self._borrow_stubs):
                    # Never registered here (or only as a count-only
                    # borrow stub): try the GCS object directory
                    # (cross-node borrow), then lineage re-execution, else
                    # fail loudly — waiting would hang forever (ref analogue:
                    # OwnershipBasedObjectDirectory lookup before PullManager
                    # engages).
                    if await self._locate_via_gcs(oid):
                        continue
                    if await self._reconstruct_object(oid):
                        events.append(
                            self._seal_events.setdefault(oid, asyncio.Event())
                        )
                        continue
                    raise ObjectLostError(
                        f"object {oid.hex()} is unknown or has been freed; "
                        "if it was only referenced from inside a container "
                        "argument, keep a live ObjectRef to it"
                    )
                events.append(self._seal_events.setdefault(oid, asyncio.Event()))
                if oid in self._lineage:
                    # No-op while the creating task is in flight; re-executes
                    # it when the entry was unsealed by a node death.
                    await self._reconstruct_object(oid)
        if events:
            if any(not ev.is_set() for ev in events):
                # ONE task for the whole set (wait_for wraps the helper
                # once) instead of gather's Task per object: a deep
                # drain get() used to mint 1M asyncio Tasks here.
                # Sequential awaits are equivalent — every event must be
                # set before returning, and they fire independently of
                # the await order.
                async def _wait_all(evs=events):
                    for ev in evs:
                        if not ev.is_set():
                            await ev.wait()

                await asyncio.wait_for(_wait_all(), timeout)
        out: List[Tuple[ObjectID, Location]] = []
        for oid in object_ids:
            loc = self.directory.lookup(oid)
            if isinstance(loc, RemoteLocation):
                loc = await self._ensure_local(oid, loc)
            if isinstance(loc, SpilledLocation):
                loc = await self._restore_spilled(oid, loc)
            out.append((oid, loc))
        return out

    async def _ensure_local(self, oid: ObjectID, loc: RemoteLocation) -> Location:
        """Pull a remote object's bytes and re-home them locally, deduping
        concurrent pulls (ref analogue: PullManager bundles + the object
        buffer pool's single in-flight chunk set per object). A failed pull
        goes through object recovery (replica re-locate, then lineage
        re-execution) before surfacing ObjectLostError."""
        while True:
            fut = self._pulls.get(oid)
            if fut is None:
                fut = asyncio.ensure_future(self._pull_object(oid, loc))
                self._pulls[oid] = fut

                def _cleanup(f, oid=oid):
                    if self._pulls.get(oid) is f:
                        del self._pulls[oid]

                fut.add_done_callback(_cleanup)
            try:
                return await asyncio.shield(fut)
            except ObjectLostError:
                if not await self._recover_object(oid, exclude_hex=loc.node_id):
                    raise
                new_loc = await self._wait_recovered(oid)
                if not isinstance(new_loc, RemoteLocation):
                    return new_loc
                loc = new_loc

    # --------------------------------------------------------- object recovery

    def _can_reconstruct(self, oid: ObjectID) -> bool:
        return (
            oid in self._lineage
            and self._reconstructions.get(oid, 0)
            < self.config.max_object_reconstructions
        )

    async def _recover_object(
        self, oid: ObjectID, exclude_hex: Optional[str] = None
    ) -> bool:
        """Make a lost object readable again: prefer another live replica
        from the GCS directory, else re-execute the creating task from
        lineage (ref analogue: ObjectRecoveryManager::RecoverObject —
        PinExistingObjectCopy first, ReconstructObject second)."""
        self._sealed.discard(oid)
        if self._gcs is not None and self._multi_node:
            try:
                nid = await self._gcs.locate_object(oid, timeout=0)
            except Exception:
                nid = None
            if (
                nid is not None
                and nid != self.node_id
                and nid.hex() != exclude_hex
                and nid.hex() in self._cluster_view
            ):
                self.directory.replace_location(oid, RemoteLocation(nid.hex(), 0))
                self._seal_object(oid, RemoteLocation(nid.hex(), 0))
                return True
        return await self._reconstruct_object(oid)

    async def _reconstruct_object(self, oid: ObjectID) -> bool:
        """Re-execute the creating task of a lost object, within the
        per-object reconstruction budget."""
        if not self._can_reconstruct(oid):
            return False
        spec = self._lineage[oid]
        live = self._tasks.get(spec.task_id)
        if live is not None and live.state in (
            "waiting", "ready", "running", "forwarded", "pg_resolving"
        ):
            # The creating task is already in flight (sibling return slot
            # kicked off recovery, or a retry is running): wait for its seal.
            return True
        self._reconstructions[oid] = self._reconstructions.get(oid, 0) + 1
        self._stats["tasks_retried"] += 1
        for rid in spec.return_ids():
            self._sealed.discard(rid)
        await self.submit_task(spec)
        return True

    async def _wait_recovered(self, oid: ObjectID) -> Location:
        """Block until the recovered object (or its failure blob) seals."""
        if oid not in self._sealed:
            ev = self._seal_events.setdefault(oid, asyncio.Event())
            await ev.wait()
        return self.directory.lookup(oid)

    # ----------------------------------------------------------- spilling

    def _maybe_spill(self, need: int = 0):
        """Start one spill pass when store usage crosses the high-water
        mark, or when a caller explicitly needs ``need`` bytes freed
        regardless of the mark (pull admission below high water; ref
        analogue: LocalObjectManager::SpillObjectUptoMaxThroughput
        triggered from the eviction path)."""
        cap = self.directory.capacity_bytes
        if not self.directory.spill_enabled or self._spilling or cap <= 0:
            return
        if (
            need <= 0
            and self.directory.used_bytes
            <= cap * self.config.spill_high_water_frac
        ):
            return
        self._spilling = True
        self._spawn_bg(self._spill_pass(need))

    async def _spill_pass(self, extra_need: int = 0):
        """Move LRU local objects to disk until under the low-water mark
        (or until ``extra_need`` bytes are freed, whichever is more).
        Byte IO runs in executor threads; the directory entry swaps via
        compare-and-swap so racing reads/GC stay correct."""
        try:
            target = int(
                self.directory.capacity_bytes * self.config.spill_low_water_frac
            )
            need = max(self.directory.used_bytes - target, extra_need)
            if need <= 0:
                return
            spilled_n = spilled_bytes = 0
            for oid, loc in self.directory.spill_candidates(need):
                try:
                    data = self.local_store.get_bytes(loc)
                except Exception:
                    continue  # lost the race with GC
                try:
                    sloc = await self._loop.run_in_executor(
                        None, self.spill_manager.write, oid, data
                    )
                except Exception:
                    continue  # disk trouble: skip, keep relieving others
                if self.directory.replace_if(oid, loc, sloc):
                    _free_location(loc)
                    spilled_n += 1
                    spilled_bytes += len(data)
                else:
                    self.spill_manager.delete(sloc)
            if spilled_n:
                cluster_events.emit(
                    cluster_events.INFO, cluster_events.OBJECT_STORE,
                    f"spilled {spilled_n} object(s) "
                    f"({spilled_bytes} bytes) to disk",
                    node_id=self.node_id.hex(),
                    custom_fields={"objects": spilled_n,
                                   "bytes": spilled_bytes},
                )
        finally:
            self._spilling = False
            # Puts/restores that landed mid-pass can leave usage above the
            # mark with no future trigger — re-check so pressure can't get
            # stranded between passes. Delayed, so a pass that cannot make
            # progress (full disk, all candidates raced) does not respawn
            # itself in a tight loop.
            self._loop.call_later(0.2, self._maybe_spill)

    async def _restore_spilled(
        self, oid: ObjectID, sloc: SpilledLocation
    ) -> Location:
        """Bring a spilled object back into the store, deduping concurrent
        restores (ref analogue: the restore IO-worker path of
        LocalObjectManager + PinObjectIDs)."""
        fut = self._restores.get(oid)
        if fut is None:
            fut = asyncio.ensure_future(self._restore_io(oid, sloc))
            self._restores[oid] = fut

            def _cleanup(f, oid=oid):
                if self._restores.get(oid) is f:
                    del self._restores[oid]

            fut.add_done_callback(_cleanup)
        return await asyncio.shield(fut)

    async def _restore_io(self, oid: ObjectID, sloc: SpilledLocation) -> Location:
        data = await self._loop.run_in_executor(
            None, self.spill_manager.read, sloc
        )
        if len(data) <= self.config.max_inline_object_size:
            new_loc: Location = InlineLocation(bytes(data))
        else:
            new_loc = self.local_store.put_raw(oid, data)
        if self.directory.replace_if(oid, sloc, new_loc):
            self.spill_manager.delete(sloc)
            cluster_events.emit(
                cluster_events.DEBUG, cluster_events.OBJECT_STORE,
                f"restored object {oid.hex()[:8]} from disk "
                f"({len(data)} bytes)",
                node_id=self.node_id.hex(),
                custom_fields={"object_id": oid.hex(),
                               "bytes": len(data)},
            )
            self._maybe_spill()  # restoring may re-cross the high-water mark
            return new_loc
        cur = self.directory.lookup(oid)
        return cur if cur is not None else new_loc

    # ------------------------------------------------------ memory monitor

    async def _memory_monitor_loop(self):
        """Kill the newest retriable running task's worker under system
        memory pressure (ref: MemoryMonitor common/memory_monitor.h:52 +
        retriable-FIFO policy worker_killing_policy_retriable_fifo.h)."""
        thresh = self.config.memory_usage_threshold
        if thresh <= 0:
            return
        while not self._shutdown:
            await asyncio.sleep(self.config.memory_monitor_interval_s)
            try:
                frac = _system_memory_usage_fraction()
            except Exception:
                continue
            if frac < thresh:
                continue
            victim = self._pick_oom_victim()
            if victim is None:
                continue
            worker, record = victim
            sys.stderr.write(
                f"[ray_tpu] memory pressure ({frac:.0%}): killing task "
                f"'{record.spec.name}' (worker {worker.worker_id.hex()[:8]})\n"
            )
            cluster_events.emit(
                cluster_events.ERROR, cluster_events.RAYLET,
                f"memory pressure ({frac:.0%}): OOM-killing task "
                f"'{record.spec.name}' "
                f"(worker {worker.worker_id.hex()[:8]}, "
                f"retries_left={record.spec.retries_left})",
                node_id=self.node_id.hex(),
                task_id=record.spec.task_id.hex(),
                custom_fields={"memory_usage_frac": round(frac, 4),
                               "retriable": record.spec.retries_left > 0},
            )
            worker._oom_killed = True
            if worker.proc is not None:
                try:
                    worker.proc.kill()
                except Exception:
                    pass

    def _pick_oom_victim(self):
        """Newest running non-actor task, preferring one with retries left
        so the kill is survivable (retriable-FIFO, ref:
        worker_killing_policy_retriable_fifo.h:34)."""
        retriable, any_task = None, None
        for w in self._workers.values():
            if w.state != "busy" or w.current is None or w.actor_id is not None:
                continue
            rec = w.current
            if any_task is None or rec.created > any_task[1].created:
                any_task = (w, rec)
            if rec.spec.retries_left > 0 and (
                retriable is None or rec.created > retriable[1].created
            ):
                retriable = (w, rec)
        return retriable or any_task

    async def _pull_object(self, oid: ObjectID, loc: RemoteLocation) -> Location:
        try:
            peer = await self._get_peer(loc.node_id)
            got = await self._transfer.pull(peer, oid)
        except Exception as e:
            raise ObjectLostError(
                f"object {oid.hex()} unavailable from node "
                f"{loc.node_id[:8]}: {e}"
            ) from e
        if isinstance(got, (bytes, bytearray, memoryview)):
            if len(got) <= self.config.max_inline_object_size:
                new_loc: Location = InlineLocation(bytes(got))
            else:
                new_loc = self.local_store.put_raw(oid, got)
        else:
            # Chunked pull: bytes already landed in the local store.
            new_loc = got
        self.directory.replace_location(oid, new_loc)
        # The pulled copy is now the locatable one (the source may free and
        # unpublish its copy once the hold is released).
        if self._gcs is not None and (self._multi_node or not self.is_head):
            asyncio.ensure_future(self._publish_seal(oid))
        if loc.held:
            # Release the hold the remote node keeps on our behalf.
            try:
                await peer.notify({"type": "free_object", "object_id": oid})
            except Exception:
                pass
        return new_loc

    async def wait_objects(
        self,
        object_ids: List[ObjectID],
        num_returns: int,
        timeout: Optional[float],
    ) -> List[ObjectID]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ready = [oid for oid in object_ids if oid in self._sealed]
            if len(ready) >= num_returns:
                return ready
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return ready
            # Event-driven: wake when any unsealed object seals.
            pending = [
                self._seal_events.setdefault(oid, asyncio.Event())
                for oid in object_ids
                if oid not in self._sealed
            ]
            tasks = [asyncio.ensure_future(ev.wait()) for ev in pending]
            try:
                await asyncio.wait(
                    tasks,
                    timeout=remaining,
                    return_when=asyncio.FIRST_COMPLETED,
                )
            finally:
                for t in tasks:
                    t.cancel()

    def _remove_ref(self, object_id: ObjectID, count: int = 1):
        self.directory.remove_ref(object_id, count)

    # ------------------------------------------------------ borrower protocol

    def _pin_ref(self, oid: ObjectID, count: int = 1) -> bool:
        """Stub-aware increment (NM loop only). When this node has no
        entry for ``oid`` — a ref to an object owned elsewhere — create a
        count-only borrow stub and register this node as a borrower with
        the owner (async). Returns True when a NEW stub was created, so
        completion paths can await the registration explicitly."""
        created = self.directory.add_ref_or_create(
            oid, count, _RETURN_PLACEHOLDER
        )
        if created:
            self._borrow_stubs.add(oid)
        return created

    def _pin_ref_bg(self, oid: ObjectID, count: int = 1):
        """_pin_ref + fire-and-forget borrow registration (callers that
        have no async context)."""
        if self._pin_ref(oid, count):
            self._spawn_bg(self._register_borrow(oid))

    async def _register_borrow(self, oid: ObjectID,
                               owner_hex: Optional[str] = None):
        """Resolve the owner of a borrow stub through the GCS object
        directory (unless the caller already knows it) and register this
        node in its borrower set. Idempotent; a failure leaves the stub
        unregistered (reads fail loudly if the owner frees it — same
        contract as an unregistered smuggled ref in the reference before
        the borrow lands)."""
        if self._gcs is None or not self._multi_node:
            return
        if oid in self._borrowed_from or oid in self._borrow_registering:
            return
        if oid not in self._borrow_stubs:
            return
        self._borrow_registering.add(oid)
        try:
            if owner_hex is None:
                try:
                    nid = await self._gcs.locate_object(
                        oid, timeout=self.config.object_locate_timeout_s
                    )
                except Exception:
                    return
                if nid is None or nid == self.node_id:
                    return
                owner_hex = nid.hex()
            try:
                peer = await self._get_peer(owner_hex)
                reply = await peer.request(
                    {"type": "register_borrow", "object_id": oid,
                     "borrower": self.node_id.hex()}
                )
            except Exception:
                return
            if reply.get("ok"):
                if oid in self._borrow_stubs:
                    self._borrowed_from[oid] = owner_hex
                else:
                    # The local entry was collected while the
                    # registration was in flight: undo it at the owner
                    # now, or the borrow pins the object forever.
                    self._spawn_bg(self._release_borrow(owner_hex, oid))
        finally:
            self._borrow_registering.discard(oid)

    async def _release_borrow(self, owner_hex: str, oid: ObjectID):
        try:
            peer = await self._get_peer(owner_hex)
            await peer.notify(
                {"type": "release_borrow", "object_id": oid,
                 "borrower": self.node_id.hex()}
            )
        except Exception:
            pass  # owner gone: nothing to release

    async def _apply_ref_deltas(self, deltas: Dict[ObjectID, int]):
        """Apply a worker's ref deltas shipped inside its task-completion
        frame — BEFORE the task's pins are dropped, so a ref the worker
        still holds (stored in actor state, returned inside a container)
        is counted, and any new cross-node borrow is REGISTERED with the
        owner, while the submission-time pin still protects the object."""
        new_stubs = []
        for oid, d in deltas.items():
            if d > 0:
                if self._pin_ref(oid, d):
                    new_stubs.append(oid)
            elif d < 0:
                self._remove_ref(oid, -d)
        for oid in new_stubs:
            await self._register_borrow(oid)

    def _register_nested(self, container: ObjectID,
                         nested: List[ObjectID]):
        """Pin refs serialized inside ``container`` until its directory
        entry is collected (ref analogue: AddNestedObjectIds)."""
        if not nested:
            return
        prior = self._nested_pins.setdefault(container, [])
        for oid in nested:
            prior.append(oid)
            self._pin_ref_bg(oid)

    async def _gc_loop(self):
        grace = self.config.gc_grace_period_s
        while not self._shutdown:
            await asyncio.sleep(min(1.0, grace / 2))
            for oid, loc in self.directory.collect_garbage(grace):
                self._sealed.discard(oid)
                self._seal_events.pop(oid, None)
                self._lineage.pop(oid, None)
                self._reconstructions.pop(oid, None)
                # This node's borrow of the object ends with its entry.
                self._borrow_stubs.discard(oid)
                owner_hex = self._borrowed_from.pop(oid, None)
                if owner_hex is not None:
                    # _spawn_bg: strong ref + drained at shutdown, so the
                    # release cannot be dropped mid-flight.
                    self._spawn_bg(self._release_borrow(owner_hex, oid))
                # Refs contained in this object lose their containment pin.
                for nested_oid in self._nested_pins.pop(oid, ()):
                    self._remove_ref(nested_oid)
                if isinstance(loc, RemoteLocation):
                    if loc.held:
                        # Release the hold the remote node keeps for us.
                        asyncio.ensure_future(self._free_remote(loc.node_id, oid))
                else:
                    _free_location(loc)
                    if self._gcs is not None and (
                        self._multi_node or not self.is_head
                    ):
                        asyncio.ensure_future(self._unpublish(oid))
            # Reclaim arena blocks stuck in pending-delete because a pinning
            # reader died without unpinning (ref analogue: plasma client
            # disconnect releasing its objects).
            arena = current_arena()
            if arena is not None:
                try:
                    arena.purge_dead_pins()
                except Exception:
                    pass

    async def _free_remote(self, node_hex: str, oid: ObjectID):
        try:
            peer = await self._get_peer(node_hex)
            await peer.notify({"type": "free_object", "object_id": oid})
        except Exception:
            pass

    async def _unpublish(self, oid: ObjectID):
        try:
            await self._gcs.unpublish_object(oid, self.node_id)
        except Exception:
            pass

    async def _reply_locations(self, w: WorkerHandle, msg):
        try:
            locs = await self.get_locations(msg["object_ids"], msg.get("timeout"))
            await w.writer.send(
                {"type": "reply", "msg_id": msg["msg_id"], "locations": locs}
            )
        except asyncio.TimeoutError:
            await w.writer.send(
                {"type": "reply", "msg_id": msg["msg_id"], "timeout": True}
            )
        # Reply-carried; the nested send races the worker's death —
        # a dead requester needs no reply.
        except Exception as e:  # rtlint: disable=swallowed-failure
            try:
                await w.writer.send(
                    {"type": "reply", "msg_id": msg["msg_id"], "error": str(e)}
                )
            except Exception:  # rtlint: disable=swallowed-failure
                pass

    async def _reply_wait(self, w: WorkerHandle, msg):
        ready = await self.wait_objects(
            msg["object_ids"], msg["num_returns"], msg.get("timeout")
        )
        await w.writer.send({"type": "reply", "msg_id": msg["msg_id"], "ready": ready})

    # --------------------------------------------------------------------- kv

    async def _handle_kv(self, w: WorkerHandle, msg):
        """Cluster KV (ref analogue: GCS InternalKV, gcs_kv_manager.h) —
        authoritative store lives at the GCS; the per-node dict is only a
        fallback for GCS-less unit setups."""
        op = msg["op"]
        out: Dict[str, Any] = {"type": "reply", "msg_id": msg["msg_id"]}
        if self._gcs is not None:
            try:
                if op == "put":
                    out["added"] = await self._gcs.kv_put(
                        msg["key"], msg["value"], msg.get("overwrite", True)
                    )
                elif op == "get":
                    out["value"] = await self._gcs.kv_get(
                        msg["key"], msg.get("wait_timeout") or 0
                    )
                elif op == "del":
                    out["deleted"] = await self._gcs.kv_del(msg["key"])
                elif op == "keys":
                    out["keys"] = await self._gcs.kv_keys(msg.get("prefix", ""))
            # Reply-carried: the worker's kv call raises it.
            except Exception as e:  # rtlint: disable=swallowed-failure
                out["error"] = str(e)
            await w.writer.send(out)
            return
        if op == "put":
            overwrite = msg.get("overwrite", True)
            if not overwrite and msg["key"] in self._kv:
                out["added"] = False
            else:
                self._kv[msg["key"]] = msg["value"]
                out["added"] = True
        elif op == "get":
            out["value"] = self._kv.get(msg["key"])
        elif op == "del":
            out["deleted"] = self._kv.pop(msg["key"], None) is not None
        elif op == "keys":
            prefix = msg.get("prefix", "")
            out["keys"] = [k for k in self._kv if k.startswith(prefix)]
        await w.writer.send(out)

    # -------------------------------------------------------- pubsub proxy

    async def _handle_pubsub(self, w: WorkerHandle, msg):
        """Driver/worker access to the GCS pubsub (ref analogue: workers
        reach GCS pubsub through their raylet-side gcs client;
        gcs_service.proto:595 InternalPubSub). The proxy keeps pubsub on
        the same authenticated node↔GCS channel everything else uses."""
        out: Dict[str, Any] = {"type": "reply", "msg_id": msg["msg_id"]}
        try:
            out.update(await self._pubsub_op(msg))
        # Reply-carried: pubsub_op raises it caller-side.
        except Exception as e:  # rtlint: disable=swallowed-failure
            out["error"] = str(e)
        try:
            await w.writer.send(out)
        except Exception:  # rtlint: disable=swallowed-failure
            pass  # dead requester needs no reply

    async def _pubsub_op(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        if self._gcs is None:
            raise RuntimeError("pubsub requires the cluster GCS")
        op = msg["op"]
        if op == "subscribe":
            await self._gcs.psub_subscribe(
                msg["subscriber_id"], msg["channels"]
            )
            return {"ok": True}
        if op == "poll":
            return await self._gcs.psub_poll(
                msg["subscriber_id"], msg.get("timeout", 30.0),
                msg.get("max_events", 1000),
            )
        if op == "publish":
            return {"seq": await self._gcs.psub_publish(
                msg["channel"], msg["data"], key=msg.get("key")
            )}
        if op == "unsubscribe":
            await self._gcs.psub_unsubscribe(
                msg["subscriber_id"], msg.get("channels")
            )
            return {"ok": True}
        if op == "describe":
            return {"services": await self._gcs.rpc_describe()}
        raise RuntimeError(f"unknown pubsub op {op}")

    def pubsub_op(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Sync entry for the in-process driver runtime."""
        return self.call_sync(self._pubsub_op(msg))

    # ------------------------------------------------- cluster-event query

    async def _handle_events_query(self, w: WorkerHandle, msg):
        out: Dict[str, Any] = {"type": "reply", "msg_id": msg["msg_id"]}
        try:
            out.update(await self._events_list(
                severity=msg.get("severity"), source=msg.get("source"),
                limit=msg.get("limit", 1000),
            ))
        # Reply-carried: list_cluster_events raises it caller-side.
        except Exception as e:  # rtlint: disable=swallowed-failure
            out["error"] = str(e)
        try:
            await w.writer.send(out)
        except Exception:  # rtlint: disable=swallowed-failure
            pass  # dead requester needs no reply

    async def _events_list(self, severity=None, source=None,
                           limit: int = 1000) -> Dict[str, Any]:
        """Fetch the head aggregator's event store (ref analogue:
        `ray list cluster-events` hitting the GCS)."""
        if self._gcs is None:
            raise RuntimeError("cluster events require the cluster GCS")
        return await self._gcs.events_list(
            severity=severity, source=source, limit=limit
        )

    async def _handle_timeseries_query(self, w: WorkerHandle, msg):
        out: Dict[str, Any] = {"type": "reply", "msg_id": msg["msg_id"]}
        try:
            out.update(await self._timeseries_query(
                name=msg.get("name", ""), tags=msg.get("tags"),
                since=msg.get("since", 0.0), limit=msg.get("limit", 0),
                quantile=msg.get("quantile", 0.0),
                window=msg.get("window", 60.0),
            ))
        # Reply-carried: timeseries_query raises it caller-side.
        except Exception as e:  # rtlint: disable=swallowed-failure
            out["error"] = str(e)
        try:
            await w.writer.send(out)
        except Exception:  # rtlint: disable=swallowed-failure
            pass  # dead requester needs no reply

    async def _handle_slo_query(self, w: WorkerHandle, msg):
        out: Dict[str, Any] = {"type": "reply", "msg_id": msg["msg_id"]}
        try:
            out.update(await self._slo_status())
        # Reply-carried: slo_status raises it caller-side.
        except Exception as e:  # rtlint: disable=swallowed-failure
            out["error"] = str(e)
        try:
            await w.writer.send(out)
        except Exception:  # rtlint: disable=swallowed-failure
            pass  # dead requester needs no reply

    async def _timeseries_query(self, name="", tags=None, since=0.0,
                                limit: int = 0, quantile: float = 0.0,
                                window: float = 60.0) -> Dict[str, Any]:
        """Query the head TSDB (ref analogue: the dashboard hitting the
        metrics head). ``quantile`` > 0 adds a head-derived histogram
        quantile over the trailing ``window`` seconds."""
        if self._gcs is None:
            raise RuntimeError("timeseries require the cluster GCS")
        return await self._gcs.timeseries_query(
            name=name, tags=tags, since=since, limit=limit,
            quantile=quantile, window=window
        )

    async def _slo_status(self) -> Dict[str, Any]:
        if self._gcs is None:
            raise RuntimeError("SLO status requires the cluster GCS")
        return await self._gcs.slo_status()

    # ------------------------------------------------- profiling plane

    def _worker_frame_future(self, w: WorkerHandle,
                             frame: Dict[str, Any]):
        """Send one stack_dump/profile frame to a worker and return
        (req_id, future) for its reply — the single place that owns the
        pending-table bookkeeping. (None, None) if the send failed.
        Loop-thread only."""
        self._profile_req_seq += 1
        req_id = self._profile_req_seq
        fut: asyncio.Future = self._loop.create_future()
        self._profile_pending[req_id] = fut
        try:
            w.writer.send_nowait({**frame, "req_id": req_id})
        except Exception:
            self._profile_pending.pop(req_id, None)
            return None, None
        return req_id, fut

    def _profile_fanout_workers(self, frame: Dict[str, Any]):
        """Send a stack_dump/profile frame to every live worker; returns
        [(handle, req_id, future), ...] for the replies. Loop-thread
        only."""
        waits = []
        for w in list(self._workers.values()):
            if w.state in ("dead", "client") or w.worker_type == "client":
                continue
            req_id, fut = self._worker_frame_future(w, frame)
            if fut is not None:
                waits.append((w, req_id, fut))
        return waits

    async def _gather_profile_replies(self, waits, timeout: float):
        """Await the fan-out replies; a worker that never answers (dead,
        wedged reader) is dropped from the result instead of hanging the
        whole dump. Returns (replies, missing_worker_hexes)."""
        if waits:
            await asyncio.wait([f for _, _, f in waits], timeout=timeout)
        replies, missing = [], []
        for w, req_id, fut in waits:
            if fut.done():
                # done() checked: result() returns immediately.
                replies.append(fut.result())  # rtlint: disable=loop-blocking
            else:
                self._profile_pending.pop(req_id, None)
                missing.append(w.worker_id.hex())
        return replies, missing

    async def stacks_dump(self, timeout: float = 5.0) -> Dict[str, Any]:
        """One-shot stack dump of this node: the node-manager process
        plus every live worker (ref analogue: `ray stack` against one
        node). Workers that do not answer within ``timeout`` degrade to
        a partial result listed under ``missing_workers``."""
        from ..util import profiler

        procs = [{
            "pid": os.getpid(),
            "kind": "node_manager",
            "worker_id": None,
            "threads": profiler.dump_stacks(),
        }]
        waits = self._profile_fanout_workers({"type": "stack_dump"})
        replies, missing = await self._gather_profile_replies(
            waits, timeout
        )
        for r in replies:
            procs.append({
                "pid": r.get("pid"),
                "kind": "worker",
                "worker_id": r.get("worker_id"),
                "threads": r.get("threads", []),
            })
        return {
            "node_id": self.node_id.hex(),
            "is_head": self.is_head,
            "procs": procs,
            "missing_workers": missing,
        }

    async def profile_run(self, seconds: float = 2.0,
                          hz: int = 100) -> Dict[str, Any]:
        """Timed sampling profile of this node: the node-manager process
        (sampled OFF this event loop, in the default executor) plus
        every live worker, merged to collapsed-stack counts keyed
        ``pid:<pid>(<kind>);<thread>;<frames...>``."""
        from ..util import profiler

        seconds = max(0.0, min(float(seconds),
                               profiler.MAX_SAMPLE_SECONDS))
        hz = max(1, min(int(hz), profiler.MAX_SAMPLE_HZ))
        local_fut = self._loop.run_in_executor(
            None, profiler.sample, seconds, hz
        )
        waits = self._profile_fanout_workers(
            {"type": "profile", "seconds": seconds, "hz": hz}
        )
        # Gather runs CONCURRENTLY with the local sample: its timeout
        # clock starts now, so a wedged worker bounds the whole node
        # reply at ~seconds+5 — within the GCS's per-node timeout —
        # instead of 2*seconds+5, which would drop the node (and every
        # healthy worker's samples) from the cluster reply.
        gather_task = asyncio.ensure_future(
            self._gather_profile_replies(waits, seconds + 5.0)
        )
        local = await local_fut
        replies, missing = await gather_task
        counts: Dict[str, int] = {}
        samples = local.get("samples", 0)

        def fold(pid, kind, src):
            prefix = f"pid:{pid}({kind})"
            for stack, n in (src or {}).items():
                key = f"{prefix};{stack}"
                counts[key] = counts.get(key, 0) + n

        fold(os.getpid(), "node_manager", local.get("counts"))
        for r in replies:
            fold(r.get("pid"), "worker", r.get("counts"))
            samples += r.get("samples", 0)
        return {
            "node_id": self.node_id.hex(),
            "is_head": self.is_head,
            "seconds": seconds,
            "hz": hz,
            "counts": counts,
            "samples": samples,
            "missing_workers": missing,
        }

    async def cluster_stacks(self, timeout: float = 5.0) -> Dict[str, Any]:
        """Cluster-wide stack dump via the GCS ProfileService (falls
        back to this node alone in GCS-less unit setups)."""
        if self._gcs is None:
            return {"nodes": [await self.stacks_dump(timeout)],
                    "errors": {}}
        return await self._gcs.stacks_dump(timeout=timeout)

    async def cluster_profile(self, seconds: float = 2.0,
                              hz: int = 100) -> Dict[str, Any]:
        """Cluster-wide sampling profile via the GCS ProfileService."""
        if self._gcs is None:
            return {"nodes": [await self.profile_run(seconds, hz)],
                    "errors": {}}
        return await self._gcs.profile_run(seconds=seconds, hz=hz)

    def traces_dump(self, reason: Optional[str] = None,
                    limit: int = 200) -> Dict[str, Any]:
        """This node's tail-sampled flight-recorder ring (the node
        manager shares a process with the driver/head ingress, so the
        proxy's retained requests live here; worker rings mirror through
        the cluster KV)."""
        from ..util import flight_recorder

        rec = flight_recorder.get_recorder()
        return {
            "node_id": self.node_id.hex(),
            "is_head": self.is_head,
            "records": rec.list(reason=reason, limit=limit),
            "stats": rec.stats(),
        }

    async def cluster_traces(self, reason: Optional[str] = None,
                             limit: int = 200) -> Dict[str, Any]:
        """Cluster-wide flight-recorder dump via the GCS fan-out."""
        if self._gcs is None:
            return {"nodes": [self.traces_dump(reason, limit)],
                    "errors": {}}
        return await self._gcs.traces_dump(reason=reason or "",
                                           limit=limit)

    def objects_census(self, limit: int = 500) -> Dict[str, Any]:
        """This node's slice of the cluster object census (ref analogue:
        the GCS object table + local_object_manager stats, merged): the
        directory's per-object rows enriched with a coarse lifecycle
        state (in-memory / spilled / inflight / remote), the borrow
        owner's node hex where known, plus store/spill/pull accounting
        so the head can aggregate without a second round trip."""
        rows = self.directory.census_rows(limit=limit)
        transfer = getattr(self, "_transfer", None)
        inflight = (transfer.inflight_pulls()
                    if transfer is not None else [])
        pulling = {p.get("oid") for p in inflight}
        for r in rows:
            where = r["where"]
            if where in ("shm", "inline", "arena"):
                r["state"] = "in-memory"
            elif where == "spilled":
                r["state"] = "spilled"
            elif where == "remote":
                r["state"] = ("inflight" if r["object_id"] in pulling
                              else "remote")
            else:
                r["state"] = where
            owner_hex = self._borrowed_from.get(
                ObjectID.from_hex(r["object_id"]))
            if owner_hex:
                r["owner_node"] = owner_hex
        spill = getattr(self, "spill_manager", None)
        return {
            "node_id": self.node_id.hex(),
            "is_head": self.is_head,
            "objects": rows,
            "used_bytes": self.directory.used_bytes,
            "capacity_bytes": self.directory.capacity_bytes,
            "num_objects": self.directory.num_objects(),
            "spilled_bytes": (spill.used_bytes() if spill is not None
                              else 0),
            "inflight_pulls": inflight,
        }

    async def cluster_objects(self, limit: int = 500) -> Dict[str, Any]:
        """Cluster-wide object census via the GCS fan-out (same
        partial-tolerant shape as cluster_stacks/cluster_traces)."""
        if self._gcs is None:
            return {"nodes": [self.objects_census(limit)], "errors": {}}
        return await self._gcs.objects_census(limit=limit)

    # ---------------------------------------------------- leak detection

    def _maybe_leak_sweep(self) -> None:
        """Kick one background leak sweep when due (head only). Cadence
        scales with the warn threshold so a leak is flagged within
        ``object_leak_warn_s`` of crossing it without hammering the
        census fan-out on the default 5-minute threshold."""
        from ..util import data_obs

        warn_s = getattr(self.config, "object_leak_warn_s", 0.0)
        if warn_s <= 0 or not data_obs.ENABLED:
            return
        if (self._leak_sweep_task is not None
                and not self._leak_sweep_task.done()):
            return
        interval = max(0.5, min(warn_s / 2.0, 30.0))
        now = time.monotonic()
        if now - self._leak_last_sweep < interval:
            return
        self._leak_last_sweep = now
        self._leak_sweep_task = asyncio.ensure_future(self._leak_sweep())

    async def _leak_sweep(self) -> None:
        """One head-side leak pass over the cluster census: a sealed
        object is leaked when it has sat at zero live refs past
        ``object_leak_warn_s``, or when it is a borrow whose owner node
        is dead/fenced. Publishes the leak gauges every pass (so GC
        clears them) and emits ONE deduped WARNING OBJECT_STORE event
        per offender per episode. Never raises."""
        from ..util import data_obs

        try:
            warn_s = float(self.config.object_leak_warn_s)
            census = await self.cluster_objects(limit=2000)
            me = self.node_id.hex()
            leaked = []  # (holder node hex, row, why)
            for node in census.get("nodes", []):
                holder = node.get("node_id", "")
                for r in node.get("objects", []):
                    if r.get("state") == "inflight":
                        continue
                    why = ""
                    zero = r.get("zero_ref_s")
                    if zero is not None and zero > warn_s:
                        why = f"zero refs for {zero:.0f}s"
                    owner_node = r.get("owner_node")
                    if not why and owner_node and owner_node != me:
                        view = self._cluster_view.get(owner_node)
                        state = (view or {}).get("state", "dead")
                        if (owner_node in self._fenced_nodes
                                or state not in ("alive", "draining")):
                            why = (f"owner node {owner_node[:8]} is "
                                   f"{state}")
                    if why:
                        leaked.append((holder, r, why))
            data_obs.set_leaked(
                len(leaked),
                sum(r.get("size_bytes") or 0 for _, r, _ in leaked),
            )
            current = set()
            for holder, r, why in leaked:
                oid = r["object_id"]
                current.add(oid)
                if oid in self._leak_warned:
                    continue
                self._leak_warned.add(oid)
                cluster_events.emit(
                    cluster_events.WARNING, cluster_events.OBJECT_STORE,
                    f"LEAK suspected: object {oid[:8]} "
                    f"({r.get('size_bytes') or 0} bytes, "
                    f"owner {r.get('owner') or '?'}) on node "
                    f"{holder[:8]}: {why}",
                    node_id=holder,
                    custom_fields={
                        "object_id": oid,
                        "size_bytes": r.get("size_bytes") or 0,
                        "owner": r.get("owner") or "",
                        "state": r.get("state") or "",
                        "age_s": r.get("age_s"),
                        "why": why,
                    },
                )
            # Offenders that stopped looking leaked (GC'd, or refs
            # re-appeared) leave the dedup set: a future re-leak of the
            # same oid warns again instead of staying silent forever.
            self._leak_warned &= current
        except Exception:  # rtlint: disable=swallowed-failure
            pass  # telemetry sweep must never take the loop down

    async def _handle_profile_query(self, w: WorkerHandle, msg):
        out: Dict[str, Any] = {"type": "reply", "msg_id": msg["msg_id"]}
        try:
            if msg.get("op") == "stacks":
                out["result"] = await self.cluster_stacks(
                    timeout=msg.get("timeout", 5.0)
                )
            elif msg.get("op") == "run":
                out["result"] = await self.cluster_profile(
                    seconds=msg.get("seconds", 2.0),
                    hz=msg.get("hz", 100),
                )
            elif msg.get("op") == "traces":
                out["result"] = await self.cluster_traces(
                    reason=msg.get("reason") or None,
                    limit=msg.get("limit", 200),
                )
            elif msg.get("op") == "objects":
                out["result"] = await self.cluster_objects(
                    limit=msg.get("limit", 500)
                )
            else:
                out["error"] = f"unknown profile op {msg.get('op')!r}"
        # Reply-carried: the rtpu profile caller shows it.
        except Exception as e:  # rtlint: disable=swallowed-failure
            out["error"] = str(e)
        try:
            await w.writer.send(out)
        except Exception:  # rtlint: disable=swallowed-failure
            pass  # dead requester needs no reply

    # ---------------------------------------------------- hang detector

    async def _check_hung_tasks(self):
        """Flag tasks running longer than ``hang_task_warn_s``: capture
        the owning worker's stack and emit a WARNING cluster event (ref
        analogue: the reference's "task is hung" debugging loop — `ray
        stack` by hand — folded into the control plane)."""
        thresh = getattr(self.config, "hang_task_warn_s", 0.0)
        if thresh <= 0:
            return
        now = time.monotonic()
        for record in list(self._tasks.values()):
            if (
                record.state != "running"
                or record.hang_warned
                or record.dispatched is None
                or now - record.dispatched < thresh
            ):
                continue
            worker = self._workers.get(record.worker_id)
            if worker is None or worker.current is not record:
                # Pipelined rider still queued on its worker: it is not
                # EXECUTING yet — warning now would blame it for the
                # head task's runtime and capture the wrong stack.
                continue
            record.hang_warned = True
            self._spawn_bg(self._warn_hung_task(
                record, now - record.dispatched, thresh
            ))

    async def _warn_hung_task(self, record: TaskRecord, elapsed: float,
                              thresh: float):
        from ..util import profiler

        worker = self._workers.get(record.worker_id)
        stack_text = ""
        worker_pid = None
        if worker is not None and worker.state != "dead":
            worker_pid = worker.proc.pid if worker.proc else None
            req_id, fut = self._worker_frame_future(
                worker, {"type": "stack_dump"}
            )
            if fut is not None:
                try:
                    reply = await asyncio.wait_for(fut, timeout=2.0)
                    stack_text = profiler.format_stack_text(
                        reply.get("threads", [])
                    )
                except Exception:
                    self._profile_pending.pop(req_id, None)
        name = record.spec.name or record.spec.method_name or "task"
        captured = ("worker stack captured" if stack_text
                    else "worker stack capture failed")
        cluster_events.emit(
            cluster_events.WARNING, cluster_events.TASK,
            f"task '{name}' has been running for {elapsed:.1f}s "
            f"(> hang_task_warn_s={thresh:g}); {captured}",
            node_id=self.node_id.hex(),
            task_id=record.spec.task_id.hex(),
            actor_id=(record.spec.actor_id.hex()
                      if record.spec.actor_id else None),
            custom_fields={
                "elapsed_s": round(elapsed, 3),
                "threshold_s": thresh,
                "worker_pid": worker_pid,
                "stack": stack_text[:8000],
            },
        )

    # ------------------------------------------------- placement-group proxy

    async def _handle_pg(self, w: WorkerHandle, msg):
        out: Dict[str, Any] = {"type": "reply", "msg_id": msg["msg_id"]}
        try:
            out.update(await self.pg_op(msg))
        # Reply-carried: the placement-group API raises it caller-side.
        except Exception as e:  # rtlint: disable=swallowed-failure
            out["error"] = str(e)
        try:
            await w.writer.send(out)
        except Exception:  # rtlint: disable=swallowed-failure
            pass  # dead requester needs no reply

    async def pg_op(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        if self._gcs is None:
            raise RuntimeError("placement groups require the cluster GCS")
        op = msg["op"]
        if op == "create":
            await self._gcs.pg_create(
                msg["pg_id"], msg["bundles"], msg["strategy"],
                msg.get("name", ""),
                label_selectors=msg.get("label_selectors"),
            )
            return {"ok": True}
        if op == "wait":
            return {"ready": await self._gcs.pg_wait(msg["pg_id"], msg["timeout"])}
        if op == "remove":
            await self._gcs.pg_remove(msg["pg_id"])
            self._pg_nodes.pop(msg["pg_id"], None)
            return {"ok": True}
        if op == "table":
            return {"table": await self._gcs.pg_table()}
        raise RuntimeError(f"unknown pg op {op}")

    def kv_put(self, key: str, value: bytes, overwrite: bool = True) -> bool:
        async def _put():
            if self._gcs is not None:
                return await self._gcs.kv_put(key, value, overwrite)
            if not overwrite and key in self._kv:
                return False
            self._kv[key] = value
            return True

        return self.call_sync(_put())

    def kv_get(self, key: str) -> Optional[bytes]:
        async def _get():
            if self._gcs is not None:
                return await self._gcs.kv_get(key)
            return self._kv.get(key)

        return self.call_sync(_get())

    def kv_keys(self, prefix: str = "") -> List[str]:
        async def _keys():
            if self._gcs is not None:
                return await self._gcs.kv_keys(prefix)
            return [k for k in self._kv if k.startswith(prefix)]

        return self.call_sync(_keys())

    def kv_del(self, key: str) -> bool:
        async def _del():
            if self._gcs is not None:
                return await self._gcs.kv_del(key)
            return self._kv.pop(key, None) is not None

        return self.call_sync(_del())

    # ----------------------------------------------------------- cancellation

    async def get_actor_direct(
        self, actor_id: ActorID, timeout: float = 30.0
    ) -> Optional[Dict[str, Any]]:
        """Resolve an actor's direct-call endpoint descriptor
        ({"path": uds, "addr": (host, port), "ver", "node"}). A local
        actor answers only once it is alive, has advertised endpoints,
        AND has no node-manager-routed calls queued or in flight — the
        caller's switch to the direct channel therefore cannot overtake
        any call routed through here (per-caller actor ordering). An
        actor homed on a peer node resolves through that node's NM,
        which applies the same drain gate."""
        if actor_id not in self._actors:
            home = self._actor_homes.get(actor_id)
            if home and home != "dead":
                try:
                    peer = await self._get_peer(home)
                    reply = await peer.request(
                        {"type": "get_actor_direct_peer",
                         "actor_id": actor_id, "timeout": timeout},
                        timeout=timeout + 10.0,
                    )
                    return reply.get("direct")
                except Exception:
                    return None
            return None
        start = self._loop.time()
        deadline = start + timeout
        alive_no_path_since = None
        while True:
            if self._shutdown:
                return None  # don't outlive the loop (pending-task warning)
            info = self._actors.get(actor_id)
            if info is None or info.state == "dead":
                return None
            if info.state == "alive":
                if info.direct_path is None and info.direct_addr is None:
                    # Worker predates direct support or the advert is in
                    # flight; give it a moment then report unsupported.
                    now = self._loop.time()
                    if alive_no_path_since is None:
                        alive_no_path_since = now
                    elif now - alive_no_path_since > 1.0:
                        return None
                elif not info.queued and not info.inflight:
                    return {
                        "path": info.direct_path,
                        "addr": info.direct_addr,
                        "ver": info.direct_ver,
                        "node": self.node_id.hex(),
                        # Incarnation rides the descriptor into the
                        # direct hello; the worker refuses a mismatch
                        # (fencing: a recycled endpoint or restarted
                        # actor can never serve a stale resolution).
                        "inc": info.incarnation,
                    }
            now = self._loop.time()
            if now > deadline:
                return None
            # Adaptive poll: fine-grained while the drain window is hot
            # (the common sync case resolves in ms), coarse afterwards so
            # a long-busy actor does not ride the control loop at 200 Hz.
            await asyncio.sleep(0.005 if now - start < 0.25 else 0.05)

    async def _reply_actor_direct(self, w: WorkerHandle, msg):
        """Worker/client-side get_actor_direct request: long-polls the
        drain window off the message loop and replies when resolved."""
        try:
            desc = await self.get_actor_direct(
                msg["actor_id"], timeout=float(msg.get("timeout") or 30.0)
            )
        except Exception:
            desc = None
        try:
            await w.writer.send({"type": "reply", "msg_id": msg["msg_id"],
                                 "direct": desc})
        except Exception:
            pass

    async def cancel_task(self, task_id: TaskID, force: bool = False):
        record = self._tasks.get(task_id)
        if record is None or record.state in ("finished", "failed", "cancelled"):
            return
        if record.state == "forwarded" and record.target is not None:
            try:
                peer = await self._get_peer(record.target)
                await peer.notify(
                    {"type": "cancel_task_peer", "task_id": task_id,
                     "force": force}
                )
            except Exception:
                pass
            return
        if record.state in ("waiting", "ready", "queued"):
            prev = record.state
            record.state = "cancelled"
            if prev == "waiting":
                self._waiting.pop(task_id, None)
            self._fail_task(record, TaskCancelledError(record.spec.name))
            record.state = "cancelled"
        elif record.state == "running" and force:
            worker = self._workers.get(record.worker_id)
            record.state = "cancelled"
            self._fail_task(record, TaskCancelledError(record.spec.name))
            record.state = "cancelled"
            if worker is not None and record in worker.pending:
                # Only QUEUED on the worker (pipelined frame, not yet
                # executing): reclaim the frame instead of killing the
                # process — the kill would take down the unrelated task
                # actually running there. Flush buffered execute frames
                # first so the reclaim cannot overtake this record's own
                # frame on the socket.
                self._flush_worker_exec_buf(worker)
                try:
                    worker.writer.send_nowait(
                        {"type": "reclaim",
                         "task_ids": [record.spec.task_id]}
                    )
                except Exception:
                    pass
            elif worker is not None and worker.proc is not None:
                worker._intentional_kill = True
                try:
                    worker.proc.kill()
                except Exception:
                    pass

    # ------------------------------------------------------ functions / stats

    async def register_function(self, function_id: str, blob: bytes):
        self._functions[function_id] = blob
        # Export to the cluster function table so every node can lazy-import
        # (ref analogue: function_manager.py export to GCS KV).
        if self._gcs is not None:
            asyncio.ensure_future(self._export_function(function_id, blob))

    async def _export_function(self, function_id: str, blob: bytes):
        try:
            await self._gcs.register_function(function_id, blob)
        except Exception:
            pass

    async def _function_blob(self, function_id: str) -> Optional[bytes]:
        blob = self._functions.get(function_id)
        if blob is None and self._gcs is not None:
            try:
                blob = await self._gcs.fetch_function(function_id)
            except Exception:
                blob = None
            if blob is not None:
                self._functions[function_id] = blob
        return blob

    def _observe_task_duration(self, seconds: float) -> None:
        h = self._task_duration
        h["count"] += 1
        h["sum"] += seconds
        for i, b in enumerate(h["bounds"]):
            if seconds <= b:
                h["buckets"][i] += 1
                return
        h["buckets"][-1] += 1

    async def stats(self) -> Dict[str, Any]:
        return {
            **self._stats,
            "num_workers": len(self._workers),
            "num_actors_alive": sum(
                1 for a in self._actors.values() if a.state == "alive"
            ),
            "object_store_used_bytes": self.directory.used_bytes,
            "num_objects": self.directory.num_objects(),
            "available_resources": self.node_resources.available.to_dict(),
            "total_resources": self.node_resources.total.to_dict(),
            "pending_tasks": len(self._ready) + len(self._waiting),
            "num_nodes": max(1, len(self._cluster_view)),
            "tasks_forwarded": len(self._forwarded),
        }

    async def cluster_nodes(self) -> List[Dict[str, Any]]:
        """Alive-node views (ref analogue: ray.nodes() via
        GlobalStateAccessor)."""
        if self.is_head and self.gcs_service is not None:
            return self.gcs_service.nodes_view()
        self._cluster_view[self.node_id.hex()] = self._local_view()
        return list(self._cluster_view.values())

    # ------------------------------------------------------------- state API

    def _local_state_snapshot(self) -> Dict[str, List[Dict[str, Any]]]:
        """This node's live-state tables in wire form (ref analogue: the
        raylet's contribution to ray.util.state — NodeManagerService
        GetTasksInfo / GetObjectsInfo handlers)."""
        node = self.node_id.hex()
        tasks = []
        for tid, rec in self._tasks.items():
            tasks.append({
                "task_id": tid.hex(),
                "name": rec.spec.name,
                "state": rec.state,
                "node_id": node,
                "type": rec.spec.task_type.name,
                "actor_id": (rec.spec.actor_id.hex()
                             if rec.spec.actor_id else None),
                "age_s": round(time.monotonic() - rec.created, 3),
            })
        # Terminal records retained after leaving the live table: the
        # failure history list_tasks needs to answer "what failed".
        tasks.extend(dict(row) for row in self._task_history)
        actors = []
        for aid, info in self._actors.items():
            w = self._workers.get(info.worker_id)
            actors.append({
                "actor_id": aid.hex(),
                "class_name": info.creation_spec.class_name,
                "state": info.state,
                "name": info.name,
                "node_id": node,
                "pid": (w.proc.pid if w is not None and w.proc else None),
                "restart_count": info.restart_count,
                "pending_calls": len(info.queued) + len(info.inflight),
            })
        from ..util.profiler import process_stats

        workers = []
        now = time.monotonic()
        for wid, w in self._workers.items():
            pid = w.proc.pid if w.proc else None
            row = {
                "worker_id": wid.hex(),
                "pid": pid,
                "state": w.state,
                "worker_type": w.worker_type,
                "node_id": node,
                "actor_id": w.actor_id.hex() if w.actor_id else None,
                # Current activity ("what is it doing right now"):
                # running task + live cpu/rss from /proc.
                "current_task": (w.current.spec.name
                                 or w.current.spec.method_name
                                 if w.current is not None else None),
                "current_task_id": (w.current.spec.task_id.hex()
                                    if w.current is not None else None),
                "running_for_s": (
                    round(now - w.current.dispatched, 3)
                    if w.current is not None
                    and w.current.dispatched is not None else None
                ),
            }
            if pid is not None:
                row.update(process_stats(pid))
            workers.append(row)
        objects = []
        for oid, size, where, refs in self.directory.entries_view():
            objects.append({
                "object_id": oid.hex(),
                "size_bytes": size,
                "where": where,
                "state": ("in-memory"
                          if where in ("shm", "inline", "arena")
                          else where),
                "owner": self.directory.owner_of(oid),
                "refcount": refs,
                "node_id": node,
            })
        return {
            "tasks": tasks,
            "actors": actors,
            "workers": workers,
            "objects": objects,
        }

    async def cluster_state(self) -> Dict[str, List[Dict[str, Any]]]:
        """Aggregate state across every alive node: own snapshot plus a
        fan-out ``state_snapshot`` peer query (ref analogue:
        util/state/api.py querying the GCS + each raylet)."""
        merged = self._local_state_snapshot()
        me = self.node_id.hex()
        peer_ids = [
            hex_id for hex_id, view in self._cluster_view.items()
            if hex_id != me and view.get("state", "alive") == "alive"
        ]

        async def query(hex_id: str):
            try:
                peer = await self._get_peer(hex_id)
                reply = await peer.request(
                    {"type": "state_snapshot"}, timeout=5.0
                )
                return reply.get("state")
            except Exception:
                return None

        for snap in await asyncio.gather(*(query(h) for h in peer_ids)):
            if snap:
                for kind in merged:
                    merged[kind].extend(snap.get(kind, []))
        return merged

    # ---------------------------------------------------------------- blocked

    def _on_worker_blocked(self, w: WorkerHandle):
        """Worker blocked in get(): release its task's resources so other
        tasks can run (ref analogue: NodeManager::HandleNotifyWorkerBlocked +
        the CPU release in local_task_manager)."""
        if w.state == "busy" and w.current is not None and w.current.resources_held:
            bundle_key = w.current.bundle_key  # keep for re-acquire
            self._release_task_resources(w.current)
            w.current.bundle_key = bundle_key
            w.state = "blocked"
            if w.pending:
                # Pipelined tasks behind a blocked task could DEADLOCK (the
                # blocked task may be waiting on one of them). Reclaim every
                # not-yet-started frame; the worker replies with what it
                # actually pulled back and those requeue elsewhere. Flush
                # buffered execute frames FIRST: the reclaim must arrive
                # after them on the socket or it misses frames still in
                # our buffer (the worker only scans its own queue).
                self._flush_worker_exec_buf(w)
                ids = [r.spec.task_id for r in w.pending]
                try:
                    w.writer.send_nowait(
                        {"type": "reclaim", "task_ids": ids}
                    )
                except Exception:
                    asyncio.ensure_future(self._on_worker_death(w))
            self._schedule()

    def _on_tasks_reclaimed(self, w: WorkerHandle, msg: Dict[str, Any]):
        """Worker returned pipelined frames it had not started: requeue
        them for dispatch elsewhere."""
        reclaimed = set(msg["task_ids"])

        def _requeue(record: TaskRecord):
            self._release_task_resources(record)
            record.worker_id = None
            if record.state != "cancelled":
                record.state = "ready"
                self._ready.append(record)

        kept: Deque[TaskRecord] = deque()
        for record in w.pending:
            if record.spec.task_id in reclaimed:
                _requeue(record)
            else:
                kept.append(record)
        w.pending = kept
        # Race: a completion that beat this reply may have PROMOTED a
        # reclaimed task to w.current — the worker will never run it (its
        # frame left the queue), so it must requeue too or it hangs.
        while w.current is not None and w.current.spec.task_id in reclaimed:
            _requeue(w.current)
            w.current = w.pending.popleft() if w.pending else None
        if w.current is None and w.state == "busy":
            w.state = "idle"
            self._idle[w.worker_type].append(w.worker_id)
        self._schedule()

    def _on_worker_unblocked(self, w: WorkerHandle):
        if w.state == "blocked" and w.current is not None:
            # Oversubscribe if necessary: clamp availability at zero rather
            # than deadlocking (the reference behaves the same way when a
            # blocked worker resumes).
            record = w.current
            res = record.spec.resources

            def _force_take(avail: ResourceSet) -> ResourceSet:
                fixed = dict(avail._amounts)
                for k, v in res._amounts.items():
                    fixed[k] = max(0, fixed.get(k, 0) - v)
                return ResourceSet(_fixed=fixed)

            if record.bundle_key is not None and (
                bundle := self._bundles.get(record.bundle_key)
            ) is not None:
                if res.is_subset_of(bundle.available):
                    bundle.available = bundle.available - res
                else:
                    bundle.available = _force_take(bundle.available)
            elif not self.node_resources.acquire(res):
                # Includes the bundle-released-while-blocked case: the
                # reservation rejoined the pool, so take from (and later
                # release to) the pool.
                record.bundle_key = None
                self.node_resources.available = _force_take(
                    self.node_resources.available
                )
            record.resources_held = True
            w.state = "busy"

    # --------------------------------------------------------------- shutdown

    def shutdown(self):
        if self._shutdown:
            return
        # Ship the event ring's tail while this process's transport is
        # still installed — after clear_publish_hook the buffered events
        # (crash-adjacent ERROR/CHAOS context included) have no way out.
        try:
            cluster_events.flush()
        except Exception:
            pass
        cluster_events.clear_publish_hook(self._publish_event_batch)
        self._shutdown = True
        if getattr(self, "dashboard_agent", None) is not None:
            self.dashboard_agent.stop()
        if getattr(self, "capi_server", None) is not None:
            self.capi_server.stop()
        # Data plane first: closing the listener + channel sockets makes
        # in-flight stripe workers error out instead of blocking the io
        # pool through the loop teardown below.
        if getattr(self, "_data_server", None) is not None:
            self._data_server.stop()
        self._transfer.close()

        async def _stop():
            if self._bg_tasks:
                try:
                    await asyncio.wait_for(
                        asyncio.gather(*list(self._bg_tasks),
                                       return_exceptions=True),
                        2.0,
                    )
                except Exception:
                    pass
            if getattr(self, "_gc_task", None) is not None:
                self._gc_task.cancel()
            if getattr(self, "_health_task", None) is not None:
                self._health_task.cancel()
            if getattr(self, "_memmon_task", None) is not None:
                self._memmon_task.cancel()
            if self._heartbeat_task is not None:
                self._heartbeat_task.cancel()
            for peer in self._peers.values():
                if isinstance(peer, PeerClient):
                    peer.close()
                else:
                    peer.cancel()
            if self._gcs_client is not None:
                self._gcs_client.close()
            if self.gcs_service is not None:
                self.gcs_service.stop()
            if self._peer_server is not None:
                self._peer_server.close()
            for w in list(self._workers.values()):
                try:
                    await asyncio.wait_for(w.writer.send({"type": "kill"}), 1.0)
                except Exception:
                    pass
            if self._server is not None:
                self._server.close()
            # Cancel stragglers (e.g. a get_actor_direct discovery poll
            # issued via call_sync) so the loop closes without "Task was
            # destroyed but it is pending" noise — and WAIT for the
            # cancellations to retire (a finally needing one more await
            # would otherwise still be pending at loop close).
            me = asyncio.current_task()
            others = [t for t in asyncio.all_tasks() if t is not me]
            for task in others:
                task.cancel()
            if others:
                try:
                    await asyncio.wait_for(
                        asyncio.gather(*others, return_exceptions=True),
                        1.0,
                    )
                except Exception:
                    pass

        # Cancel the watchdog tick while the loop still runs, so a
        # closed loop never holds a stale callback.
        loop_monitor.detach("nm")
        try:
            self._call(_stop()).result(timeout=5)
        except Exception:
            pass
        for w in list(self._workers.values()):
            if w.proc is not None:
                try:
                    w.proc.terminate()
                except Exception:
                    pass
        for w in list(self._workers.values()):
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=2)
                except Exception:
                    try:
                        w.proc.kill()
                    except Exception:
                        pass
        for proc in getattr(self, "_pending_procs", {}).values():
            try:
                proc.terminate()
            except Exception:
                pass
        # Unlink all remaining shm segments we know about, then the arena.
        for oid in list(self.directory._entries):
            _free_location(self.directory._entries.get(oid))
        if self.arena_name:
            shutdown_arena(unlink=True)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
