"""Single-node control plane: scheduler + worker pool + object directory.

This is the raylet-equivalent (ref: src/ray/raylet/node_manager.h NodeManager,
worker_pool.h WorkerPool, scheduling/cluster_task_manager.h +
local_task_manager.h) fused with the GCS-lite services a single node needs
(function table, KV store, named actors — ref: src/ray/gcs/gcs_server/). It
runs an asyncio event loop in a background thread of the head process; workers
connect over a unix socket with framed pickled messages (protocol.py).

The multi-node design splits along the same seams as the reference: this
class's public coroutines are the RPC surface a remote raylet/GCS would
expose; nothing below the coroutine layer assumes the caller is in-process.
"""

from __future__ import annotations

import asyncio
import os
import pickle
import struct
import subprocess
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

import cloudpickle

from .config import Config
from .exceptions import (
    ActorDiedError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from .ids import ActorID, NodeID, ObjectID, TaskID, WorkerID
from .object_store import (
    ArenaLocation,
    InlineLocation,
    Location,
    ObjectDirectory,
    ShmLocation,
    current_arena,
    init_arena,
    shutdown_arena,
)
from .resources import CPU, NodeResources, ResourceSet
from .task_spec import TaskSpec, TaskType

_HEADER = struct.Struct("<I")


def _free_location(loc) -> None:
    """Release an object's storage: arena delete or shm unlink."""
    if isinstance(loc, ArenaLocation):
        arena = current_arena()
        if arena is not None:
            try:
                arena.delete(loc.oid)
            except Exception:
                pass
    elif isinstance(loc, ShmLocation):
        try:
            from multiprocessing import shared_memory

            seg = shared_memory.SharedMemory(name=loc.name)
            seg.close()
            seg.unlink()
        except FileNotFoundError:
            pass
        except Exception:
            pass


def _task_worker_type(spec: TaskSpec) -> str:
    """Tasks/actors requesting TPU resources run in workers that keep the
    accelerator environment; everything else runs in fast-starting CPU
    workers (the chip is exclusive-access, so TPU workers are scarce)."""
    return "tpu" if spec.resources.get("TPU") > 0 else "cpu"


async def _read_frame(reader: asyncio.StreamReader) -> Dict[str, Any]:
    header = await reader.readexactly(_HEADER.size)
    (length,) = _HEADER.unpack(header)
    payload = await reader.readexactly(length)
    return pickle.loads(payload)


class _FramedWriter:
    def __init__(self, writer: asyncio.StreamWriter):
        self._writer = writer
        self._lock = asyncio.Lock()

    async def send(self, message: Dict[str, Any]):
        payload = cloudpickle.dumps(message, protocol=5)
        async with self._lock:
            self._writer.write(_HEADER.pack(len(payload)) + payload)
            await self._writer.drain()

    def close(self):
        try:
            self._writer.close()
        except Exception:
            pass


@dataclass
class TaskRecord:
    spec: TaskSpec
    state: str = "waiting"  # waiting | ready | running | finished | failed | cancelled
    worker_id: Optional[WorkerID] = None
    resources_held: bool = False
    deps_unpinned: bool = False


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    writer: _FramedWriter
    proc: Optional[subprocess.Popen] = None
    state: str = "idle"  # idle | busy | blocked | actor | dead
    worker_type: str = "cpu"  # cpu | tpu — tpu workers own the accelerator env
    current: Optional[TaskRecord] = None
    known_functions: Set[str] = field(default_factory=set)
    actor_id: Optional[ActorID] = None
    last_active: float = field(default_factory=time.monotonic)


@dataclass
class ActorInfo:
    actor_id: ActorID
    creation_spec: TaskSpec
    state: str = "pending"  # pending | alive | restarting | dead
    worker_id: Optional[WorkerID] = None
    queued: Deque[TaskSpec] = field(default_factory=deque)
    inflight: Dict[TaskID, TaskRecord] = field(default_factory=dict)
    restarts_left: int = 0
    restart_count: int = 0
    name: str = ""
    death_cause: str = ""


class NodeManager:
    def __init__(
        self,
        node_id: NodeID,
        session_dir: str,
        resources: Dict[str, float],
        config: Config,
    ):
        self.node_id = node_id
        self.session_dir = session_dir
        self.socket_path = os.path.join(session_dir, "node.sock")
        self.config = config
        self.node_resources = NodeResources(ResourceSet(resources))
        capacity = config.object_store_memory
        self.directory = ObjectDirectory(capacity)
        # Native C++ arena store (plasma-equivalent, src/store/): created by
        # the head process; workers attach via RAY_TPU_ARENA. Pure-Python
        # per-object shm remains the fallback when the toolchain is missing.
        self.arena_name: Optional[str] = None
        if config.use_native_store:
            name = f"/rtpu-{node_id.hex()[:16]}"
            if init_arena(name, capacity=capacity or (1 << 30), create=True):
                self.arena_name = name

        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run_loop, name="ray_tpu-node-manager", daemon=True
        )
        self._server: Optional[asyncio.AbstractServer] = None
        self._started = threading.Event()
        self._shutdown = False

        # Scheduling state (loop-thread only).
        self._ready: Deque[TaskRecord] = deque()
        self._waiting: Dict[TaskID, Tuple[TaskRecord, Set[ObjectID]]] = {}
        self._dep_index: Dict[ObjectID, Set[TaskID]] = {}
        self._tasks: Dict[TaskID, TaskRecord] = {}

        self._workers: Dict[WorkerID, WorkerHandle] = {}
        self._idle: Dict[str, Deque[WorkerID]] = {"cpu": deque(), "tpu": deque()}
        self._starting_workers = {"cpu": 0, "tpu": 0}
        self._pending_types: Dict[WorkerID, str] = {}

        self._actors: Dict[ActorID, ActorInfo] = {}
        self._named_actors: Dict[str, ActorID] = {}

        self._functions: Dict[str, bytes] = {}
        self._kv: Dict[str, bytes] = {}

        self._sealed: Set[ObjectID] = set()
        self._seal_events: Dict[ObjectID, asyncio.Event] = {}
        self._pending_procs: Dict[WorkerID, subprocess.Popen] = {}

        self._stats = {
            "tasks_submitted": 0,
            "tasks_finished": 0,
            "tasks_failed": 0,
            "tasks_retried": 0,
            "workers_started": 0,
            "actors_created": 0,
        }

    # ------------------------------------------------------------------ boot

    def start(self):
        self._thread.start()
        self._started.wait(timeout=30)
        for _ in range(self.config.num_prestart_workers):
            self._loop.call_soon_threadsafe(self._spawn_worker)

    def _run_loop(self):
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._start_server())
        self._started.set()
        self._loop.run_forever()
        # Drain pending callbacks after stop().
        self._loop.run_until_complete(self._loop.shutdown_asyncgens())
        self._loop.close()

    async def _start_server(self):
        self._server = await asyncio.start_unix_server(
            self._handle_connection, path=self.socket_path
        )
        self._gc_task = asyncio.ensure_future(self._gc_loop())
        self._health_task = asyncio.ensure_future(self._health_loop())

    async def _health_loop(self):
        """Detect workers that died before registering (e.g. import errors)
        so pending tasks fail loudly instead of hanging (ref analogue:
        WorkerPool startup-failure handling + GcsHealthCheckManager)."""
        consecutive_failures = 0
        while not self._shutdown:
            await asyncio.sleep(0.5)
            for worker_id, proc in list(self._pending_procs.items()):
                if proc.poll() is None:
                    continue
                self._pending_procs.pop(worker_id, None)
                wtype = self._pending_types.pop(worker_id, "cpu")
                self._starting_workers[wtype] = max(
                    0, self._starting_workers[wtype] - 1
                )
                consecutive_failures += 1
                log = os.path.join(
                    self.session_dir, "logs", f"worker-{worker_id.hex()[:8]}.log"
                )
                detail = ""
                try:
                    with open(log, "r") as f:
                        detail = f.read()[-2000:]
                except OSError:
                    pass
                sys.stderr.write(
                    f"[ray_tpu] worker {worker_id.hex()[:8]} exited during "
                    f"startup (code {proc.returncode}). Log tail:\n{detail}\n"
                )
                if consecutive_failures >= 3:
                    # Workers cannot start at all: fail queued work loudly.
                    while self._ready:
                        rec = self._ready.popleft()
                        self._fail_task(
                            rec,
                            TaskError(
                                None,
                                rec.spec.name,
                                f"worker processes fail to start; last log:\n"
                                f"{detail}",
                            ),
                        )
                else:
                    self._schedule()
            if self._workers:
                consecutive_failures = 0

    def _call(self, coro):
        """Run a coroutine on the loop from a foreign thread."""
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    def call_sync(self, coro, timeout: Optional[float] = None):
        return self._call(coro).result(timeout)

    # ------------------------------------------------------- worker lifecycle

    def _spawn_worker(self, worker_type: str = "cpu"):
        """Synchronous spawn entry: reserves the starting-worker slot
        immediately so back-to-back scheduler passes can't over-spawn."""
        self._starting_workers[worker_type] += 1
        asyncio.ensure_future(self._spawn_worker_async(worker_type))

    async def _spawn_worker_async(self, worker_type: str = "cpu") -> WorkerID:
        worker_id = WorkerID.from_random()
        log_path = os.path.join(self.session_dir, "logs")
        os.makedirs(log_path, exist_ok=True)
        out = open(os.path.join(log_path, f"worker-{worker_id.hex()[:8]}.log"), "wb")
        env = dict(os.environ)
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_NODE_SOCKET"] = self.socket_path
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        env["RAY_TPU_WORKER_TYPE"] = worker_type
        if self.arena_name:
            env["RAY_TPU_ARENA"] = self.arena_name
        # Ensure the worker can import this package even when the driver was
        # launched from elsewhere with ray_tpu on sys.path but not installed.
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        existing_pp = env.get("PYTHONPATH", "")
        if pkg_root not in existing_pp.split(os.pathsep):
            env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + existing_pp if existing_pp else "")
            )
        if worker_type == "cpu":
            # CPU workers skip accelerator-runtime registration at interpreter
            # start (it costs seconds per process and the chip is exclusive);
            # only "tpu"-typed workers keep the accelerator environment.
            env.pop("PALLAS_AXON_POOL_IPS", None)
            if env.get("JAX_PLATFORMS", "") in ("", "axon", "tpu"):
                env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker_main"],
            env=env,
            stdout=out,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        out.close()
        self._stats["workers_started"] += 1
        # The handle is registered when the worker connects and registers.
        self._pending_procs[worker_id] = proc
        self._pending_types[worker_id] = worker_type
        return worker_id

    async def _handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ):
        framed = _FramedWriter(writer)
        handle: Optional[WorkerHandle] = None
        try:
            msg = await _read_frame(reader)
            if msg.get("type") != "register":
                framed.close()
                return
            worker_id = WorkerID.from_hex(msg["worker_id"])
            proc = self._pending_procs.pop(worker_id, None)
            wtype = self._pending_types.pop(worker_id, "cpu")
            handle = WorkerHandle(
                worker_id=worker_id, writer=framed, proc=proc, worker_type=wtype
            )
            self._workers[worker_id] = handle
            self._starting_workers[wtype] = max(0, self._starting_workers[wtype] - 1)
            self._idle[wtype].append(worker_id)
            await framed.send({"type": "registered", "node_id": self.node_id.hex()})
            self._schedule()
            while True:
                msg = await _read_frame(reader)
                await self._dispatch_message(handle, msg)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            if handle is not None:
                await self._on_worker_death(handle)
            framed.close()

    async def _dispatch_message(self, w: WorkerHandle, msg: Dict[str, Any]):
        mtype = msg["type"]
        w.last_active = time.monotonic()
        if mtype == "task_done":
            await self._on_task_done(w, msg)
        elif mtype == "submit":
            await self.submit_task(msg["spec"])
        elif mtype == "get_locations":
            asyncio.ensure_future(self._reply_locations(w, msg))
        elif mtype == "wait":
            asyncio.ensure_future(self._reply_wait(w, msg))
        elif mtype == "put":
            await self.put_object(msg["object_id"], msg["loc"], msg.get("refs", 1))
        elif mtype == "add_refs":
            for oid in msg["object_ids"]:
                self.directory.add_ref(oid)
        elif mtype == "remove_refs":
            for oid, count in msg["counts"].items():
                self._remove_ref(oid, count)
        elif mtype == "fetch_function":
            await w.writer.send(
                {
                    "type": "reply",
                    "msg_id": msg["msg_id"],
                    "blob": self._functions.get(msg["function_id"]),
                }
            )
        elif mtype == "register_function":
            self._functions[msg["function_id"]] = msg["blob"]
        elif mtype == "blocked":
            self._on_worker_blocked(w)
        elif mtype == "unblocked":
            self._on_worker_unblocked(w)
        elif mtype == "kv":
            await self._handle_kv(w, msg)
        elif mtype == "actor_exit":
            await self._on_actor_graceful_exit(w, msg)
        elif mtype == "kill_actor":
            await self.kill_actor(msg["actor_id"], msg.get("no_restart", True))
        elif mtype == "cancel_task":
            await self.cancel_task(msg["task_id"], msg.get("force", False))
        elif mtype == "get_named_actor":
            spec = await self.get_named_actor(msg["name"])
            await w.writer.send(
                {"type": "reply", "msg_id": msg["msg_id"], "spec": spec}
            )
        elif mtype == "ping":
            await w.writer.send({"type": "reply", "msg_id": msg["msg_id"]})
        else:
            raise RuntimeError(f"unknown message type {mtype}")

    async def _on_worker_death(self, w: WorkerHandle):
        if w.state == "dead":
            return
        prev_state = w.state
        w.state = "dead"
        self._workers.pop(w.worker_id, None)
        try:
            self._idle[w.worker_type].remove(w.worker_id)
        except ValueError:
            pass
        if w.actor_id is not None:
            await self._on_actor_worker_death(w)
        elif w.current is not None:
            record = w.current
            w.current = None
            if record.resources_held:
                self.node_resources.release(record.spec.resources)
                record.resources_held = False
            if record.state == "cancelled":
                pass
            elif record.spec.retries_left > 0:
                record.spec.retries_left -= 1
                record.state = "ready"
                record.worker_id = None
                self._stats["tasks_retried"] += 1
                self._ready.append(record)
            else:
                self._fail_task(record, WorkerCrashedError(record.spec.name))
        elif prev_state in ("busy", "blocked"):
            pass
        if w.proc is not None and w.proc.poll() is None:
            try:
                w.proc.terminate()
            except Exception:
                pass
        self._schedule()

    # ------------------------------------------------------------- scheduling

    async def submit_task(self, spec: TaskSpec):
        """Entry point for both driver and nested worker submissions
        (ref analogue: ClusterTaskManager::QueueAndScheduleTask)."""
        self._stats["tasks_submitted"] += 1
        record = TaskRecord(spec=spec)
        self._tasks[spec.task_id] = record
        for oid in spec.return_ids():
            # Return slots exist in the directory from submission time so
            # consumers can hold refs before the task runs.
            self.directory.add(oid, InlineLocation(b""), initial_refs=0)
        # Pin dependencies for the task's lifetime so owners dropping their
        # refs mid-flight cannot free an argument (ref analogue: submitted
        # task references in ReferenceCounter).
        for oid in spec.dependency_ids():
            self.directory.add_ref(oid)
        if spec.task_type == TaskType.ACTOR_CREATION_TASK:
            # Register the actor synchronously (so method calls submitted
            # right after creation can route/queue), but never block the
            # submitter on placement.
            self._register_actor(record)
            return
        if spec.task_type == TaskType.ACTOR_TASK:
            self._route_actor_task(record)
            return
        missing = {oid for oid in spec.dependency_ids() if oid not in self._sealed}
        if missing:
            record.state = "waiting"
            self._waiting[spec.task_id] = (record, missing)
            for oid in missing:
                self._dep_index.setdefault(oid, set()).add(spec.task_id)
        else:
            record.state = "ready"
            self._ready.append(record)
        self._schedule()

    def _schedule(self):
        """Dispatch ready tasks to idle workers while resources allow
        (ref analogue: LocalTaskManager::DispatchScheduledTasksToWorkers)."""
        if self._shutdown:
            return
        # One bounded pass over the queue: dispatch everything that fits,
        # skip (in order) what doesn't — a task waiting on a busy resource
        # class must not head-of-line-block other resource classes (ref
        # analogue: ClusterTaskManager keeps per-scheduling-class queues).
        deferred: Deque[TaskRecord] = deque()
        spawn_needed: Set[str] = set()
        while self._ready:
            record = self._ready.popleft()
            if record.state == "cancelled":
                continue
            if not self.node_resources.can_fit(record.spec.resources):
                if not self.node_resources.is_feasible(record.spec.resources):
                    self._fail_task(
                        record,
                        TaskError(
                            None,
                            record.spec.name,
                            f"infeasible resource request "
                            f"{record.spec.resources.to_dict()} on node with "
                            f"{self.node_resources.total.to_dict()}",
                        ),
                    )
                    continue
                deferred.append(record)
                continue
            wtype = _task_worker_type(record.spec)
            worker = self._take_idle_worker(wtype)
            if worker is None:
                spawn_needed.add(wtype)
                deferred.append(record)
                continue
            self.node_resources.acquire(record.spec.resources)
            record.resources_held = True
            record.state = "running"
            record.worker_id = worker.worker_id
            worker.state = "busy"
            worker.current = record
            asyncio.ensure_future(self._send_execute(worker, record.spec))
        self._ready = deferred
        for wtype in spawn_needed:
            self._maybe_spawn_worker(wtype)

    def _take_idle_worker(self, worker_type: str = "cpu") -> Optional[WorkerHandle]:
        pool = self._idle[worker_type]
        while pool:
            wid = pool.popleft()
            w = self._workers.get(wid)
            if w is not None and w.state == "idle":
                return w
        return None

    def _num_starting(self) -> int:
        return sum(self._starting_workers.values())

    def _maybe_spawn_worker(self, worker_type: str = "cpu"):
        """Spawn workers demand-driven but bounded by schedulable slots:
        more worker processes than CPU slots can dispatch is pure thrash
        (ref analogue: worker_pool.h PopWorker-triggered starts bounded by
        maximum_startup_concurrency)."""
        demand = sum(
            1 for r in self._ready if _task_worker_type(r.spec) == worker_type
        )
        if demand == 0:
            return
        capacity = len(self._workers) + self._num_starting()
        if capacity >= self.config.max_workers:
            return
        cpu_total = max(1, int(self.node_resources.total.get(CPU)))
        n_blocked = sum(1 for w in self._workers.values() if w.state == "blocked")
        # Blocked workers released their CPU, so extra tasks may run.
        want = min(demand, cpu_total + n_blocked)
        n_idle = len(self._idle[worker_type])
        usable = n_idle + self._starting_workers[worker_type]
        if usable < want:
            self._spawn_worker(worker_type)

    async def _send_execute(self, worker: WorkerHandle, spec: TaskSpec):
        blob = None
        if spec.function_id not in worker.known_functions:
            blob = self._functions.get(spec.function_id)
            worker.known_functions.add(spec.function_id)
        try:
            await worker.writer.send(
                {"type": "execute", "spec": spec, "function_blob": blob}
            )
        except Exception:
            await self._on_worker_death(worker)

    async def _on_task_done(self, w: WorkerHandle, msg: Dict[str, Any]):
        task_id: TaskID = msg["task_id"]
        record = self._tasks.get(task_id)
        results: List[Tuple[ObjectID, Location]] = msg["results"]
        if record is None:
            return
        for oid, loc in results:
            self._seal_object(oid, loc)
        if msg.get("failed"):
            self._stats["tasks_failed"] += 1
            record.state = "failed"
        else:
            self._stats["tasks_finished"] += 1
            record.state = "finished"
        # Creation-task deps stay pinned while the actor may restart (the
        # creation spec re-executes with the same arguments). Terminal
        # normal/actor-task records are dropped to keep the head's memory
        # bounded (the spec holds serialized args).
        if record.spec.task_type != TaskType.ACTOR_CREATION_TASK:
            self._unpin_deps(record)
            self._tasks.pop(task_id, None)
        elif msg.get("failed"):
            self._unpin_deps(record)
        if w.actor_id is not None:
            info = self._actors.get(w.actor_id)
            if info is not None:
                info.inflight.pop(task_id, None)
                if record.spec.task_type == TaskType.ACTOR_CREATION_TASK:
                    if msg.get("failed"):
                        info.state = "dead"
                        info.death_cause = "actor constructor failed"
                        info.restarts_left = 0
                        self._fail_actor_queue(info)
                        if info.name:
                            self._named_actors.pop(info.name, None)
                        await self.kill_actor(info.actor_id)
                    else:
                        info.state = "alive"
                        self._flush_actor_queue(info)
        else:
            if record.resources_held:
                self.node_resources.release(record.spec.resources)
                record.resources_held = False
            w.current = None
            if w.state != "dead":
                w.state = "idle"
                self._idle[w.worker_type].append(w.worker_id)
        self._schedule()

    def _seal_object(self, oid: ObjectID, loc: Location):
        existing = self.directory.lookup(oid)
        if existing is not None and oid in self._sealed:
            return
        if existing is None:
            self.directory.add(oid, loc, initial_refs=0)
        else:
            self.directory.seal_over_placeholder(oid, loc)
        self._sealed.add(oid)
        ev = self._seal_events.pop(oid, None)
        if ev is not None:
            ev.set()
        waiters = self._dep_index.pop(oid, None)
        if waiters:
            for tid in waiters:
                entry = self._waiting.get(tid)
                if entry is None:
                    continue
                rec, missing = entry
                missing.discard(oid)
                if not missing:
                    del self._waiting[tid]
                    rec.state = "ready"
                    self._ready.append(rec)
            self._schedule()

    def _unpin_deps(self, record: TaskRecord):
        if record.deps_unpinned:
            return
        record.deps_unpinned = True
        for oid in record.spec.dependency_ids():
            self.directory.remove_ref(oid)

    def _fail_task(self, record: TaskRecord, error: TaskError):
        record.state = "failed"
        self._stats["tasks_failed"] += 1
        self._unpin_deps(record)
        if record.spec.task_type != TaskType.ACTOR_CREATION_TASK:
            self._tasks.pop(record.spec.task_id, None)
        try:
            from .serialization import serialize

            blob = serialize(error).to_bytes()
        except Exception:
            from .serialization import serialize

            blob = serialize(
                TaskError(None, record.spec.name, "unserializable failure")
            ).to_bytes()
        for oid in record.spec.return_ids():
            self._seal_object(oid, InlineLocation(blob))

    # ------------------------------------------------------------------ actors

    def _register_actor(self, record: TaskRecord):
        spec = record.spec
        info = ActorInfo(
            actor_id=spec.actor_id,
            creation_spec=spec,
            restarts_left=spec.max_restarts,
            name=spec.name,
        )
        if spec.name:
            if spec.name in self._named_actors:
                self._fail_task(
                    record,
                    TaskError(None, spec.name, f"actor name {spec.name!r} taken"),
                )
                return
            self._named_actors[spec.name] = spec.actor_id
        self._actors[spec.actor_id] = info
        asyncio.ensure_future(self._place_actor(info, record))

    async def _place_actor(self, info: ActorInfo, record: TaskRecord):
        spec = info.creation_spec
        if not self.node_resources.is_feasible(spec.resources):
            self._fail_task(
                record,
                TaskError(
                    None, spec.name, f"infeasible actor resources "
                    f"{spec.resources.to_dict()}"
                ),
            )
            info.state = "dead"
            return
        wtype = _task_worker_type(spec)
        # Atomically acquire resources (acquire() both checks and takes, so
        # two concurrently-placing actors can't share an exclusive resource),
        # then wait for a worker without blocking the loop.
        while not self.node_resources.acquire(spec.resources):
            await asyncio.sleep(0.01)
            if self._shutdown:
                return
        worker = self._take_idle_worker(wtype)
        while worker is None:
            self._maybe_spawn_worker_for_actor(wtype)
            await asyncio.sleep(0.01)
            if self._shutdown:
                self.node_resources.release(spec.resources)
                return
            worker = self._take_idle_worker(wtype)
        worker.state = "actor"
        worker.actor_id = spec.actor_id
        info.worker_id = worker.worker_id
        record.state = "running"
        record.worker_id = worker.worker_id
        record.resources_held = True
        info.inflight[spec.task_id] = record
        self._stats["actors_created"] += 1
        # The actor transitions to "alive" (or "dead") in _on_task_done when
        # the creation task reports back.
        await self._send_execute(worker, spec)

    def _maybe_spawn_worker_for_actor(self, worker_type: str = "cpu"):
        capacity = len(self._workers) + self._num_starting()
        if capacity < self.config.max_workers and not self._idle[worker_type] \
                and self._starting_workers[worker_type] == 0:
            self._spawn_worker(worker_type)

    def _route_actor_task(self, record: TaskRecord):
        spec = record.spec
        info = self._actors.get(spec.actor_id)
        if info is None or info.state == "dead":
            cause = info.death_cause if info else "actor not found"
            self._fail_task(record, ActorDiedError(spec.name, cause))
            return
        if info.state in ("pending", "restarting"):
            info.queued.append(spec)
            record.state = "queued"
            return
        self._forward_actor_task(info, record)

    def _forward_actor_task(self, info: ActorInfo, record: TaskRecord):
        worker = self._workers.get(info.worker_id)
        if worker is None:
            info.queued.append(record.spec)
            return
        record.state = "running"
        record.worker_id = worker.worker_id
        info.inflight[record.spec.task_id] = record
        asyncio.ensure_future(self._send_execute(worker, record.spec))

    def _flush_actor_queue(self, info: ActorInfo):
        while info.queued:
            spec = info.queued.popleft()
            record = self._tasks.get(spec.task_id)
            if record is None or record.state == "cancelled":
                continue
            self._forward_actor_task(info, record)

    def _fail_actor_queue(self, info: ActorInfo, cause: str = "actor died"):
        for spec in info.queued:
            rec = self._tasks.get(spec.task_id)
            if rec is not None:
                self._fail_task(rec, ActorDiedError(spec.name, cause))
        info.queued.clear()

    async def _on_actor_worker_death(self, w: WorkerHandle):
        info = self._actors.get(w.actor_id)
        if info is None:
            return
        creation_record = self._tasks.get(info.creation_spec.task_id)
        if creation_record is not None and creation_record.resources_held:
            self.node_resources.release(info.creation_spec.resources)
            creation_record.resources_held = False
        graceful = getattr(w, "_graceful_exit", False)
        cause = "graceful exit" if graceful else "actor worker process died"
        inflight = list(info.inflight.values())
        info.inflight.clear()
        # A creation task that never reported back counts as failed.
        creation_pending = any(
            rec.spec.task_type == TaskType.ACTOR_CREATION_TASK for rec in inflight
        )
        if info.state == "dead":
            return
        if not graceful and info.restarts_left != 0:
            info.state = "restarting"
            if info.restarts_left > 0:
                info.restarts_left -= 1
            info.restart_count += 1
            # Actor tasks are NOT retried by default (ref: max_task_retries=0
            # in the reference); interrupted calls fail with ActorDiedError
            # unless they carry retries, in which case they resubmit in order.
            for rec in reversed(inflight):
                if rec.spec.task_type != TaskType.ACTOR_TASK:
                    continue
                if rec.spec.retries_left > 0:
                    rec.spec.retries_left -= 1
                    info.queued.appendleft(rec.spec)
                else:
                    self._fail_task(
                        rec, ActorDiedError(rec.spec.name, "actor restarting")
                    )
            new_record = TaskRecord(spec=info.creation_spec)
            asyncio.ensure_future(self._restart_actor(info, new_record))
        else:
            info.state = "dead"
            info.death_cause = cause
            if creation_pending and creation_record is not None:
                self._fail_task(
                    creation_record, ActorDiedError(info.creation_spec.name, cause)
                )
            for rec in inflight:
                if rec.spec.task_type == TaskType.ACTOR_TASK:
                    self._fail_task(rec, ActorDiedError(rec.spec.name, cause))
            self._fail_actor_queue(info, cause)
            if creation_record is not None:
                self._unpin_deps(creation_record)
            if info.name:
                self._named_actors.pop(info.name, None)

    async def _restart_actor(self, info: ActorInfo, record: TaskRecord):
        # Re-run the creation task on a fresh worker (ref analogue:
        # GcsActorManager::RestartActor).
        spec = info.creation_spec
        self._tasks[spec.task_id] = record
        ev = self._seal_events.get(spec.return_ids()[0])
        if ev is not None:
            ev.clear()
        self._sealed.discard(spec.return_ids()[0])
        await self._place_actor(info, record)

    async def _on_actor_graceful_exit(self, w: WorkerHandle, msg):
        w._graceful_exit = True

    async def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        info = self._actors.get(actor_id)
        if info is None:
            return
        if no_restart:
            info.restarts_left = 0
        worker = self._workers.get(info.worker_id) if info.worker_id else None
        if worker is not None:
            try:
                await worker.writer.send({"type": "kill"})
            except Exception:
                pass
            if worker.proc is not None:
                try:
                    worker.proc.kill()
                except Exception:
                    pass

    async def get_named_actor(self, name: str) -> Optional[TaskSpec]:
        actor_id = self._named_actors.get(name)
        if actor_id is None:
            return None
        return self._actors[actor_id].creation_spec

    # ---------------------------------------------------------------- objects

    async def put_object(self, object_id: ObjectID, loc: Location, refs: int = 1):
        self.directory.add(object_id, loc, initial_refs=refs)
        self._seal_object(object_id, loc)

    async def get_locations(
        self, object_ids: List[ObjectID], timeout: Optional[float] = None
    ) -> List[Tuple[ObjectID, Location]]:
        events = []
        for oid in object_ids:
            if oid not in self._sealed:
                if self.directory.lookup(oid) is None:
                    # Never registered or already freed: waiting would hang
                    # forever. (Nested refs inside serialized args are not
                    # pinned by the control plane yet — full borrower
                    # accounting is future work; this turns the silent hang
                    # into a loud error.)
                    from .exceptions import ObjectLostError

                    raise ObjectLostError(
                        f"object {oid.hex()} is unknown or has been freed; "
                        "if it was only referenced from inside a container "
                        "argument, keep a live ObjectRef to it"
                    )
                events.append(self._seal_events.setdefault(oid, asyncio.Event()))
        if events:
            waiters = [ev.wait() for ev in events if not ev.is_set()]
            if waiters:
                await asyncio.wait_for(asyncio.gather(*waiters), timeout)
        return [(oid, self.directory.lookup(oid)) for oid in object_ids]

    async def wait_objects(
        self,
        object_ids: List[ObjectID],
        num_returns: int,
        timeout: Optional[float],
    ) -> List[ObjectID]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ready = [oid for oid in object_ids if oid in self._sealed]
            if len(ready) >= num_returns:
                return ready
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return ready
            # Event-driven: wake when any unsealed object seals.
            pending = [
                self._seal_events.setdefault(oid, asyncio.Event())
                for oid in object_ids
                if oid not in self._sealed
            ]
            tasks = [asyncio.ensure_future(ev.wait()) for ev in pending]
            try:
                await asyncio.wait(
                    tasks,
                    timeout=remaining,
                    return_when=asyncio.FIRST_COMPLETED,
                )
            finally:
                for t in tasks:
                    t.cancel()

    def _remove_ref(self, object_id: ObjectID, count: int = 1):
        self.directory.remove_ref(object_id, count)

    async def _gc_loop(self):
        grace = self.config.gc_grace_period_s
        while not self._shutdown:
            await asyncio.sleep(min(1.0, grace / 2))
            for oid, loc in self.directory.collect_garbage(grace):
                self._sealed.discard(oid)
                self._seal_events.pop(oid, None)
                _free_location(loc)
            # Reclaim arena blocks stuck in pending-delete because a pinning
            # reader died without unpinning (ref analogue: plasma client
            # disconnect releasing its objects).
            arena = current_arena()
            if arena is not None:
                try:
                    arena.purge_dead_pins()
                except Exception:
                    pass

    async def _reply_locations(self, w: WorkerHandle, msg):
        try:
            locs = await self.get_locations(msg["object_ids"], msg.get("timeout"))
            await w.writer.send(
                {"type": "reply", "msg_id": msg["msg_id"], "locations": locs}
            )
        except asyncio.TimeoutError:
            await w.writer.send(
                {"type": "reply", "msg_id": msg["msg_id"], "timeout": True}
            )
        except Exception as e:  # connection gone etc.
            try:
                await w.writer.send(
                    {"type": "reply", "msg_id": msg["msg_id"], "error": str(e)}
                )
            except Exception:
                pass

    async def _reply_wait(self, w: WorkerHandle, msg):
        ready = await self.wait_objects(
            msg["object_ids"], msg["num_returns"], msg.get("timeout")
        )
        await w.writer.send({"type": "reply", "msg_id": msg["msg_id"], "ready": ready})

    # --------------------------------------------------------------------- kv

    async def _handle_kv(self, w: WorkerHandle, msg):
        op = msg["op"]
        out: Dict[str, Any] = {"type": "reply", "msg_id": msg["msg_id"]}
        if op == "put":
            overwrite = msg.get("overwrite", True)
            if not overwrite and msg["key"] in self._kv:
                out["added"] = False
            else:
                self._kv[msg["key"]] = msg["value"]
                out["added"] = True
        elif op == "get":
            out["value"] = self._kv.get(msg["key"])
        elif op == "del":
            out["deleted"] = self._kv.pop(msg["key"], None) is not None
        elif op == "keys":
            prefix = msg.get("prefix", "")
            out["keys"] = [k for k in self._kv if k.startswith(prefix)]
        await w.writer.send(out)

    def kv_put(self, key: str, value: bytes, overwrite: bool = True) -> bool:
        async def _put():
            if not overwrite and key in self._kv:
                return False
            self._kv[key] = value
            return True

        return self.call_sync(_put())

    def kv_get(self, key: str) -> Optional[bytes]:
        async def _get():
            return self._kv.get(key)

        return self.call_sync(_get())

    # ----------------------------------------------------------- cancellation

    async def cancel_task(self, task_id: TaskID, force: bool = False):
        record = self._tasks.get(task_id)
        if record is None or record.state in ("finished", "failed", "cancelled"):
            return
        if record.state in ("waiting", "ready", "queued"):
            prev = record.state
            record.state = "cancelled"
            if prev == "waiting":
                self._waiting.pop(task_id, None)
            self._fail_task(record, TaskCancelledError(record.spec.name))
            record.state = "cancelled"
        elif record.state == "running" and force:
            worker = self._workers.get(record.worker_id)
            record.state = "cancelled"
            self._fail_task(record, TaskCancelledError(record.spec.name))
            record.state = "cancelled"
            if worker is not None and worker.proc is not None:
                try:
                    worker.proc.kill()
                except Exception:
                    pass

    # ------------------------------------------------------ functions / stats

    async def register_function(self, function_id: str, blob: bytes):
        self._functions[function_id] = blob

    async def stats(self) -> Dict[str, Any]:
        return {
            **self._stats,
            "num_workers": len(self._workers),
            "num_actors_alive": sum(
                1 for a in self._actors.values() if a.state == "alive"
            ),
            "object_store_used_bytes": self.directory.used_bytes,
            "num_objects": self.directory.num_objects(),
            "available_resources": self.node_resources.available.to_dict(),
            "total_resources": self.node_resources.total.to_dict(),
            "pending_tasks": len(self._ready) + len(self._waiting),
        }

    # ---------------------------------------------------------------- blocked

    def _on_worker_blocked(self, w: WorkerHandle):
        """Worker blocked in get(): release its task's resources so other
        tasks can run (ref analogue: NodeManager::HandleNotifyWorkerBlocked +
        the CPU release in local_task_manager)."""
        if w.state == "busy" and w.current is not None and w.current.resources_held:
            self.node_resources.release(w.current.spec.resources)
            w.current.resources_held = False
            w.state = "blocked"
            self._schedule()

    def _on_worker_unblocked(self, w: WorkerHandle):
        if w.state == "blocked" and w.current is not None:
            # Oversubscribe if necessary: clamp availability at zero rather
            # than deadlocking (the reference behaves the same way when a
            # blocked worker resumes).
            res = w.current.spec.resources
            if not self.node_resources.acquire(res):
                avail = self.node_resources.available
                fixed = dict(avail._amounts)
                for k, v in res._amounts.items():
                    fixed[k] = max(0, fixed.get(k, 0) - v)
                from .resources import ResourceSet as _RS

                self.node_resources.available = _RS(_fixed=fixed)
            w.current.resources_held = True
            w.state = "busy"

    # --------------------------------------------------------------- shutdown

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True

        async def _stop():
            if getattr(self, "_gc_task", None) is not None:
                self._gc_task.cancel()
            if getattr(self, "_health_task", None) is not None:
                self._health_task.cancel()
            for w in list(self._workers.values()):
                try:
                    await asyncio.wait_for(w.writer.send({"type": "kill"}), 1.0)
                except Exception:
                    pass
            if self._server is not None:
                self._server.close()

        try:
            self._call(_stop()).result(timeout=5)
        except Exception:
            pass
        for w in list(self._workers.values()):
            if w.proc is not None:
                try:
                    w.proc.terminate()
                except Exception:
                    pass
        for w in list(self._workers.values()):
            if w.proc is not None:
                try:
                    w.proc.wait(timeout=2)
                except Exception:
                    try:
                        w.proc.kill()
                    except Exception:
                        pass
        for proc in getattr(self, "_pending_procs", {}).values():
            try:
                proc.terminate()
            except Exception:
                pass
        # Unlink all remaining shm segments we know about, then the arena.
        for oid in list(self.directory._entries):
            _free_location(self.directory._entries.get(oid))
        if self.arena_name:
            shutdown_arena(unlink=True)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
