"""Split-brain fencing: shared metric surface + fence-state helpers.

Ref analogue: the reference fences node death instead of merely
observing it — the GCS stamps membership changes (``NotifyGCSRestart``,
the node-death broadcast in gcs_node_manager) and a raylet that learns
it was declared dead kills itself rather than rejoining as a zombie.
Here the mechanism spans four layers:

- **Membership epochs** (core/gcs.py): a monotonic cluster epoch bumped
  on every node death and registration, persisted in the GCS snapshot.
  Every node-death broadcast doubles as a ``node_fenced(node, epoch)``
  fence decision.
- **Incarnations**: each node registration and each actor start/restart
  gets a GCS-assigned incarnation. ``get_actor_direct`` resolution
  returns the actor incarnation and the direct hello/welcome handshake
  carries and validates it — a caller holding a cached endpoint to a
  stale incarnation is refused and re-resolves through the NM.
- **Fence broadcast** (core/node_manager.py): receiving NMs tear down
  direct channels and peer/data pools to the fenced node, park
  in-flight direct calls into the exactly-once NM replay path (where
  calls bound to the fenced incarnation are REFUSED, never re-executed
  into the new incarnation), and drop subsequent peer frames from the
  fenced incarnation.
- **Zombie self-termination** (core/node_manager.py): a node whose
  re-register reply says "you were declared dead at epoch E" kills its
  workers (the stale actor incarnations die with them), skips its
  sealed-object republish, and rejoins as a fresh incarnation with
  empty state.

The metrics below are the fence plane's documented surface
(tools/rtlint validates names/kinds); they are declared here — one
light module importable from the GCS, NM, worker and runtime sides —
so every layer increments the same series.
"""

from __future__ import annotations

from ..util.metrics import Counter as _Counter

# Fence decisions observed by this process: the GCS declaring a node
# dead at an epoch (kind="node_fenced"), an NM tearing down channels on
# receipt of the broadcast (kind="channel_teardown"), a peer frame from
# a fenced incarnation dropped (kind="peer_refused").
FENCE_EVENTS = _Counter(
    "ray_tpu_fence_events_total",
    "Membership-fence decisions: node fenced at an epoch, fence-driven "
    "channel teardowns, peer frames refused from fenced incarnations",
    tag_keys=("kind",),
)

# Calls refused because they crossed an incarnation boundary: a
# direct-channel replay bound to a fenced incarnation refused at the NM
# (where="replay"), or a direct hello naming a stale actor incarnation
# refused at the worker (where="hello").
FENCE_REFUSED = _Counter(
    "ray_tpu_fence_refused_calls_total",
    "Actor calls refused at an incarnation boundary instead of risking "
    "double execution (replay onto a restarted actor, stale hello)",
    tag_keys=("where",),
)

# Zombie self-terminations: this node learned it was declared dead
# while partitioned and killed its workers before rejoining fresh.
ZOMBIE_KILLS = _Counter(
    "ray_tpu_fence_zombie_kills_total",
    "Times this node self-terminated its workers after learning it was "
    "declared dead at an earlier membership epoch (zombie fencing)",
)

EVENT_NODE_FENCED = FENCE_EVENTS.with_tags(kind="node_fenced")
EVENT_CHANNEL_TEARDOWN = FENCE_EVENTS.with_tags(kind="channel_teardown")
EVENT_PEER_REFUSED = FENCE_EVENTS.with_tags(kind="peer_refused")
REFUSED_REPLAY = FENCE_REFUSED.with_tags(where="replay")
REFUSED_HELLO = FENCE_REFUSED.with_tags(where="hello")
