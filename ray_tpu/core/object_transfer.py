"""Inter-node object transfer: striped data plane + chunked control fallback.

Plays the role of the reference's object manager data plane (ref:
src/ray/object_manager/object_manager.h Push/Pull over
object_manager.proto:61). Two paths:

**Striped data plane (default).** Object payload moves over a small pool
of raw stream sockets per peer (core/data_channel.py,
``transfer_streams_per_peer``), opened lazily beside the control channel.
One request advertises ``(oid, offset, length)`` and the source streams
the whole range back in a length-prefixed binary frame — no pickle, no
per-chunk round trips. Large pulls are striped across the pool so every
stream stays busy, the server sends straight from the store's sealed
memoryview (``sendall`` on slices, zero staging copies) and the receiver
``recv_into``s directly into the ``ObjectWriter``'s pre-allocated
shared-memory view. The control socket carries only the initial locate
round trip, so peer RPCs keep flowing while gigabytes move.

**Control-plane chunk protocol (fallback).** The previous pickled
request/response chunks (``pull_chunk``), kept for mixed-version peers,
dead data servers and degraded networks: any data-channel error fails the
pull over to this path (and emits a WARNING OBJECT_STORE event) instead
of failing the object.

Admission control is unchanged (ref: pull_manager.h:52 bundles admitted
against available memory): the puller bounds concurrent large pulls and
reserves whole-object bytes against store capacity before any socket
opens; the server bounds concurrent range reads. Small objects still
answer inline on the control channel in one round trip.

Dedup notes: per-object pull dedup lives in the node manager's ``_pulls``
future table (one pull per object per node, concurrent requesters share
it); a broadcast (N nodes pulling one object) therefore issues exactly
one pull per receiving node — the role of the reference's PushManager
dedup.
"""

from __future__ import annotations

import asyncio
import functools
import pickle
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from ..util import data_obs
from ..util import events as cluster_events
from ..util.metrics import Counter, Gauge, Histogram
from .data_channel import DataChannelError, DataChannelPool, plan_stripes
from .ids import ObjectID
from .object_store import Location
from .rpc import Method, ServiceRegistry, ServiceSpec

# Observability riders on the PR 1-3 planes: byte/second series per
# direction (pull|serve) and plane (stream|control), per-peer in-flight
# gauges, and fallback counters. Rendered by `rtpu metrics` via the
# util/metrics KV pipeline; tools/check_metric_names.py lints the names.
TRANSFER_BYTES = Counter(
    "ray_tpu_object_transfer_bytes_total",
    "Object payload bytes moved between nodes.",
    tag_keys=("node", "direction", "plane"),
)
TRANSFER_SECONDS = Histogram(
    "ray_tpu_object_transfer_seconds",
    "Wall seconds per completed large-object transfer.",
    boundaries=[0.005, 0.02, 0.1, 0.5, 2.0, 10.0, 60.0],
    tag_keys=("node", "direction", "plane"),
)
TRANSFER_INFLIGHT = Gauge(
    "ray_tpu_object_transfer_inflight",
    "Large-object pulls currently streaming, per source peer.",
    tag_keys=("node", "peer"),
)
TRANSFER_FALLBACKS = Counter(
    "ray_tpu_object_transfer_fallbacks_total",
    "Pulls that fell back from the striped data plane to the "
    "control-plane chunk protocol.",
    tag_keys=("node",),
)

# Typed peer-service boundary (ref analogue: ObjectManagerService in
# object_manager.proto): the control-plane half of the transfer protocol,
# validated at dispatch so malformed peer frames fail loudly at the
# boundary instead of as KeyErrors inside a handler.
TRANSFER_SERVICE = ServiceSpec("ObjectTransferService", (
    Method("pull_object",
           request=(("object_id", "id"),
                    ("max_unchunked", "int", False, 0)),
           reply=(("data", "any"), ("chunked", "bool"), ("size", "int"),
                  ("data_port", "int"), ("error", "str"))),
    Method("pull_chunk",
           request=(("object_id", "id"), ("offset", "int"),
                    ("length", "int")),
           reply=(("data", "any"), ("error", "str"))),
))


class TransferError(Exception):
    """Data-plane failure; the caller maps it to object recovery."""


class ProgressDeadline:
    """Admission deadline that RESETS whenever the watched meter moves
    toward admission. The old fixed deadline counted from request
    arrival, so a big pull queued behind a slow-but-live drain (bytes
    visibly being freed the whole time) was spuriously failed at
    ``pull_admission_timeout_s`` even though it was seconds from
    admission; the timeout now only fires after a full window with NO
    progress (ref: pull_manager.h's retry timer resetting on activity).
    """

    def __init__(self, timeout_s: float, clock=time.monotonic):
        self._timeout = timeout_s
        self._clock = clock
        self._deadline = clock() + timeout_s
        self._best: Optional[float] = None

    def note(self, meter: float) -> None:
        """Feed the progress meter (here: free store bytes); any
        improvement over the current baseline restarts the timeout
        window. A DROP lowers the baseline without resetting: when a
        sibling pull admits and consumes the freed bytes, later
        freeing must count as fresh progress, not be hidden under the
        all-time peak."""
        if self._best is None or meter > self._best:
            self._best = meter
            self._deadline = self._clock() + self._timeout
        elif meter < self._best:
            self._best = meter

    @property
    def expired(self) -> bool:
        return self._clock() > self._deadline


class ObjectTransfer:
    """Both halves of the transfer protocol, owned by the node manager."""

    def __init__(self, node_manager):
        self._nm = node_manager
        cfg = node_manager.config
        self.chunk_bytes = int(cfg.object_transfer_chunk_bytes)
        self.streams_per_peer = int(cfg.transfer_streams_per_peer)
        # Puller-side admission: whole large pulls, then chunk frames.
        self._pull_slots = asyncio.Semaphore(cfg.pull_large_concurrency)
        self._chunk_slots = asyncio.Semaphore(cfg.pull_chunks_in_flight)
        # Server-side: bound concurrent control-plane chunk reads (each
        # stages one chunk_bytes buffer + an executor thread).
        self._serve_slots = asyncio.Semaphore(cfg.serve_chunks_in_flight)
        # Memory admission (ref: pull_manager.h:52 — bundles admitted
        # against available store memory): bytes reserved by in-flight
        # chunked pulls, counted against store capacity so N admitted
        # pulls can never exceed what the store can hold.
        self._inflight_bytes = 0
        self._stats_lock = threading.Lock()
        self.stats = {
            "chunks_pulled": 0, "chunks_served": 0,
            "chunked_pulls": 0, "pulls_queued_on_memory": 0,
            # Data-plane counters (stripe = one range request on one
            # stream; ranges_served counts the server side).
            "striped_pulls": 0, "fallback_pulls": 0, "ranges_served": 0,
            "bytes_pulled_stream": 0, "bytes_served_stream": 0,
        }
        # Stripe workers + fallback memmoves run here, NOT on the shared
        # default executor — a pull must never starve writer finalization
        # or spill IO of threads.
        self._io_pool = ThreadPoolExecutor(
            max_workers=max(4, self.streams_per_peer
                            * int(cfg.pull_large_concurrency) + 2),
            thread_name_prefix="rtpu-xfer",
        )
        # Lazily-opened data-channel pools, one per source peer.
        self._pools: Dict[str, DataChannelPool] = {}
        self._pools_lock = threading.Lock()
        self._inflight_peers: Dict[str, int] = {}
        self._closed = False
        # Data-obs plane (util/data_obs.py): per-pull progress records
        # feeding the stall watchdog + the (src,dst) link-bandwidth
        # matrix. None when RTPU_NO_DATA_OBS=1 — every touch point
        # treats a None tracker as a full no-op.
        self._tracker = data_obs.pull_tracker()
        # Typed dispatch for the control-plane methods (node_manager
        # routes peer pull_object/pull_chunk frames through this).
        self.rpc = ServiceRegistry()
        self.rpc.register(TRANSFER_SERVICE, self)

    # ------------------------------------------------------------ lifecycle

    def close(self):
        """Node shutdown: kill every data channel (borrowed ones too, so
        stripe workers blocked in recv error out) and the io pool."""
        self._closed = True
        with self._pools_lock:
            pools = list(self._pools.values())
            self._pools.clear()
        for pool in pools:
            pool.close()
        self._io_pool.shutdown(wait=False)

    def drop_peer(self, peer_hex: str):
        """Peer death (channel lifecycle rider): its data channels are
        dead sockets — close them so in-flight stripes fail fast to the
        (also-dead) control path and the pull surfaces ObjectLostError.
        The per-peer in-flight row is retired too (zeroed in the metrics
        KV, pruned locally) so peer churn cannot grow the gauge table
        without bound."""
        with self._pools_lock:
            pool = self._pools.pop(peer_hex, None)
        if pool is not None:
            pool.close()
        peer_tag = peer_hex[:8]
        with self._stats_lock:
            had = self._inflight_peers.pop(peer_tag, None)
        if had:
            try:
                TRANSFER_INFLIGHT.set(
                    0.0, tags={"node": self._node_tag(),
                               "peer": peer_tag}
                )
            except Exception:
                pass

    def _bump(self, key: str, n: int = 1):
        with self._stats_lock:
            self.stats[key] += n

    def _node_tag(self) -> str:
        return self._nm.node_id.hex()[:8]

    def _progress_cb(self, prog, peer_tag: str):
        """Per-recv-window byte callback for one pull: advances the
        stall-watchdog record and feeds the (src,dst) link matrix. None
        when the data-obs plane is off (callers pass it straight through
        to the channel layer, which treats None as a no-op)."""
        if prog is None:
            return None
        dst = self._node_tag()

        def _advance(n: int, _p=prog, _src=peer_tag, _dst=dst) -> None:
            _p.advance(n)
            data_obs.record_link_bytes(_src, _dst, n)

        return _advance

    def _set_inflight(self, peer_tag: str, delta: int):
        with self._stats_lock:
            cur = self._inflight_peers.get(peer_tag, 0) + delta
            self._inflight_peers[peer_tag] = max(0, cur)
            val = self._inflight_peers[peer_tag]
        try:
            TRANSFER_INFLIGHT.set(
                float(val), tags={"node": self._node_tag(),
                                  "peer": peer_tag}
            )
        except Exception:
            pass

    def inflight_by_peer(self) -> Dict[str, int]:
        with self._stats_lock:
            return {k: v for k, v in self._inflight_peers.items() if v}

    def inflight_pulls(self) -> list:
        """Progress snapshots of every in-flight pull (oid, peer, bytes
        moved, age, idle time, stall flag) — the census / `rtpu
        transfers` inflight-aging table. Empty when the data-obs plane
        is off."""
        return self._tracker.inflight() if self._tracker is not None \
            else []

    def check_stalls(self) -> None:
        """Stall-watchdog sweep, driven by the node manager's periodic
        loop: publish the live per-peer stalled gauge, and for every
        pull that JUST crossed ``transfer_stall_warn_s`` with no byte
        progress emit one deduped WARNING OBJECT_STORE event plus a
        flight-recorder record (reason "stalled_pull") joinable from
        ``rtpu trace`` — the record's trace id is the one the pull's
        data-plane spans root on. Never raises."""
        if self._tracker is None:
            return
        try:
            stall_s = float(getattr(self._nm.config,
                                    "transfer_stall_warn_s", 0.0))
        except Exception:
            stall_s = 0.0
        for p in self._tracker.sweep(stall_s):
            try:
                snap = p.snapshot()
                oid8 = p.oid[:8]
                detail = (f"moved {snap['bytes_moved']}/{snap['size']} B, "
                          f"idle {snap['idle_s']:.1f}s "
                          f"(> transfer_stall_warn_s={stall_s:g}) "
                          f"{p.detail}").strip()
                cluster_events.emit(
                    cluster_events.WARNING, cluster_events.OBJECT_STORE,
                    f"TRANSFER stalled: pull of {oid8} from peer "
                    f"{p.peer} has made no byte progress — {detail}",
                    node_id=self._nm.node_id.hex(),
                    custom_fields={"object_id": p.oid, "peer": p.peer,
                                   "bytes_moved": snap["bytes_moved"],
                                   "size": snap["size"],
                                   "idle_s": snap["idle_s"]},
                )
                from ..util import flight_recorder

                now = time.time()
                flight_recorder.observe_request(
                    f"pull:{oid8}", p.oid[:32],
                    now - snap["age_s"], now,
                    status="stalled", reason="stalled_pull",
                    detail=f"peer={p.peer} {detail}",
                    surface="data")
            # Telemetry must never fail the pulls it watches.
            except Exception:  # rtlint: disable=swallowed-failure
                pass

    # ------------------------------------------------------------- pull side

    async def pull(self, peer, oid: ObjectID) -> bytes | Location:
        """Fetch one object from ``peer``. Returns raw framed bytes for
        small objects (caller stores them) or a ready local Location for
        chunked large objects (bytes already in the store)."""
        reply = await peer.request(
            {"type": "pull_object", "object_id": oid,
             "max_unchunked": self.chunk_bytes}
        )
        data = reply.get("data")
        if data is not None:
            # Small inline answer: still link traffic for the matrix.
            try:
                data_obs.record_link_bytes(
                    peer.peer_hex[:8], self._node_tag(), len(data),
                    flush=True)
            except Exception:
                pass
            return data
        size = reply.get("size")
        if not reply.get("chunked") or size is None:
            raise TransferError(
                reply.get("error") or "object freed on source"
            )
        size = int(size)
        async with self._pull_slots:
            self._bump("chunked_pulls")
            await self._admit_bytes(size)
            t0 = time.perf_counter()
            prog = (self._tracker.start(oid.hex(), peer.peer_hex[:8],
                                        size)
                    if self._tracker is not None else None)
            try:
                loc, plane = await self._pull_into_store(
                    peer, reply, oid, size, prog
                )
            finally:
                self._inflight_bytes -= size
                if self._tracker is not None:
                    self._tracker.finish(prog)
                    data_obs.record_link_bytes(
                        peer.peer_hex[:8], self._node_tag(), 0,
                        flush=True)
            try:
                tags = {"node": self._node_tag(), "direction": "pull",
                        "plane": plane}
                TRANSFER_BYTES.inc(float(size), tags=tags)
                TRANSFER_SECONDS.observe(time.perf_counter() - t0,
                                         tags=tags)
            except Exception:
                pass
            return loc

    async def _admit_bytes(self, size: int):
        """Queue until the store can hold ``size`` more bytes (spilling
        cold objects to make room); fail cleanly when the object can
        never fit (ref: PullManager admission vs available memory)."""
        d = self._nm.directory
        cap = d.capacity_bytes
        if cap > 0 and size > cap:
            raise TransferError(
                f"object of {size} bytes exceeds the object store "
                f"capacity ({cap} bytes); it can never be pulled whole"
            )
        # NOTE: directory.used_bytes does not see a transfer's arena
        # block until finalize registers the object, so the full-size
        # reservation here is the ONLY meter for in-flight pulls (no
        # double counting while chunks land).
        if cap <= 0:
            self._inflight_bytes += size
            return
        from ..util.backoff import Backoff

        loop = self._nm._loop
        timeout_s = self._nm.config.pull_admission_timeout_s
        deadline = ProgressDeadline(timeout_s, clock=loop.time)
        # Hard backstop: progress resets are bounded — store churn
        # (siblings admitting and freeing in a cycle that never opens
        # `size` bytes) must not keep this request parked forever.
        hard_deadline = loop.time() + 10.0 * timeout_s
        wait = Backoff(base=0.02, factor=1.5, max_delay=0.25, jitter=0.0)
        queued = False
        while True:
            free = cap - d.used_bytes - self._inflight_bytes
            # Any growth in free bytes (a sibling pull finalized, a
            # spill landed) is progress: the admission window restarts
            # instead of counting from request arrival.
            deadline.note(free)
            if size <= free:
                self._inflight_bytes += size
                return
            if not queued:
                queued = True
                self._bump("pulls_queued_on_memory")
            # Ask the spill pass to free exactly what we lack — the
            # high-water trigger alone would no-op below the mark.
            self._nm._maybe_spill(need=size - max(free, 0))
            if deadline.expired or loop.time() >= hard_deadline:
                raise TransferError(
                    f"pull of {size} bytes not admitted within "
                    f"{timeout_s}s of the last progress (hard cap "
                    f"{10.0 * timeout_s}s): store full "
                    f"({d.used_bytes}/{cap} used, "
                    f"{self._inflight_bytes} in flight)"
                )
            await asyncio.sleep(wait.next_delay())

    async def _pull_into_store(self, peer, reply: Dict[str, Any],
                               oid: ObjectID, size: int, prog=None):
        """Allocate the destination block and fill it — striped data
        plane first, control-plane chunks on any data-channel failure.
        Returns ``(Location, plane)``. ``prog`` is the pull's data-obs
        progress record (None when the plane is off)."""
        store = self._nm.local_store
        loop = self._nm._loop
        writer = await loop.run_in_executor(
            None, store.create_writer, oid, size
        )
        try:
            plane = "control"
            data_port = int(reply.get("data_port") or 0)
            if data_port and self.streams_per_peer > 0 and not self._closed:
                try:
                    await self._pull_striped(peer, data_port, oid, size,
                                             writer, prog)
                    plane = "stream"
                except (DataChannelError, TransferError, OSError,
                        ConnectionError) as e:
                    # Mixed-version peer, dead data server, mid-stream
                    # reset: fall back to the chunk protocol. Offsets
                    # already landed are simply rewritten — chunk writes
                    # are idempotent.
                    self._bump("fallback_pulls")
                    try:
                        TRANSFER_FALLBACKS.inc(
                            tags={"node": self._node_tag()}
                        )
                    except Exception:
                        pass
                    cluster_events.emit(
                        cluster_events.WARNING, cluster_events.OBJECT_STORE,
                        f"TRANSFER fallback: striped pull of "
                        f"{oid.hex()[:8]} ({size} B) from peer "
                        f"{peer.peer_hex[:8]} failed ({e}); retrying over "
                        f"the control-plane chunk protocol",
                        node_id=self._nm.node_id.hex(),
                        custom_fields={"object_id": oid.hex(),
                                       "bytes": size,
                                       "peer": peer.peer_hex,
                                       "error": str(e)},
                    )
                    await self._pull_chunked_into(peer, oid, size, writer,
                                                  prog)
            else:
                await self._pull_chunked_into(peer, oid, size, writer,
                                              prog)
            loc = await loop.run_in_executor(None, writer.finalize)
            return loc, plane
        except BaseException:
            writer.abort()
            raise

    # ---- striped data plane -----------------------------------------------

    def _get_pool(self, peer, data_port: int) -> DataChannelPool:
        cfg = self._nm.config
        with self._pools_lock:
            pool = self._pools.get(peer.peer_hex)
            if pool is not None and (
                    pool.closed or pool.port != data_port
                    or pool.host != peer.host):
                # Source restarted its data server (new port) or the old
                # pool died: start fresh — recovery is automatic because
                # every pull re-learns the port from the locate reply.
                pool.close()
                pool = None
            if pool is None:
                pool = DataChannelPool(
                    peer.host, data_port, self._nm.node_id.hex(),
                    cfg.session_token,
                    max_streams=self.streams_per_peer,
                    connect_timeout=cfg.transfer_connect_timeout_s,
                    io_timeout=cfg.transfer_io_timeout_s,
                )
                self._pools[peer.peer_hex] = pool
            return pool

    def _drop_pool(self, peer_hex: str, pool: DataChannelPool):
        with self._pools_lock:
            if self._pools.get(peer_hex) is pool:
                del self._pools[peer_hex]

    async def _pull_striped(self, peer, data_port: int, oid: ObjectID,
                            size: int, writer, prog=None):
        """Stream ``[0, size)`` into the writer's shared-memory view,
        striped across the peer's data-channel pool. All socket IO runs
        on the transfer io pool; the control loop only awaits."""
        from .timeline import current_span, get_buffer, new_span_id

        pool = self._get_pool(peer, data_port)
        stripes = plan_stripes(size, self.streams_per_peer,
                               self.chunk_bytes)
        view = writer.readinto_view(0, size)
        oid_b = oid.binary()
        peer_tag = peer.peer_hex[:8]
        loop = self._nm._loop
        progress = self._progress_cb(prog, peer_tag)
        if prog is not None:
            prog.detail = f"stripes={len(stripes)} port={data_port}"
        # Data-plane span: the pull (and each stripe under it) lands in
        # the waterfall. The NM loop has no ambient request context, so
        # a pull outside any traced request roots on the object id —
        # still joinable by name from the timeline.
        pull_ctx = current_span() or (oid.hex()[:32], "")
        pull_sid = new_span_id()
        pull_t0 = time.time()
        self._set_inflight(peer_tag, +1)
        try:
            futs = [
                loop.run_in_executor(
                    self._io_pool, self._stripe_worker, pool, oid_b,
                    off, length, view, (pull_ctx[0], pull_sid), progress,
                )
                for off, length in stripes
            ]
            try:
                await asyncio.gather(*futs)
            except asyncio.CancelledError:
                # Hard abort (caller gone / shutdown): kill the pool so
                # sibling workers blocked in recv error out NOW, then
                # drain every worker before the caller may abort the
                # writer — a recv_into racing abort() would land bytes
                # in freed arena memory.
                pool.close()
                await asyncio.gather(*futs, return_exceptions=True)
                self._drop_pool(peer.peer_hex, pool)
                raise
            except BaseException:
                # One stripe failed: its worker already discarded its own
                # channel. Do NOT close the shared pool — a concurrent
                # pull from the same peer may be streaming healthily on
                # it, and collateral closes would cascade every pull onto
                # the slow fallback. Drain the sibling workers (each is
                # bounded by the io timeout) before the writer can be
                # aborted.
                await asyncio.gather(*futs, return_exceptions=True)
                raise
        finally:
            self._set_inflight(peer_tag, -1)
            view.release()
            try:
                # Record OFF the event loop: TaskEventBuffer.record may
                # inline-flush to the cluster KV, which blocks — fine on
                # an io-pool thread, a deadlock on the NM loop.
                loop.run_in_executor(self._io_pool, functools.partial(
                    get_buffer().record,
                    f"pull:{oid.hex()[:8]}", pull_t0, time.time(), "",
                    trace_id=pull_ctx[0], span_id=pull_sid,
                    parent_id=pull_ctx[1],
                ))
            # Observability must never fail the pull it observes.
            except Exception:  # rtlint: disable=swallowed-failure
                pass
        self._bump("striped_pulls")
        self._bump("bytes_pulled_stream", size)

    def _stripe_worker(self, pool: DataChannelPool, oid_b: bytes,
                       offset: int, length: int, view: memoryview,
                       span_parent=None, progress=None):
        """Executor-thread body: borrow a channel, stream one stripe
        directly into the destination view. The acquire wait is bounded
        by the IO timeout, not the connect timeout — waiting for a busy
        channel means another stripe is mid-transfer, which is
        data-volume-bound."""
        t0 = time.time()
        try:
            self._stripe_pull(pool, oid_b, offset, length, view, progress)
        finally:
            if span_parent is not None:
                try:
                    from .timeline import get_buffer, new_span_id

                    get_buffer().record(
                        f"stripe:+{offset}", t0, time.time(), "",
                        trace_id=span_parent[0],
                        span_id=new_span_id(),
                        parent_id=span_parent[1],
                    )
                # As above: a lost stripe span only blanks telemetry.
                except Exception:  # rtlint: disable=swallowed-failure
                    pass

    def _stripe_pull(self, pool: DataChannelPool, oid_b: bytes,
                     offset: int, length: int, view: memoryview,
                     progress=None):
        ch = pool.acquire(timeout=self._nm.config.transfer_io_timeout_s)
        try:
            ch.pull_range(oid_b, offset, length, view, progress=progress)
        except DataChannelError:
            was_reused = ch.reused
            pool.discard(ch)
            if not was_reused:
                raise
            # A REUSED idle channel may have been closed server-side
            # (the server's io timeout reaps idle connections): retry
            # exactly once on a fresh channel before failing the stripe
            # over to the control plane. Offsets are idempotent, so a
            # partial first attempt is simply overwritten.
            ch = pool.acquire(
                timeout=self._nm.config.transfer_io_timeout_s
            )
            try:
                ch.pull_range(oid_b, offset, length, view,
                              progress=progress)
            except BaseException:
                pool.discard(ch)
                raise
            pool.release(ch)
            return
        except BaseException:
            pool.discard(ch)
            raise
        pool.release(ch)

    # ---- control-plane fallback -------------------------------------------

    async def _pull_chunked_into(self, peer, oid: ObjectID, size: int,
                                 writer, prog=None):
        """The pre-data-plane protocol: per-chunk request/reply frames
        over the control channel, staged through the executor into the
        writer. Kept as the universal fallback."""
        loop = self._nm._loop
        chunk = self.chunk_bytes
        progress = self._progress_cb(prog, peer.peer_hex[:8])
        if prog is not None:
            prog.detail = "plane=control"
        # Executor-thread writes in flight: a cancelled fetch coroutine
        # does NOT stop its already-running threadpool write, so the
        # abort path must drain THESE, not just the tasks.
        write_futs: list = []

        async def fetch(offset: int):
            length = min(chunk, size - offset)
            async with self._chunk_slots:
                reply = await peer.request(
                    {"type": "pull_chunk", "object_id": oid,
                     "offset": offset, "length": length},
                    timeout=self._nm.config.pull_chunk_timeout_s,
                )
                data = reply.get("data")
                if data is None or len(data) != length:
                    raise TransferError(
                        reply.get("error")
                        or f"chunk @{offset} missing from source"
                    )
                # Copy into shared memory off-loop (a 5 MiB memmove
                # should not stall the control plane).
                fut = loop.run_in_executor(
                    self._io_pool, writer.write, offset, data
                )
                write_futs.append(fut)
                await fut
                self._bump("chunks_pulled")
                if progress is not None:
                    progress(length)

        tasks = [
            asyncio.ensure_future(fetch(off))
            for off in range(0, size, chunk)
        ]
        try:
            await asyncio.gather(*tasks)
        except BaseException:
            # Quiesce siblings BEFORE the caller aborts the writer:
            # cancel the coroutines, then wait for every started memcpy.
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            await asyncio.gather(*write_futs, return_exceptions=True)
            raise

    # ------------------------------------------------------------ serve side

    async def _rpc_pull_object(self, _ctx, object_id, max_unchunked):
        return await self.serve_pull(
            {"object_id": object_id, "max_unchunked": max_unchunked}
        )

    async def _rpc_pull_chunk(self, _ctx, object_id, offset, length):
        return await self.serve_chunk(
            {"object_id": object_id, "offset": offset, "length": length}
        )

    async def serve_pull(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """First request of a pull: small objects answer with their bytes
        (one round trip, as before); large ones advertise chunking plus
        this node's data-plane port (absent/0 = control chunks only, the
        mixed-version escape hatch)."""
        oid = msg["object_id"]
        found = self._lookup_local(oid)
        if found is None:
            return {"data": None}
        loc, size = found
        max_unchunked = int(msg.get("max_unchunked") or 0)
        if max_unchunked and size > max_unchunked:
            out = {"data": None, "chunked": True, "size": size}
            data_port = int(getattr(self._nm, "data_port", 0) or 0)
            if data_port:
                out["data_port"] = data_port
            return out
        try:
            data = await self._nm._loop.run_in_executor(
                None, self._nm.local_store.get_bytes, loc
            )
            return {"data": data}
        except Exception as e:
            return {"data": None, "error": str(e)}

    async def serve_chunk(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """Control-plane chunk read (fallback path + thin clients). The
        payload rides as an in-band ``pickle.PickleBuffer`` over the
        store's memoryview slice — the frame encoder serializes straight
        from shared memory, no ``bytes()`` staging copy; the buffer (and
        its store pin) is released when the sent frame is dropped."""
        oid = msg["object_id"]
        offset, length = int(msg["offset"]), int(msg["length"])
        found = self._lookup_local(oid)
        if found is None:
            return {"data": None, "error": "object freed on source"}
        loc, _size = found
        async with self._serve_slots:
            try:
                data = await self._nm._loop.run_in_executor(
                    None, self._read_range, loc, offset, length
                )
                self._bump("chunks_served")
                return {"data": data}
            except Exception as e:
                return {"data": None, "error": str(e)}

    # ---- local range resolution (shared by both planes) -------------------

    def _lookup_local(self, oid: ObjectID):
        from .object_store import (
            InlineLocation,
            RemoteLocation,
            SpilledLocation,
        )

        loc = self._nm.directory.lookup(oid)
        if loc is None or isinstance(loc, RemoteLocation):
            return None
        if isinstance(loc, InlineLocation):
            return loc, len(loc.data)
        if isinstance(loc, SpilledLocation):
            import os

            try:
                return loc, os.path.getsize(loc.path)
            except OSError:
                return None
        return loc, loc.size

    def open_range(self, oid_bytes: bytes, offset: int, length: int):
        """DataPlaneServer source hook (server threads): resolve one
        sealed byte range. Returns ``("view", memoryview, release)`` for
        store-resident objects or ``("file", path)`` for spilled ones;
        raises for unknown/out-of-range requests (relayed as an error
        frame)."""
        from .object_store import SpilledLocation

        oid = ObjectID(oid_bytes)
        found = self._lookup_local(oid)
        if found is None:
            raise KeyError(f"object {oid.hex()[:8]} freed on source")
        loc, size = found
        if offset < 0 or length < 0 or offset + length > size:
            raise ValueError(
                f"range [{offset}, {offset + length}) outside object of "
                f"{size} bytes"
            )
        if isinstance(loc, SpilledLocation):
            # Ranged read straight from disk — no need to restore the
            # whole object into the store first.
            return ("file", loc.path)
        view, release = self._nm.local_store.get_view_range(
            loc, offset, length
        )
        return ("view", view, release)

    def on_range_served(self, nbytes: int):
        """DataPlaneServer progress hook: serve-side byte accounting."""
        with self._stats_lock:
            self.stats["bytes_served_stream"] += nbytes

    def on_range_done(self, nbytes: int):
        self._bump("ranges_served")
        try:
            TRANSFER_BYTES.inc(
                float(nbytes),
                tags={"node": self._node_tag(), "direction": "serve",
                      "plane": "stream"},
            )
        except Exception:
            pass

    def _read_range(self, loc, offset: int, length: int):
        from .object_store import SpilledLocation

        if isinstance(loc, SpilledLocation):
            # Serve spilled objects straight from disk — a ranged read, no
            # need to restore the whole object into the store first.
            with open(loc.path, "rb") as f:
                f.seek(offset)
                return f.read(length)
        view = self._nm.local_store.get_view(loc)
        try:
            # In-band PickleBuffer: the encoder copies once, shm -> frame
            # (the old bytes(view[...]) staged a second, whole-chunk
            # copy). The slice holds its own buffer reference, so the
            # parent view releases immediately; the slice's pin drops
            # with the reply frame.
            return pickle.PickleBuffer(view[offset:offset + length])
        finally:
            if hasattr(view, "release"):
                view.release()
