"""Chunked, flow-controlled inter-node object transfer.

Plays the role of the reference's object manager data plane (ref:
src/ray/object_manager/object_manager.h Push/Pull over
object_manager.proto:61): large objects move as bounded-size chunks
(``object_transfer_chunk_bytes``, ref object_manager_default_chunk_size =
5 MiB, common/ray_config_def.h:362) with admission control on both sides —
the puller bounds concurrent large pulls and in-flight chunk frames (ref:
pull_manager.h:52 bundles admitted against available memory), the server
bounds concurrent chunk reads (ref: push_manager.h:30 rate-limited chunked
sends). Received chunks land directly in a pre-allocated store block
(``LocalObjectStore.create_writer``), so a 1 GiB transfer occupies 1 GiB of
store plus a few staged chunks — never a second whole-object copy, and the
peer socket interleaves other RPCs between chunks instead of being held
hostage by one giant frame.

Dedup notes: per-object pull dedup lives in the node manager's ``_pulls``
future table (one pull per object per node, concurrent requesters share
it); a broadcast (N nodes pulling one object) therefore issues exactly one
pull per receiving node, and the source's serve semaphore spreads chunk
reads across the N peer connections — the role of the reference's
PushManager dedup.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict

from .ids import ObjectID
from .object_store import Location


class TransferError(Exception):
    """Data-plane failure; the caller maps it to object recovery."""


class ObjectTransfer:
    """Both halves of the chunk protocol, owned by the node manager."""

    def __init__(self, node_manager):
        self._nm = node_manager
        cfg = node_manager.config
        self.chunk_bytes = int(cfg.object_transfer_chunk_bytes)
        # Puller-side admission: whole large pulls, then chunk frames.
        self._pull_slots = asyncio.Semaphore(cfg.pull_large_concurrency)
        self._chunk_slots = asyncio.Semaphore(cfg.pull_chunks_in_flight)
        # Server-side: bound concurrent chunk reads (each stages one
        # chunk_bytes copy + an executor thread).
        self._serve_slots = asyncio.Semaphore(cfg.serve_chunks_in_flight)
        # Memory admission (ref: pull_manager.h:52 — bundles admitted
        # against available store memory): bytes reserved by in-flight
        # chunked pulls, counted against store capacity so N admitted
        # pulls can never exceed what the store can hold.
        self._inflight_bytes = 0
        self.stats = {"chunks_pulled": 0, "chunks_served": 0,
                      "chunked_pulls": 0, "pulls_queued_on_memory": 0}

    # ------------------------------------------------------------- pull side

    async def pull(self, peer, oid: ObjectID) -> bytes | Location:
        """Fetch one object from ``peer``. Returns raw framed bytes for
        small objects (caller stores them) or a ready local Location for
        chunked large objects (bytes already in the store)."""
        reply = await peer.request(
            {"type": "pull_object", "object_id": oid,
             "max_unchunked": self.chunk_bytes}
        )
        data = reply.get("data")
        if data is not None:
            return data
        size = reply.get("size")
        if not reply.get("chunked") or size is None:
            raise TransferError(
                reply.get("error") or "object freed on source"
            )
        async with self._pull_slots:
            self.stats["chunked_pulls"] += 1
            await self._admit_bytes(int(size))
            try:
                return await self._pull_chunked(peer, oid, int(size))
            finally:
                self._inflight_bytes -= int(size)

    async def _admit_bytes(self, size: int):
        """Queue until the store can hold ``size`` more bytes (spilling
        cold objects to make room); fail cleanly when the object can
        never fit (ref: PullManager admission vs available memory)."""
        d = self._nm.directory
        cap = d.capacity_bytes
        if cap > 0 and size > cap:
            raise TransferError(
                f"object of {size} bytes exceeds the object store "
                f"capacity ({cap} bytes); it can never be pulled whole"
            )
        # NOTE: directory.used_bytes does not see a transfer's arena
        # block until finalize registers the object, so the full-size
        # reservation here is the ONLY meter for in-flight pulls (no
        # double counting while chunks land).
        if cap <= 0:
            self._inflight_bytes += size
            return
        loop = self._nm._loop
        deadline = loop.time() + self._nm.config.pull_admission_timeout_s
        queued = False
        while True:
            free = cap - d.used_bytes - self._inflight_bytes
            if size <= free:
                self._inflight_bytes += size
                return
            if not queued:
                queued = True
                self.stats["pulls_queued_on_memory"] += 1
            # Ask the spill pass to free exactly what we lack — the
            # high-water trigger alone would no-op below the mark.
            self._nm._maybe_spill(need=size - max(free, 0))
            if loop.time() > deadline:
                raise TransferError(
                    f"pull of {size} bytes not admitted within "
                    f"{self._nm.config.pull_admission_timeout_s}s: store "
                    f"full ({d.used_bytes}/{cap} used, "
                    f"{self._inflight_bytes} in flight)"
                )
            await asyncio.sleep(0.05)

    async def _pull_chunked(self, peer, oid: ObjectID, size: int) -> Location:
        store = self._nm.local_store
        loop = self._nm._loop
        writer = await loop.run_in_executor(
            None, store.create_writer, oid, size
        )
        try:
            chunk = self.chunk_bytes
            # Executor-thread writes in flight: a cancelled fetch coroutine
            # does NOT stop its already-running threadpool write, so the
            # abort path must drain THESE, not just the tasks.
            write_futs: list = []

            async def fetch(offset: int):
                length = min(chunk, size - offset)
                async with self._chunk_slots:
                    reply = await peer.request(
                        {"type": "pull_chunk", "object_id": oid,
                         "offset": offset, "length": length},
                        timeout=self._nm.config.pull_chunk_timeout_s,
                    )
                    data = reply.get("data")
                    if data is None or len(data) != length:
                        raise TransferError(
                            reply.get("error")
                            or f"chunk @{offset} missing from source"
                        )
                    # Copy into shared memory off-loop (a 5 MiB memmove
                    # should not stall the control plane).
                    fut = loop.run_in_executor(
                        None, writer.write, offset, data
                    )
                    write_futs.append(fut)
                    await fut
                    self.stats["chunks_pulled"] += 1

            tasks = [
                asyncio.ensure_future(fetch(off))
                for off in range(0, size, chunk)
            ]
            try:
                await asyncio.gather(*tasks)
            except BaseException:
                # Quiesce siblings BEFORE aborting the writer: cancel the
                # coroutines, then wait for every started memcpy — a write
                # racing abort() would land in freed arena memory.
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                await asyncio.gather(*write_futs, return_exceptions=True)
                raise
            return await loop.run_in_executor(None, writer.finalize)
        except BaseException:
            writer.abort()
            raise

    # ------------------------------------------------------------ serve side

    async def serve_pull(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """First request of a pull: small objects answer with their bytes
        (one round trip, as before); large ones advertise chunking."""
        oid = msg["object_id"]
        found = self._lookup_local(oid)
        if found is None:
            return {"data": None}
        loc, size = found
        max_unchunked = int(msg.get("max_unchunked") or 0)
        if max_unchunked and size > max_unchunked:
            return {"data": None, "chunked": True, "size": size}
        try:
            data = await self._nm._loop.run_in_executor(
                None, self._nm.local_store.get_bytes, loc
            )
            return {"data": data}
        except Exception as e:
            return {"data": None, "error": str(e)}

    async def serve_chunk(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        oid = msg["object_id"]
        offset, length = int(msg["offset"]), int(msg["length"])
        found = self._lookup_local(oid)
        if found is None:
            return {"data": None, "error": "object freed on source"}
        loc, _size = found
        async with self._serve_slots:
            try:
                data = await self._nm._loop.run_in_executor(
                    None, self._read_range, loc, offset, length
                )
                self.stats["chunks_served"] += 1
                return {"data": data}
            except Exception as e:
                return {"data": None, "error": str(e)}

    def _lookup_local(self, oid: ObjectID):
        from .object_store import (
            InlineLocation,
            RemoteLocation,
            SpilledLocation,
        )

        loc = self._nm.directory.lookup(oid)
        if loc is None or isinstance(loc, RemoteLocation):
            return None
        if isinstance(loc, InlineLocation):
            return loc, len(loc.data)
        if isinstance(loc, SpilledLocation):
            import os

            try:
                return loc, os.path.getsize(loc.path)
            except OSError:
                return None
        return loc, loc.size

    def _read_range(self, loc, offset: int, length: int) -> bytes:
        from .object_store import SpilledLocation

        if isinstance(loc, SpilledLocation):
            # Serve spilled objects straight from disk — a ranged read, no
            # need to restore the whole object into the store first.
            with open(loc.path, "rb") as f:
                f.seek(offset)
                return f.read(length)
        view = self._nm.local_store.get_view(loc)
        try:
            return bytes(view[offset:offset + length])
        finally:
            if hasattr(view, "release"):
                view.release()
