"""Streaming generator returns.

Ref analogue: ObjectRefGenerator / streaming_generator.py — a task
declared ``num_returns="streaming"`` yields values; each yield is sealed
into the object store AS IT IS PRODUCED (index-derived ObjectIDs), so the
consumer iterates results while the producer is still running —
backpressure-free pipelining for long producers.

Protocol: the producing worker seals item i as
``ObjectID.from_index(task_id, STREAM_BASE | (i+1))`` with one pinned
ref, then writes a small KV record ``__stream__/<task>/<i>``; generator
exhaustion writes an ``end`` record. The consumer polls the KV (cheap:
single control-plane lookup), adopts each item ref (its +1 cancels the
producer's pin via coalesced delta flushing), and raises StopIteration at
the end marker. Works cross-node: item locations ride the GCS object
directory like any sealed object.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import cloudpickle

from .ids import ObjectID, TaskID
from .reference import ObjectRef

# High bit block distinct from return slots (small ints) and put-ids
# (0x8000_0000 block).
STREAM_BASE = 0x4000_0000

POLL_INTERVAL_S = 0.02


def stream_item_id(task_id: TaskID, index: int) -> ObjectID:
    return ObjectID.from_index(task_id, STREAM_BASE | (index + 1))


def stream_key(task_id: TaskID, index: int) -> str:
    return f"__stream__/{task_id.hex()}/{index}"


class ObjectRefGenerator:
    """Iterator over a streaming task's yielded ObjectRefs (ref:
    ObjectRefGenerator). ``next()`` returns the NEXT item's ObjectRef as
    soon as the producer sealed it; iteration ends when the producer's
    generator is exhausted. The completion ref resolves to the item count
    (and surfaces the task's exception, if any)."""

    def __init__(self, task_id: TaskID, completion_ref: ObjectRef):
        self._task_id = task_id
        self._completion_ref = completion_ref
        self._next = 0
        self._count: Optional[int] = None
        # Optional per-item production deadline (serve SSE guard).
        self.item_timeout_s = None

    @property
    def completed(self) -> ObjectRef:
        """The task's completion ref (item count / error carrier)."""
        return self._completion_ref

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        from .runtime_context import current_runtime

        rt = current_runtime()
        if self._count is not None and self._next >= self._count:
            raise StopIteration
        key = stream_key(self._task_id, self._next)
        deadline = (
            None if self.item_timeout_s is None
            else time.monotonic() + self.item_timeout_s
        )
        while True:
            blob = rt.kv_get(key)
            if blob is not None:
                break
            if deadline is not None and time.monotonic() > deadline:
                # A wedged producer must not hold consumers (serve proxy
                # threads) forever — surface a timeout instead.
                from .exceptions import GetTimeoutError

                raise GetTimeoutError(
                    f"stream item {self._next} not produced within "
                    f"{self.item_timeout_s}s"
                )
            # Surface producer failure instead of hanging: the completion
            # slot seals (with the error) when the task dies.
            import ray_tpu

            done, _ = ray_tpu.wait(
                [self._completion_ref], num_returns=1, timeout=0
            )
            if done:
                # Either finished (end marker imminent/count known) or
                # failed (get raises the task error).
                count = ray_tpu.get(self._completion_ref)
                blob = rt.kv_get(key)
                if blob is None:
                    self._count = count
                    raise StopIteration
                break
            time.sleep(POLL_INTERVAL_S)
        payload = cloudpickle.loads(blob)
        if "end" in payload:
            self._count = payload["end"]
            self._drop_all_kv()
            raise StopIteration
        idx = self._next
        self._next += 1
        oid = ObjectID.from_hex(payload["oid"])
        ref = ObjectRef(oid, _register=True)
        # Cancel the producer-side pin: the +1 just registered and this -1
        # coalesce locally, leaving the seal-time pin as the user ref's
        # count until the ref is dropped.
        rt.refs.decr(oid)
        # TOMBSTONE rather than delete: a retried producer checks this key
        # to decide whether an index was already pinned — deleting it would
        # make the retry re-pin consumed items (leak).
        try:
            rt.kv_put(stream_key(self._task_id, idx),
                      cloudpickle.dumps({"consumed": True}))
        except Exception:
            pass
        return ref

    def _drop_all_kv(self) -> None:
        """Stream finished: progress records (incl. tombstones) go away."""
        from .runtime_context import current_runtime_or_none

        rt = current_runtime_or_none()
        if rt is None:
            return
        try:
            prefix = f"__stream__/{self._task_id.hex()}/"
            for key in rt.kv_keys(prefix):
                try:
                    rt.kv_del(key)
                except Exception:
                    pass
        except Exception:
            pass

    def __del__(self):
        """Abandoned mid-stream: release the producer pins of every
        unconsumed item and drop all progress records, so a consumer that
        stops early doesn't leak object-store memory.

        The cleanup does BLOCKING control-plane calls, and __del__ can
        fire on ANY thread the garbage collector happens to run on —
        including the node-manager event loop itself (observed: gc
        during frame pickling on the NM loop → kv_keys → call_sync onto
        the same loop → the whole runtime deadlocks). So the work is
        handed to a short-lived daemon thread, never run inline."""
        try:
            import threading

            from .runtime_context import current_runtime_or_none

            rt = current_runtime_or_none()
            if rt is None:
                return
            threading.Thread(
                target=_release_abandoned_stream,
                args=(rt, self._task_id, self._next),
                name="stream-gc",
                daemon=True,
            ).start()
        except Exception:
            pass  # interpreter shutting down / runtime gone

    def __repr__(self):
        return (f"ObjectRefGenerator(task={self._task_id.hex()[:8]}, "
                f"next={self._next})")


def _release_abandoned_stream(rt, task_id, next_idx: int) -> None:
    """Off-thread body of ObjectRefGenerator.__del__ (see there)."""
    try:
        prefix = f"__stream__/{task_id.hex()}/"
        for key in rt.kv_keys(prefix):
            try:
                idx = int(key.rsplit("/", 1)[1])
            except ValueError:
                continue
            blob = rt.kv_get(key)
            if blob and idx >= next_idx:
                payload = cloudpickle.loads(blob)
                if "oid" in payload:
                    rt.refs.decr(ObjectID.from_hex(payload["oid"]))
            try:
                rt.kv_del(key)
            except Exception:
                pass
    except Exception:
        pass
