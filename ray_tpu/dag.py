"""Lazy task/actor DAG API.

Ref analogue: python/ray/dag/ (FunctionNode/ClassNode/InputNode,
``fn.bind()`` building the graph, ``dag.execute()`` walking it). Nodes
bind other nodes as arguments; ``execute`` submits the whole graph as
tasks wired by ObjectRefs — intermediate results never touch the driver,
and independent branches run concurrently (the scheduler sees the whole
frontier at submission time).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """Base: a lazily-bound computation with DAGNode-typed arguments."""

    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- building ----------------------------------------------------------

    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    # -- execution ---------------------------------------------------------

    def execute(self, *input_args, **input_kwargs):
        """Run the DAG rooted here; returns the root's ObjectRef (or
        actor handle for a ClassNode root)."""
        cache: Dict[int, Any] = {}
        input_val = input_args[0] if len(input_args) == 1 else (
            input_args if input_args else None
        )
        return self._execute_node(cache, input_val, input_kwargs)

    def _resolve_args(self, cache, input_val, input_kwargs):
        def resolve(a):
            if isinstance(a, DAGNode):
                return a._execute_node(cache, input_val, input_kwargs)
            return a

        args = tuple(resolve(a) for a in self._bound_args)
        kwargs = {k: resolve(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_node(self, cache, input_val, input_kwargs):
        key = id(self)
        if key not in cache:
            cache[key] = self._execute_impl(cache, input_val, input_kwargs)
        return cache[key]

    def _execute_impl(self, cache, input_val, input_kwargs):
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for execute()-time input (ref: dag/input_node.py).
    Usable as a context manager for parity with the reference:

        with InputNode() as inp:
            dag = f.bind(inp)
        dag.execute(5)
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def _execute_impl(self, cache, input_val, input_kwargs):
        return input_val


class FunctionNode(DAGNode):
    def __init__(self, remote_function, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_function

    def _execute_impl(self, cache, input_val, input_kwargs):
        args, kwargs = self._resolve_args(cache, input_val, input_kwargs)
        return self._fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """A bound actor constructor; attribute access yields method nodes."""

    def __init__(self, actor_class, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_class = actor_class

    def __getattr__(self, name: str) -> "_ClassMethodBinder":
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)

    def _execute_impl(self, cache, input_val, input_kwargs):
        args, kwargs = self._resolve_args(cache, input_val, input_kwargs)
        return self._actor_class.remote(*args, **kwargs)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method = method

    def _children(self) -> List["DAGNode"]:
        # The bound actor is a dependency too (graph walkers — e.g. the
        # workflow step order — must visit it).
        return [self._class_node] + super()._children()

    def _execute_impl(self, cache, input_val, input_kwargs):
        handle = self._class_node._execute_node(
            cache, input_val, input_kwargs
        )
        args, kwargs = self._resolve_args(cache, input_val, input_kwargs)
        return getattr(handle, self._method).remote(*args, **kwargs)


MultiOutputNode = tuple  # reference-API alias: wrap roots in a tuple
