"""ray_tpu.models: TPU-first model zoo for the benchmark configs
(BASELINE.json): Llama-3 family (+ Mixtral MoE via n_experts), ResNet/CIFAR,
ViT for image pipelines."""

from .llama import (  # noqa: F401
    LlamaConfig,
    causal_lm_loss,
    forward,
    init_params,
    num_params,
    param_logical_axes,
)
from .resnet import ResNet, resnet18, resnet50  # noqa: F401
