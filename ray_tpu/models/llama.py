"""Llama-family transformer, TPU-first.

The flagship model (BASELINE.json configs: Llama-3 8B/70B, Mixtral 8x7B via
``n_experts``). Design choices for TPU/XLA:

- Pure-functional: params are a pytree of arrays; sharding is declared as a
  matching pytree of logical axes (parallel/sharding.py rules) — pjit/GSPMD
  inserts the collectives for dp/fsdp/tp; ring attention (sp) is an explicit
  shard_map island inside the jitted program.
- Layers are *stacked* ([L, ...] leaves) and applied with lax.scan: one
  layer gets compiled once regardless of depth (compile-time O(1) in L),
  and the "layers" leading axis is what pipeline parallelism shards.
- bfloat16 activations/weights with float32 RMSNorm/softmax/rope, the
  standard TPU mixed-precision recipe (MXU eats bf16; norms need f32).
- jax.checkpoint around each layer body for rematerialization.

The reference has no model zoo — it orchestrates user models; this
framework owns its compute path (SURVEY.md §7 phase 7).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ..parallel.sharding import with_logical_constraint
from ..parallel.mesh import mesh_axis_size
from ..parallel.ring_attention import ring_attention
from ..parallel.moe import moe_ffn
from ..ops.attention import mha_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32_000
    hidden_size: int = 4096
    intermediate_size: int = 14_336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: Optional[int] = None
    rope_theta: float = 500_000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # MoE (Mixtral-style) when n_experts > 0.
    n_experts: int = 0
    top_k: int = 2
    capacity_factor: float = 1.25
    remat: bool = True
    # "full" (save only layer inputs), "dots" (save matmul outputs,
    # recompute elementwise), or "save_all" (save every intermediate —
    # no backward recompute). "dots"/"save_all" trade HBM for less
    # backward recompute where memory allows.
    remat_policy: str = "full"
    # Pallas flash attention kernel on TPU (ops/flash_attention.py);
    # automatically the XLA einsum path off-TPU or for odd shapes.
    # On by default: with the fused Pallas backward (KV-head-grid dK/dV,
    # GQA reduced in-kernel) flash beats the XLA path for training too —
    # 0.596 vs 0.532 MFU on the 8B-shaped bench (PERF_r04.json A/B).
    use_flash: bool = True
    # Cross-entropy sequence chunk: the loss streams over S/chunk slices
    # so the [B, S, V] float32 logits (4.3 GB at B=16, S=2k, V=32k — and
    # the backward saves log-softmax residuals of the same size) never
    # materialize; peak is one [B, chunk, V] slice, recomputed in the
    # backward (jax.checkpoint per chunk). 0 disables chunking.
    loss_chunk: int = 512
    # lax.scan over layers (compile-time O(1) in depth) vs an unrolled
    # python loop. Unrolled avoids the scan's stacked [L, ...] residual
    # buffers — at shallow depth that removes the large contiguous
    # allocations behind the allocator fragmentation that OOMs the
    # selective-remat policies.
    scan_layers: bool = True
    # Layers per scan step (the full-depth schedule). 0/1 scans one layer
    # at a time (the classic stacked-scan path). K>1 scans over L/K
    # chunks of K layers, unrolled inside the chunk body with ONE
    # jax.checkpoint (remat_policy) around the chunk: the scan's stacked
    # residual buffers shrink from [L, ...] to [L/K, ...] — the
    # allocation that drove the 43-46% allocator fragmentation OOMs on
    # selective-remat policies at real depth — while the per-chunk
    # unroll keeps the remat policy's save-set (dots/mlp outputs) local
    # to one chunk. K must divide num_layers.
    scan_chunk: int = 0

    @property
    def dh(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def llama3_70b() -> "LlamaConfig":
        return LlamaConfig(
            hidden_size=8192, intermediate_size=28_672, num_layers=80,
            num_heads=64, num_kv_heads=8,
        )

    @staticmethod
    def mixtral_8x7b() -> "LlamaConfig":
        return LlamaConfig(
            hidden_size=4096, intermediate_size=14_336, num_layers=32,
            num_heads=32, num_kv_heads=8, n_experts=8, top_k=2,
        )

    @staticmethod
    def tiny(vocab: int = 256, moe: bool = False) -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=vocab, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, rope_theta=10_000.0,
            dtype=jnp.float32, n_experts=4 if moe else 0, top_k=2,
        )


# Logical axes for each parameter leaf (maps through DEFAULT_RULES:
# embed→fsdp, heads/mlp/vocab→tp, expert→ep, layers→pp-or-scan).
def param_logical_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    layer = {
        "attn_norm": ("layers", "norm"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "mlp_norm": ("layers", "norm"),
    }
    if cfg.n_experts > 0:
        layer.update(
            router=("layers", "embed", None),
            w_gate=("layers", "expert", "embed", "mlp"),
            w_up=("layers", "expert", "embed", "mlp"),
            w_down=("layers", "expert", "mlp", "embed"),
        )
    else:
        layer.update(
            w_gate=("layers", "embed", "mlp"),
            w_up=("layers", "embed", "mlp"),
            w_down=("layers", "mlp", "embed"),
        )
    return {
        "embed": ("vocab", "embed"),
        "layers": layer,
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    k = iter(jax.random.split(key, 16))
    M, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    H, Hkv, Dh, V = cfg.num_heads, cfg.num_kv_heads, cfg.dh, cfg.vocab_size
    dt = cfg.dtype

    def norm_init(shape):
        return jnp.ones(shape, dtype=jnp.float32)

    def winit(key, shape, fan_in):
        return (jax.random.normal(key, shape, dtype=jnp.float32)
                * (fan_in ** -0.5)).astype(dt)

    layers: Dict[str, Any] = {
        "attn_norm": norm_init((L, M)),
        "wq": winit(next(k), (L, M, H, Dh), M),
        "wk": winit(next(k), (L, M, Hkv, Dh), M),
        "wv": winit(next(k), (L, M, Hkv, Dh), M),
        "wo": winit(next(k), (L, H, Dh, M), H * Dh),
        "mlp_norm": norm_init((L, M)),
    }
    if cfg.n_experts > 0:
        E = cfg.n_experts
        layers.update(
            router=winit(next(k), (L, M, E), M).astype(jnp.float32),
            w_gate=winit(next(k), (L, E, M, F), M),
            w_up=winit(next(k), (L, E, M, F), M),
            w_down=winit(next(k), (L, E, F, M), F),
        )
    else:
        layers.update(
            w_gate=winit(next(k), (L, M, F), M),
            w_up=winit(next(k), (L, M, F), M),
            w_down=winit(next(k), (L, F, M), F),
        )
    return {
        "embed": winit(next(k), (V, M), M),
        "layers": layers,
        "final_norm": norm_init((M,)),
        "lm_head": winit(next(k), (M, V), M),
    }


def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale * w).astype(x.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x [B, S, H, D], positions [S] (global indices so
    sequence-sharded blocks stay correct)."""
    D = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, D // 2, dtype=jnp.float32) / (D // 2))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _attention(cfg: LlamaConfig, mesh, q, k, v):
    if mesh is not None and mesh_axis_size(mesh, "sp") > 1:
        return ring_attention(q, k, v, mesh, causal=True)
    if cfg.use_flash:
        from ..ops.flash_attention import flash_attention

        # Pallas kernel on TPU; transparently the XLA path elsewhere.
        return flash_attention(q, k, v, causal=True)
    return mha_attention(q, k, v, causal=True)


def _layer(cfg: LlamaConfig, mesh, positions, x, lp):
    """One transformer block. x [B, S, M]."""
    h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = jnp.einsum("bsm,mhd->bshd", h, lp["wq"])
    kk = jnp.einsum("bsm,mhd->bshd", h, lp["wk"])
    vv = jnp.einsum("bsm,mhd->bshd", h, lp["wv"])
    q = rope(q, positions, cfg.rope_theta)
    kk = rope(kk, positions, cfg.rope_theta)
    q = with_logical_constraint(q, ("batch", "seq", "heads", "head_dim"),
                                mesh=mesh)
    attn = _attention(cfg, mesh, q, kk, vv)
    x = x + jnp.einsum("bshd,hdm->bsm", attn, lp["wo"])

    h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    if cfg.n_experts > 0:
        out, aux = moe_ffn(
            h, lp["router"], lp["w_up"], lp["w_down"],
            k=cfg.top_k, capacity_factor=cfg.capacity_factor,
            w_gate=lp["w_gate"],
        )
        x = x + out
        return x, aux
    up = jnp.einsum("bsm,mf->bsf", h, lp["w_up"])
    gate = jnp.einsum("bsm,mf->bsf", h, lp["w_gate"])
    # Named for the selective "mlp" remat policy: saving these two
    # outputs (the widest matmuls — ~45% of a layer's forward FLOPs)
    # removes their backward recompute at a fraction of checkpoint_dots'
    # footprint (which also saves attention/down/norm outputs).
    up = checkpoint_name(up, "mlp_up")
    gate = checkpoint_name(gate, "mlp_gate")
    h = jax.nn.silu(gate.astype(jnp.float32)).astype(up.dtype) * up
    h = with_logical_constraint(h, ("batch", "seq", "mlp"), mesh=mesh)
    x = x + jnp.einsum("bsf,fm->bsm", h, lp["w_down"])
    return x, jnp.zeros((), dtype=jnp.float32)


def forward(
    params: Dict[str, Any],
    tokens: jax.Array,  # [B, S] int32
    cfg: LlamaConfig,
    mesh=None,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (logits [B, S, V] float32, moe_aux_loss scalar)."""
    x, aux = hidden_forward(params, tokens, cfg, mesh)
    logits = jnp.einsum("bsm,mv->bsv", x, params["lm_head"])
    return logits.astype(jnp.float32), aux


def remat_policy(cfg: LlamaConfig):
    """The jax.checkpoint policy selected by cfg.remat_policy."""
    if cfg.remat_policy == "dots":
        # Save ALL matmul outputs — least recompute, largest
        # footprint (OOMs the 8B-shaped bench: ~10 G HLO temp).
        return jax.checkpoint_policies.checkpoint_dots
    if cfg.remat_policy == "mlp":
        # Selective (scaling-playbook style): save only the two
        # widest matmuls' outputs (up/gate, ~45% of forward
        # FLOPs) and recompute the rest — the best
        # recompute-per-byte trade on one chip.
        return jax.checkpoint_policies.save_only_these_names(
            "mlp_up", "mlp_gate"
        )
    if cfg.remat_policy == "save_all":
        return jax.checkpoint_policies.everything_saveable
    return None


def scan_chunks(cfg: LlamaConfig) -> Tuple[int, int]:
    """(layers_per_chunk, num_chunks) for the scan schedule. Validates
    that scan_chunk divides num_layers — a ragged final chunk would need
    its own compiled body, defeating the scan's O(1)-in-depth compile."""
    K = max(1, cfg.scan_chunk or 1)
    if cfg.num_layers % K:
        raise ValueError(
            f"scan_chunk={K} must divide num_layers={cfg.num_layers}"
        )
    return K, cfg.num_layers // K


def hidden_forward(
    params: Dict[str, Any],
    tokens: jax.Array,  # [B, S] int32
    cfg: LlamaConfig,
    mesh=None,
) -> Tuple[jax.Array, jax.Array]:
    """Transformer trunk WITHOUT the lm_head projection: returns
    (hidden [B, S, M] after final_norm, moe_aux_loss scalar)."""
    B, S = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)
    x = with_logical_constraint(x, ("batch", "seq", "embed"), mesh=mesh)
    positions = jnp.arange(S)
    policy = remat_policy(cfg)

    def body(x, lp):
        if cfg.remat:
            fn = jax.checkpoint(
                lambda x_, lp_: _layer(cfg, mesh, positions, x_, lp_),
                policy=policy,
            )
            out, aux = fn(x, lp)
        else:
            out, aux = _layer(cfg, mesh, positions, x, lp)
        out = with_logical_constraint(out, ("batch", "seq", "embed"), mesh=mesh)
        return out, aux

    if cfg.scan_layers:
        K, n_chunks = scan_chunks(cfg)
        if K == 1:
            x, aux = jax.lax.scan(body, x, params["layers"])
        else:
            # Layer-chunked schedule: scan over [L/K, ...] stacks of
            # K-layer chunks. ONE checkpoint per chunk (the policy's
            # save-set covers the whole unrolled chunk body), and the
            # carry re-annotated each step so GSPMD keeps the scan body's
            # layout resident instead of resharding per iteration.
            chunked = jax.tree.map(
                lambda p: p.reshape((n_chunks, K) + p.shape[1:]),
                params["layers"],
            )

            def chunk_fn(x_, cp):
                aux = jnp.zeros((), dtype=jnp.float32)
                for k in range(K):
                    lp = jax.tree.map(lambda p: p[k], cp)
                    x_, a = _layer(cfg, mesh, positions, x_, lp)
                    aux = aux + a
                return x_, aux

            if cfg.remat:
                chunk_fn = jax.checkpoint(chunk_fn, policy=policy)

            def chunk_body(x_, cp):
                x_ = with_logical_constraint(
                    x_, ("batch", "seq", "embed"), mesh=mesh
                )
                out, aux = chunk_fn(x_, cp)
                out = with_logical_constraint(
                    out, ("batch", "seq", "embed"), mesh=mesh
                )
                return out, aux

            x, aux = jax.lax.scan(chunk_body, x, chunked)
        aux = aux.sum()
    else:
        aux = jnp.zeros((), jnp.float32)
        for i in range(cfg.num_layers):
            lp = jax.tree.map(lambda p: p[i], params["layers"])
            x, a = body(x, lp)
            aux = aux + a
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    return x, aux


def _chunked_nll_sum(x: jax.Array, lm_head: jax.Array,
                     targets: jax.Array, chunk: int) -> jax.Array:
    """Total next-token NLL over [B, S] positions, streaming the lm_head
    projection + log-sum-exp over S/chunk slices so no [B, S, V] tensor
    ever materializes (the memory cliff behind the batch-16 collapse:
    the monolithic loss kept logits + log-softmax residuals, ~8.6 GB at
    B=16). Each chunk is rematerialized in the backward."""
    B, S, M = x.shape
    pad = (-S) % chunk
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
    nchunks = (S + pad) // chunk
    # [n, B, C, M] / [n, B, C] views for the scan.
    xs = x.reshape(B, nchunks, chunk, M).transpose(1, 0, 2, 3)
    ts = targets.reshape(B, nchunks, chunk).transpose(1, 0, 2)
    valid = jnp.arange(nchunks * chunk).reshape(nchunks, chunk) < S

    def body(total, inp):
        xc, tc, mask = inp
        logits = jnp.einsum(
            "bcm,mv->bcv", xc, lm_head
        ).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(
            logits, tc[..., None], axis=-1
        )[..., 0]
        nll = (lse - tgt) * mask[None, :]
        return total + nll.sum(), None

    total, _ = jax.lax.scan(
        jax.checkpoint(body), jnp.zeros((), jnp.float32),
        (xs, ts, valid),
    )
    return total


def causal_lm_loss(
    params: Dict[str, Any],
    tokens: jax.Array,       # [B, S]
    cfg: LlamaConfig,
    mesh=None,
    *,
    aux_weight: float = 0.01,
) -> jax.Array:
    """Next-token cross entropy (tokens shifted internally). With
    cfg.loss_chunk > 0 the head projection + softmax stream over
    sequence chunks (identical math, a fraction of the peak memory)."""
    targets = tokens[:, 1:]
    chunk = cfg.loss_chunk
    if chunk and chunk > 0 and targets.shape[1] > chunk:
        x, aux = hidden_forward(params, tokens[:, :-1], cfg, mesh)
        total = _chunked_nll_sum(x, params["lm_head"], targets, chunk)
        return total / targets.size + aux_weight * aux
    logits, aux = forward(params, tokens[:, :-1], cfg, mesh)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean() + aux_weight * aux


def num_params(params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))
