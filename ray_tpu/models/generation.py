"""Autoregressive generation with a static KV cache.

TPU-first decode path for the Llama family: all shapes static (XLA traces
once) — the cache is a fixed [L, B, T_max, Hkv, Dh] buffer updated with
dynamic_update_slice; per-slot lengths mask attention. Prefill and decode
are separate jitted programs (the standard TPU serving split: prefill is
compute-bound on the MXU, decode is HBM-bandwidth-bound).

No reference counterpart — Ray delegates model serving compute to user
code; this framework owns it (continuous batching sits on top in
ray_tpu.serve.llm).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .llama import LlamaConfig, rms_norm, rope

# Paged decode attention implementation choice, read ONCE at import (it
# is baked into the traced program — flipping the env after the first
# compile has no effect): default is the XLA gather path, which measured
# faster in the full decode step (PERF_r04 paged section).
import os as _os

_USE_PAGED_KERNEL = _os.environ.get("RAY_TPU_PAGED_KERNEL") == "1"


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, T, Hkv, Dh]
    v: jax.Array  # [L, B, T, Hkv, Dh]
    lengths: jax.Array  # [B] int32 — valid tokens per slot

    @staticmethod
    def create(cfg: LlamaConfig, batch: int, max_len: int) -> "KVCache":
        shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.dh)
        return KVCache(
            k=jnp.zeros(shape, dtype=cfg.dtype),
            v=jnp.zeros(shape, dtype=cfg.dtype),
            lengths=jnp.zeros((batch,), dtype=jnp.int32),
        )


def _attend_cached(q, ck, cv, q_pos, lengths, cfg):
    """q [B,S,H,D] against cache ck/cv [B,T,Hkv,D]; positions of q rows are
    q_pos [B,S]; cache rows >= lengths[b] (post-update) are masked."""
    B, S, H, D = q.shape
    T = ck.shape[1]
    if S == T and S % 128 == 0 and cfg.use_flash:
        # Fresh prefill (appending S tokens to an S-long cache implies
        # start position 0): pure causal self-attention — route through
        # the flash kernel (GQA handled natively; ~1.5x the XLA einsum
        # on TPU and O(S) memory). VERDICT r3 ask #7b.
        from ..ops.flash_attention import flash_attention

        return flash_attention(q, ck, cv, causal=True)
    rep = H // ck.shape[2]
    k = jnp.repeat(ck, rep, axis=2)
    v = jnp.repeat(cv, rep, axis=2)
    scores = jnp.einsum("bshd,bthd->bhst", q, k,
                        preferred_element_type=jnp.float32) * (D ** -0.5)
    t_idx = jnp.arange(T)[None, None, :]  # [1,1,T]
    causal = t_idx <= q_pos[:, :, None]  # [B,S,T]
    scores = jnp.where(causal[:, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def _layer_cached(cfg, lp, x, cache_k, cache_v, start_pos, q_pos,
                  active=None):
    """One block over cached KV. x [B,S,M]; start_pos [B] write offset;
    ``active`` [B] masks rows out of MoE routing (inactive decode slots
    must not claim expert capacity)."""
    B, S, M = x.shape
    h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = jnp.einsum("bsm,mhd->bshd", h, lp["wq"])
    k = jnp.einsum("bsm,mhd->bshd", h, lp["wk"])
    v = jnp.einsum("bsm,mhd->bshd", h, lp["wv"])
    # Rotary with per-slot positions.
    def rope_rows(x_b, pos_b):
        return rope(x_b[None], pos_b, cfg.rope_theta)[0]

    q = jax.vmap(rope_rows)(q, q_pos)
    k = jax.vmap(rope_rows)(k, q_pos)

    # Scatter new KV rows into the cache at start_pos per slot.
    def upd(cache_b, new_b, start_b):
        return jax.lax.dynamic_update_slice(
            cache_b, new_b.astype(cache_b.dtype), (start_b, 0, 0)
        )

    cache_k = jax.vmap(upd)(cache_k, k, start_pos)
    cache_v = jax.vmap(upd)(cache_v, v, start_pos)
    attn = _attend_cached(q, cache_k, cache_v, q_pos,
                          start_pos + S, cfg)
    x = x + jnp.einsum("bshd,hdm->bsm", attn.astype(x.dtype), lp["wo"])
    h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    if cfg.n_experts > 0:
        # MoE cached decode: the same static-capacity expert dispatch as
        # training (parallel/moe.py); the aux load-balancing loss is a
        # training-only term and is discarded here.
        from ..parallel.moe import moe_ffn

        token_mask = None
        if active is not None:
            token_mask = jnp.broadcast_to(
                active[:, None], h.shape[:2]
            )
        out, _aux = moe_ffn(
            h, lp["router"], lp["w_up"], lp["w_down"],
            k=cfg.top_k, capacity_factor=cfg.capacity_factor,
            w_gate=lp["w_gate"], token_mask=token_mask,
        )
        x = x + out
        return x, cache_k, cache_v
    up = jnp.einsum("bsm,mf->bsf", h, lp["w_up"])
    gate = jnp.einsum("bsm,mf->bsf", h, lp["w_gate"])
    h2 = jax.nn.silu(gate.astype(jnp.float32)).astype(up.dtype) * up
    x = x + jnp.einsum("bsf,fm->bsm", h2, lp["w_down"])
    return x, cache_k, cache_v


def forward_with_cache(
    params: Dict[str, Any],
    tokens: jax.Array,      # [B, S] — S tokens appended to each slot
    cache: KVCache,
    cfg: LlamaConfig,
    *,
    active: Optional[jax.Array] = None,  # [B] bool — rows to update
    last_index: Optional[jax.Array] = None,  # [B] logits position override
    append_len: Optional[jax.Array] = None,  # [B] real (unpadded) length
) -> Tuple[jax.Array, KVCache]:
    """Append ``tokens`` to each slot's sequence and return logits for the
    final appended position [B, V] plus the updated cache. Works for both
    prefill (S = prompt length, lengths 0) and decode (S = 1).

    ``last_index``/``append_len`` support BUCKETED prefill: tokens padded
    to a bucket length S still produce logits at the true final position
    and advance each slot's length by its true prompt length (padded cache
    rows beyond the length are never attended — masking is by length)."""
    B, S = tokens.shape
    start = cache.lengths
    q_pos = start[:, None] + jnp.arange(S)[None, :]
    x = params["embed"][tokens].astype(cfg.dtype)

    def body(carry, layer_in):
        x = carry
        lp, ck, cv = layer_in
        x, ck, cv = _layer_cached(cfg, lp, x, ck, cv, start, q_pos,
                                  active=active)
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v)
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    if last_index is None:
        last = x[:, -1]
    else:
        last = x[jnp.arange(B), last_index]
    logits = jnp.einsum("bm,mv->bv", last, params["lm_head"])
    active = jnp.ones((B,), bool) if active is None else active
    advance = append_len if append_len is not None else S
    lengths = jnp.where(active, cache.lengths + advance, cache.lengths)
    keep = active[:, None, None, None]
    new_k = jnp.where(keep[None], new_k, cache.k)
    new_v = jnp.where(keep[None], new_v, cache.v)
    return logits.astype(jnp.float32), KVCache(new_k, new_v, lengths)


class PagedKVCache(NamedTuple):
    """Paged KV cache: a SHARED pool of fixed-size token pages plus a
    per-slot page table (the TPU-static analogue of vLLM's PagedAttention
    — no reference counterpart; Ray stops at request batching). Memory is
    bounded by ``total_pages * page_size`` tokens ACROSS requests instead
    of ``max_batch * max_len`` each, so one long-context request coexists
    with many short ones; pages recycle the moment a request finishes.
    All shapes static for XLA. The pool is HEAD-MAJOR
    ([L, Hkv, P_total, page, Dh]) so the Pallas page-walk kernel blocks
    on (head, page) without a per-step transpose. Decode attention
    gathers each slot's pages (``jnp.take(ck, page_table, axis=1)``)
    into a window bounded by B * Pmax * page tokens — independent of
    pool size — and masks by length (see _attend_paged for the measured
    kernel-vs-gather tradeoff)."""

    k: jax.Array            # [L, Hkv, P_total, page, Dh] shared pool
    v: jax.Array            # [L, Hkv, P_total, page, Dh]
    page_table: jax.Array   # [B, P_max] int32 page ids per slot
    lengths: jax.Array      # [B] int32 valid tokens per slot

    @property
    def page_size(self) -> int:
        return self.k.shape[3]

    @staticmethod
    def create(cfg: LlamaConfig, batch: int, total_pages: int,
               page_size: int, max_pages_per_seq: int) -> "PagedKVCache":
        # Head-major pool: the Pallas page-walk kernel blocks on
        # (head, page) directly — no per-step pool transpose (which
        # would scale with POOL size and defeat paging).
        shape = (cfg.num_layers, cfg.num_kv_heads, total_pages,
                 page_size, cfg.dh)
        return PagedKVCache(
            k=jnp.zeros(shape, dtype=cfg.dtype),
            v=jnp.zeros(shape, dtype=cfg.dtype),
            page_table=jnp.zeros((batch, max_pages_per_seq),
                                 dtype=jnp.int32),
            lengths=jnp.zeros((batch,), dtype=jnp.int32),
        )


def _attend_paged_xla(q, ck, cv, page_table, lengths, cfg):
    """XLA fallback: gather each slot's pages into its logical
    [T, Hkv, Dh] view and attend densely (the gather output is small —
    only the slots' windows, bounded by B * Pmax * page tokens
    regardless of pool size; the Pallas kernel avoids even that)."""
    B = q.shape[0]
    q_pos = lengths[:, None]
    kp = jnp.take(ck, page_table, axis=1)  # [Hkv, B, Pmax, page, Dh]
    vp = jnp.take(cv, page_table, axis=1)
    Hkv, _, Pmax, page, Dh = kp.shape
    kp = kp.transpose(1, 2, 3, 0, 4).reshape(B, Pmax * page, Hkv, Dh)
    vp = vp.transpose(1, 2, 3, 0, 4).reshape(B, Pmax * page, Hkv, Dh)
    return _attend_cached(q, kp, vp, q_pos, lengths + 1, cfg)


def _attend_paged(q, ck, cv, page_table, lengths, cfg):
    """Single-token decode over the paged pool. Default: the XLA gather
    path — measured on chip (PERF_r04 paged section) its cost is bounded
    by the attention WINDOW (B * Pmax * page tokens), independent of
    pool size, and it edges out the Pallas page-walk kernel in the full
    decode step (2.04 vs 2.35 ms at pool=256 pages). The kernel
    (ops/paged_attention.py) stays available via
    RAY_TPU_PAGED_KERNEL=1 for shapes where the gather's window copy
    dominates (very long windows / tiny batch)."""
    from ..ops import paged_attention as pa

    page = ck.shape[2]
    if (
        _USE_PAGED_KERNEL
        and cfg.use_flash
        and pa.on_tpu()
        and pa.pageable(page, q.shape[-1])
    ):
        out = pa.paged_decode_attention(
            q[:, 0], ck, cv, page_table, lengths
        )
        return out[:, None]
    return _attend_paged_xla(q, ck, cv, page_table, lengths, cfg)


def _layer_paged_decode(cfg, lp, x, ck, cv, page_table, lengths,
                        page_ids, offsets, active):
    """One block, single-token decode against the paged pool. x [B,1,M];
    ck/cv [Hkv, P, page, Dh] (this layer's pool slice, carried by the
    layer scan); page_ids/offsets [B] name each slot's write cell for
    this token (inactive slots scatter to id -1 → dropped).

    Measured design note (PERF_r04): three structures were benchmarked
    on the real chip for the step's pool traffic — (a) this scan over
    per-layer slices, (b) an unrolled layer loop scattering/gathering
    the full [L, ...] pool with static layer indices + donation, and
    (c) the pre-head-major layout with a per-step pool transpose. (a)
    wins by 10x+ over (b) (XLA lowers the separated-advanced-index
    full-pool scatters and full-pool custom-call operands poorly) and
    strictly dominates (c). The residual pool-size dependence of (a) is
    the scan re-stacking its ys (one pool-sized copy per k/v per step)."""
    B = x.shape[0]
    page = ck.shape[2]
    h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = jnp.einsum("bsm,mhd->bshd", h, lp["wq"])
    k = jnp.einsum("bsm,mhd->bshd", h, lp["wk"])
    v = jnp.einsum("bsm,mhd->bshd", h, lp["wv"])
    q_pos = lengths[:, None]

    def rope_rows(x_b, pos_b):
        return rope(x_b[None], pos_b, cfg.rope_theta)[0]

    q = jax.vmap(rope_rows)(q, q_pos)
    k = jax.vmap(rope_rows)(k, q_pos)
    # Scatter this token's KV into each active slot's current page cell.
    # Inactive slots aim past the pool: -1 would WRAP to the last page
    # (NumPy semantics) and corrupt it; only >= n is truly dropped.
    n_pages = ck.shape[1]
    drop = jnp.where(active, page_ids, n_pages)
    ck = ck.at[:, drop, offsets].set(
        k[:, 0].astype(ck.dtype).transpose(1, 0, 2), mode="drop")
    cv = cv.at[:, drop, offsets].set(
        v[:, 0].astype(cv.dtype).transpose(1, 0, 2), mode="drop")
    attn = _attend_paged(q, ck, cv, page_table, lengths, cfg)
    x = x + jnp.einsum("bshd,hdm->bsm", attn.astype(x.dtype), lp["wo"])
    h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    if cfg.n_experts > 0:
        from ..parallel.moe import moe_ffn

        token_mask = jnp.broadcast_to(active[:, None], h.shape[:2])
        out, _aux = moe_ffn(
            h, lp["router"], lp["w_up"], lp["w_down"],
            k=cfg.top_k, capacity_factor=cfg.capacity_factor,
            w_gate=lp["w_gate"], token_mask=token_mask,
        )
        return x + out, ck, cv
    up = jnp.einsum("bsm,mf->bsf", h, lp["w_up"])
    gate = jnp.einsum("bsm,mf->bsf", h, lp["w_gate"])
    h2 = jax.nn.silu(gate.astype(jnp.float32)).astype(up.dtype) * up
    return x + jnp.einsum("bsf,fm->bsm", h2, lp["w_down"]), ck, cv


def paged_decode(
    params: Dict[str, Any],
    tokens: jax.Array,          # [B] one token per slot
    cache: PagedKVCache,
    cfg: LlamaConfig,
    *,
    active: jax.Array,          # [B] bool
) -> Tuple[jax.Array, PagedKVCache]:
    """One decode step over the paged pool: write each slot's token into
    its current page cell, attend over its gathered pages, return [B, V]
    logits and the updated cache."""
    B = tokens.shape[0]
    page = cache.page_size
    page_ids = cache.page_table[jnp.arange(B), cache.lengths // page]
    offsets = cache.lengths % page
    x = params["embed"][tokens][:, None].astype(cfg.dtype)

    def body(carry, layer_in):
        x = carry
        lp, ck, cv = layer_in
        x, ck, cv = _layer_paged_decode(
            cfg, lp, x, ck, cv, cache.page_table, cache.lengths,
            page_ids, offsets, active,
        )
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v)
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = jnp.einsum("bm,mv->bv", x[:, 0], params["lm_head"])
    lengths = jnp.where(active, cache.lengths + 1, cache.lengths)
    return logits.astype(jnp.float32), PagedKVCache(
        new_k, new_v, cache.page_table, lengths
    )


def paged_prefill(
    params: Dict[str, Any],
    tokens: jax.Array,          # [1, S_bucket] padded prompt
    real_len: jax.Array,        # [] int32 true prompt length
    cache: PagedKVCache,
    cfg: LlamaConfig,
    slot: int | jax.Array,
    pages: jax.Array,           # [S_bucket // page] page ids for this slot
) -> Tuple[jax.Array, PagedKVCache]:
    """Prefill one request through the dense single-row path, then scatter
    the resulting rows into the slot's pool pages. The bucket length must
    be a multiple of the page size (buckets are powers of two >= page)."""
    S = tokens.shape[1]
    page = cache.page_size
    small = KVCache.create(cfg, 1, S)
    logits, small = forward_with_cache(
        params, tokens, small, cfg,
        last_index=real_len[None] - 1, append_len=real_len[None],
    )
    n = S // page
    # [L, 1, S, Hkv, Dh] -> [L, Hkv, n, page, Dh] -> scatter at page ids.
    k_pages = small.k[:, 0].reshape(
        cfg.num_layers, n, page, cfg.num_kv_heads, cfg.dh
    ).transpose(0, 3, 1, 2, 4)
    v_pages = small.v[:, 0].reshape(
        cfg.num_layers, n, page, cfg.num_kv_heads, cfg.dh
    ).transpose(0, 3, 1, 2, 4)
    k = cache.k.at[:, :, pages].set(k_pages.astype(cache.k.dtype))
    v = cache.v.at[:, :, pages].set(v_pages.astype(cache.v.dtype))
    lengths = cache.lengths.at[slot].set(real_len)
    return logits, PagedKVCache(k, v, cache.page_table, lengths)


def sample_logits(logits: jax.Array, rng: jax.Array, *,
                  temperature: float = 0.0, top_k: int = 0) -> jax.Array:
    """Greedy (temperature 0) or temperature/top-k sampling. [B,V] → [B]."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        vals, _ = jax.lax.top_k(logits, top_k)
        kth = vals[:, -1][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def generate(
    params: Dict[str, Any],
    prompt: jax.Array,       # [B, S_prompt]
    cfg: LlamaConfig,
    *,
    max_new_tokens: int,
    max_len: Optional[int] = None,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_token: Optional[int] = None,
) -> jax.Array:
    """Simple batch generation (prefill + scan decode). Returns
    [B, max_new_tokens]."""
    B, S = prompt.shape
    max_len = max_len or (S + max_new_tokens)
    cache = KVCache.create(cfg, B, max_len)
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    logits, cache = forward_with_cache(params, prompt, cache, cfg)
    first = sample_logits(logits, rng, temperature=temperature)
    if max_new_tokens == 1:
        return first[:, None]

    def step(carry, key):
        tok, cache = carry
        logits, cache = forward_with_cache(params, tok[:, None], cache, cfg)
        nxt = sample_logits(logits, key, temperature=temperature)
        return (nxt, cache), nxt

    keys = jax.random.split(rng, max_new_tokens - 1)
    (_, _), rest = jax.lax.scan(step, (first, cache), keys)
    return jnp.concatenate([first[:, None], rest.T], axis=1)
