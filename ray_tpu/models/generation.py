"""Autoregressive generation with a static KV cache.

TPU-first decode path for the Llama family: all shapes static (XLA traces
once) — the cache is a fixed [L, B, T_max, Hkv, Dh] buffer updated with
dynamic_update_slice; per-slot lengths mask attention. Prefill and decode
are separate jitted programs (the standard TPU serving split: prefill is
compute-bound on the MXU, decode is HBM-bandwidth-bound).

No reference counterpart — Ray delegates model serving compute to user
code; this framework owns it (continuous batching sits on top in
ray_tpu.serve.llm).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .llama import LlamaConfig, rms_norm, rope


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, T, Hkv, Dh]
    v: jax.Array  # [L, B, T, Hkv, Dh]
    lengths: jax.Array  # [B] int32 — valid tokens per slot

    @staticmethod
    def create(cfg: LlamaConfig, batch: int, max_len: int) -> "KVCache":
        shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.dh)
        return KVCache(
            k=jnp.zeros(shape, dtype=cfg.dtype),
            v=jnp.zeros(shape, dtype=cfg.dtype),
            lengths=jnp.zeros((batch,), dtype=jnp.int32),
        )


def _attend_cached(q, ck, cv, q_pos, lengths, cfg):
    """q [B,S,H,D] against cache ck/cv [B,T,Hkv,D]; positions of q rows are
    q_pos [B,S]; cache rows >= lengths[b] (post-update) are masked."""
    B, S, H, D = q.shape
    T = ck.shape[1]
    rep = H // ck.shape[2]
    k = jnp.repeat(ck, rep, axis=2)
    v = jnp.repeat(cv, rep, axis=2)
    scores = jnp.einsum("bshd,bthd->bhst", q, k,
                        preferred_element_type=jnp.float32) * (D ** -0.5)
    t_idx = jnp.arange(T)[None, None, :]  # [1,1,T]
    causal = t_idx <= q_pos[:, :, None]  # [B,S,T]
    scores = jnp.where(causal[:, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def _layer_cached(cfg, lp, x, cache_k, cache_v, start_pos, q_pos,
                  active=None):
    """One block over cached KV. x [B,S,M]; start_pos [B] write offset;
    ``active`` [B] masks rows out of MoE routing (inactive decode slots
    must not claim expert capacity)."""
    B, S, M = x.shape
    h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
    q = jnp.einsum("bsm,mhd->bshd", h, lp["wq"])
    k = jnp.einsum("bsm,mhd->bshd", h, lp["wk"])
    v = jnp.einsum("bsm,mhd->bshd", h, lp["wv"])
    # Rotary with per-slot positions.
    def rope_rows(x_b, pos_b):
        return rope(x_b[None], pos_b, cfg.rope_theta)[0]

    q = jax.vmap(rope_rows)(q, q_pos)
    k = jax.vmap(rope_rows)(k, q_pos)

    # Scatter new KV rows into the cache at start_pos per slot.
    def upd(cache_b, new_b, start_b):
        return jax.lax.dynamic_update_slice(
            cache_b, new_b.astype(cache_b.dtype), (start_b, 0, 0)
        )

    cache_k = jax.vmap(upd)(cache_k, k, start_pos)
    cache_v = jax.vmap(upd)(cache_v, v, start_pos)
    attn = _attend_cached(q, cache_k, cache_v, q_pos,
                          start_pos + S, cfg)
    x = x + jnp.einsum("bshd,hdm->bsm", attn.astype(x.dtype), lp["wo"])
    h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
    if cfg.n_experts > 0:
        # MoE cached decode: the same static-capacity expert dispatch as
        # training (parallel/moe.py); the aux load-balancing loss is a
        # training-only term and is discarded here.
        from ..parallel.moe import moe_ffn

        token_mask = None
        if active is not None:
            token_mask = jnp.broadcast_to(
                active[:, None], h.shape[:2]
            )
        out, _aux = moe_ffn(
            h, lp["router"], lp["w_up"], lp["w_down"],
            k=cfg.top_k, capacity_factor=cfg.capacity_factor,
            w_gate=lp["w_gate"], token_mask=token_mask,
        )
        x = x + out
        return x, cache_k, cache_v
    up = jnp.einsum("bsm,mf->bsf", h, lp["w_up"])
    gate = jnp.einsum("bsm,mf->bsf", h, lp["w_gate"])
    h2 = jax.nn.silu(gate.astype(jnp.float32)).astype(up.dtype) * up
    x = x + jnp.einsum("bsf,fm->bsm", h2, lp["w_down"])
    return x, cache_k, cache_v


def forward_with_cache(
    params: Dict[str, Any],
    tokens: jax.Array,      # [B, S] — S tokens appended to each slot
    cache: KVCache,
    cfg: LlamaConfig,
    *,
    active: Optional[jax.Array] = None,  # [B] bool — rows to update
    last_index: Optional[jax.Array] = None,  # [B] logits position override
    append_len: Optional[jax.Array] = None,  # [B] real (unpadded) length
) -> Tuple[jax.Array, KVCache]:
    """Append ``tokens`` to each slot's sequence and return logits for the
    final appended position [B, V] plus the updated cache. Works for both
    prefill (S = prompt length, lengths 0) and decode (S = 1).

    ``last_index``/``append_len`` support BUCKETED prefill: tokens padded
    to a bucket length S still produce logits at the true final position
    and advance each slot's length by its true prompt length (padded cache
    rows beyond the length are never attended — masking is by length)."""
    B, S = tokens.shape
    start = cache.lengths
    q_pos = start[:, None] + jnp.arange(S)[None, :]
    x = params["embed"][tokens].astype(cfg.dtype)

    def body(carry, layer_in):
        x = carry
        lp, ck, cv = layer_in
        x, ck, cv = _layer_cached(cfg, lp, x, ck, cv, start, q_pos,
                                  active=active)
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v)
    )
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    if last_index is None:
        last = x[:, -1]
    else:
        last = x[jnp.arange(B), last_index]
    logits = jnp.einsum("bm,mv->bv", last, params["lm_head"])
    active = jnp.ones((B,), bool) if active is None else active
    advance = append_len if append_len is not None else S
    lengths = jnp.where(active, cache.lengths + advance, cache.lengths)
    keep = active[:, None, None, None]
    new_k = jnp.where(keep[None], new_k, cache.k)
    new_v = jnp.where(keep[None], new_v, cache.v)
    return logits.astype(jnp.float32), KVCache(new_k, new_v, lengths)


def sample_logits(logits: jax.Array, rng: jax.Array, *,
                  temperature: float = 0.0, top_k: int = 0) -> jax.Array:
    """Greedy (temperature 0) or temperature/top-k sampling. [B,V] → [B]."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        vals, _ = jax.lax.top_k(logits, top_k)
        kth = vals[:, -1][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def generate(
    params: Dict[str, Any],
    prompt: jax.Array,       # [B, S_prompt]
    cfg: LlamaConfig,
    *,
    max_new_tokens: int,
    max_len: Optional[int] = None,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_token: Optional[int] = None,
) -> jax.Array:
    """Simple batch generation (prefill + scan decode). Returns
    [B, max_new_tokens]."""
    B, S = prompt.shape
    max_len = max_len or (S + max_new_tokens)
    cache = KVCache.create(cfg, B, max_len)
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    logits, cache = forward_with_cache(params, prompt, cache, cfg)
    first = sample_logits(logits, rng, temperature=temperature)
    if max_new_tokens == 1:
        return first[:, None]

    def step(carry, key):
        tok, cache = carry
        logits, cache = forward_with_cache(params, tok[:, None], cache, cfg)
        nxt = sample_logits(logits, key, temperature=temperature)
        return (nxt, cache), nxt

    keys = jax.random.split(rng, max_new_tokens - 1)
    (_, _), rest = jax.lax.scan(step, (first, cache), keys)
    return jnp.concatenate([first[:, None], rest.T], axis=1)
