"""ResNet for image classification (the PR1 reference config:
ResNet-18 / CIFAR-10, BASELINE.json configs[0]).

flax.linen implementation; NHWC layout (TPU conv-native), bfloat16 compute
with float32 batch-norm statistics.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    small_images: bool = True  # CIFAR stem (3x3, no max-pool)

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=jnp.float32,
        )
        x = x.astype(self.dtype)
        if self.small_images:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        if not self.small_images:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = ResNetBlock(
                    self.num_filters * 2 ** i, strides=strides,
                    conv=conv, norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def resnet18(num_classes: int = 10, **kw) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], num_classes=num_classes, **kw)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    # Note: uses basic blocks (not bottleneck) — parity placeholder; the
    # benchmark configs use ResNet-18.
    return ResNet(stage_sizes=[3, 4, 6, 3], num_classes=num_classes, **kw)


def create_train_state(model: ResNet, rng: jax.Array, input_shape, tx):
    """Initialize params + batch stats + optimizer state."""
    import optax  # noqa: F401

    variables = model.init(rng, jnp.zeros(input_shape, jnp.float32), train=True)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    opt_state = tx.init(params)
    return {"params": params, "batch_stats": batch_stats,
            "opt_state": opt_state, "step": 0}
