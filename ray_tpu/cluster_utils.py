"""Single-machine multi-node test cluster.

Mirrors the reference's workhorse distributed-test pattern (ref:
python/ray/cluster_utils.py:108 Cluster — ``add_node`` at :174 starts extra
raylet+plasma processes on the same machine; killing a node =
``remove_node``). Here ``add_node`` spawns a ``ray_tpu.core.node_main``
process that registers with the head's GCS; ``remove_node`` kills it (and
its worker subprocesses), which the GCS detects as node death.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, Optional

from . import core as _core  # noqa: F401  (ensures package import order)
import ray_tpu


@dataclass
class NodeHandle:
    proc: subprocess.Popen
    session_dir: str
    resources: Dict[str, float]
    node_id_hex: Optional[str] = None
    labels: Dict[str, str] = field(default_factory=dict)


class Cluster:
    """Start a head (in-process driver) plus N simulated nodes."""

    def __init__(
        self,
        head_resources: Optional[Dict[str, float]] = None,
        system_config: Optional[Dict] = None,
    ):
        res = dict(head_resources or {"CPU": 2})
        num_cpus = res.pop("CPU", 2)
        self._driver = ray_tpu.init(
            num_cpus=int(num_cpus),
            resources=res or None,
            system_config=system_config,
        )
        nm = self._driver._nm
        assert nm.gcs_service is not None, "head must host the GCS"
        host, port = nm.gcs_service.address
        self.gcs_address = f"{host}:{port}"
        self.head_node_id = nm.node_id.hex()
        self._nodes: list[NodeHandle] = []

    # ------------------------------------------------------------------ nodes

    def add_node(
        self,
        *,
        num_cpus: float = 1,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        wait: bool = True,
    ) -> NodeHandle:
        res = dict(resources or {})
        res["CPU"] = num_cpus
        session_dir = os.path.join(
            tempfile.gettempdir(),
            "ray_tpu",
            f"node-{int(time.time())}-{uuid.uuid4().hex[:8]}",
        )
        os.makedirs(session_dir, exist_ok=True)
        env = dict(os.environ)
        env["RAY_TPU_GCS_ADDRESS"] = self.gcs_address
        env["RAY_TPU_SESSION_DIR"] = session_dir
        env["RAY_TPU_RESOURCES"] = json.dumps(res)
        env["RAY_TPU_NODE_LABELS"] = json.dumps(labels or {})
        from ray_tpu.core.config import get_config as _get_config

        if _get_config().session_token:
            env["RAY_TPU_SESSION_TOKEN"] = _get_config().session_token
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        existing_pp = env.get("PYTHONPATH", "")
        if pkg_root not in existing_pp.split(os.pathsep):
            env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + existing_pp if existing_pp else "")
            )
        log = open(os.path.join(session_dir, "node.log"), "wb")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node_main"],
            env=env,
            stdout=log,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        log.close()
        handle = NodeHandle(proc=proc, session_dir=session_dir,
                            resources=res, labels=dict(labels or {}))
        self._nodes.append(handle)
        if wait:
            self.wait_for_nodes(len(self._nodes) + 1)
            handle.node_id_hex = self._latest_node_id(exclude_known=True)
        return handle

    def _latest_node_id(self, exclude_known: bool = False) -> Optional[str]:
        known = {self.head_node_id} | {
            h.node_id_hex for h in self._nodes if h.node_id_hex
        }
        for view in self._driver.nodes():
            if view["state"] == "alive" and view["node_id"] not in known:
                return view["node_id"]
        return None

    def wait_for_nodes(self, count: int, timeout: float = 30.0):
        """Block until ``count`` nodes (head included) are alive."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            alive = [
                v for v in self._driver.nodes() if v["state"] == "alive"
            ]
            if len(alive) >= count:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"cluster did not reach {count} nodes within {timeout}s"
        )

    def remove_node(self, handle: NodeHandle, *, graceful: bool = False):
        """Kill a node's process tree; the GCS notices the closed
        connection and broadcasts node death (the chaos-test primitive —
        ref analogue: Cluster.remove_node + kill_raylet)."""
        self._nodes = [h for h in self._nodes if h is not handle]
        try:
            if graceful:
                handle.proc.terminate()
            else:
                # Kill the whole process group (node manager + its workers).
                os.killpg(os.getpgid(handle.proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            handle.proc.wait(timeout=10)
        except Exception:
            handle.proc.kill()

    # ------------------------------------------------------- rolling restart

    def rolling_restart(
        self,
        *,
        drain_timeout: Optional[float] = None,
    ) -> list:
        """Zero-downtime rolling node replacement (ref analogue: kuberay's
        drain-based rolling upgrade): for each worker node, in order —
        (1) start a same-shape replacement and wait for it to register,
        (2) drain the old node (``ray_tpu.drain_node``: schedulers stop
        targeting it, serve replicas surge-migrate, in-flight work
        finishes, primary object copies replicate off), (3) the drained
        node exits cleanly and is reaped. A live serve deployment keeps
        answering throughout. Returns ``[(old_hex, new_hex), ...]``."""
        import ray_tpu

        replaced = []
        for handle in list(self._nodes):
            old_hex = handle.node_id_hex
            res = dict(handle.resources)
            num_cpus = res.pop("CPU", 1)
            new = self.add_node(num_cpus=num_cpus,
                                resources=res or None,
                                labels=handle.labels or None)
            ray_tpu.drain_node(old_hex, timeout=drain_timeout)
            # The drained node exits on its own; give it a moment, then
            # reap whatever is left (remove_node tolerates an already-
            # exited process).
            try:
                handle.proc.wait(timeout=30)
            except Exception:
                pass
            self.remove_node(handle, graceful=True)
            replaced.append((old_hex, new.node_id_hex))
        return replaced

    # --------------------------------------------------------------- teardown

    def shutdown(self):
        for handle in list(self._nodes):
            self.remove_node(handle)
        ray_tpu.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
