"""rtpu — the cluster CLI.

Ref analogue: python/ray/scripts/scripts.py (`ray start/stop/status`) +
dashboard/modules/job/cli.py (`ray job submit/logs/list/stop`). Invoke as
``python -m ray_tpu.scripts.cli`` or ``python -m ray_tpu``.

Cluster bookkeeping lives under /tmp/ray_tpu/cluster/: the head writes
``address`` (host:port of its GCS) and every started node appends a
pidfile, which is what `rtpu stop` walks.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

CLUSTER_DIR = "/tmp/ray_tpu/cluster"
ADDRESS_FILE = os.path.join(CLUSTER_DIR, "address")
PID_DIR = os.path.join(CLUSTER_DIR, "pids")
LOG_DIR = os.path.join(CLUSTER_DIR, "logs")


def _read_default_address() -> Optional[str]:
    addr = os.environ.get("RAY_TPU_ADDRESS")
    if addr:
        return addr
    try:
        with open(ADDRESS_FILE) as f:
            return f.read().strip() or None
    except OSError:
        return None


def _resolve_address(args) -> str:
    addr = getattr(args, "address", None) or _read_default_address()
    if not addr:
        sys.exit(
            "no cluster address: pass --address, set RAY_TPU_ADDRESS, or "
            "run `rtpu start --head` on this machine first"
        )
    return addr


def _record_pid(kind: str, pid: int) -> None:
    os.makedirs(PID_DIR, exist_ok=True)
    with open(os.path.join(PID_DIR, f"{kind}-{pid}.pid"), "w") as f:
        f.write(str(pid))


# ---------------------------------------------------------------- start

def _run_head_blocking(args) -> int:
    """Run a head node (GCS + node manager + worker pool) until SIGTERM
    (ref: `ray start --head --block`)."""
    from ray_tpu.core.config import get_config, reset_config
    from ray_tpu.core.ids import NodeID
    from ray_tpu.core.node_manager import NodeManager
    from ray_tpu.core.tpu import node_tpu_labels

    reset_config()
    config = get_config()
    config.gcs_port = args.port
    config.node_ip = args.node_ip
    res = json.loads(args.resources) if args.resources else {}
    res.setdefault("CPU", args.num_cpus if args.num_cpus is not None
                   else os.cpu_count() or 1)
    if args.num_tpus is not None:
        res["TPU"] = args.num_tpus

    import tempfile
    import uuid

    session_dir = os.path.join(
        tempfile.gettempdir(), "ray_tpu",
        f"head-{int(time.time())}-{uuid.uuid4().hex[:8]}",
    )
    os.makedirs(session_dir, exist_ok=True)
    cluster_cfg = None
    if getattr(args, "cluster_config", None):
        from ray_tpu.autoscaler.cluster_config import load_cluster_config

        cluster_cfg = load_cluster_config(args.cluster_config)
        # With an autoscaler, shapes no node can serve yet must WAIT for
        # upscale instead of failing fast (config.infeasible_grace_s).
        config.infeasible_grace_s = float(
            cluster_cfg.get("infeasible_grace_s", 120.0)
        )
        head = cluster_cfg.get("head") or {}
        if "port" in head:
            config.gcs_port = int(head["port"])
        if "num_cpus" in head:
            res["CPU"] = float(head["num_cpus"])
        for k, v in (head.get("resources") or {}).items():
            res[k] = float(v)
    nm = NodeManager(
        NodeID.from_random(), session_dir, res, config,
        is_head=True, node_ip=args.node_ip, labels=node_tpu_labels(),
    )
    nm.start()
    host, port = nm.gcs_service.address
    address = f"{host}:{port}"
    os.makedirs(CLUSTER_DIR, exist_ok=True)
    with open(ADDRESS_FILE, "w") as f:
        f.write(address)
    _record_pid("head", os.getpid())
    print(f"ray_tpu head up at {address}")
    print(f"  connect drivers with ray_tpu.init(address={address!r})")
    print(f"  or: export RAY_TPU_ADDRESS={address}")
    sys.stdout.flush()

    scaler = None
    if cluster_cfg is not None:
        # `rtpu up`: the head hosts the autoscaler (ref: the monitor
        # process `ray up` starts beside the GCS).
        from ray_tpu.autoscaler.cluster_config import build_autoscaler

        scaler = build_autoscaler(
            cluster_cfg, address,
            nodes_fn=lambda: nm.call_sync(nm.cluster_nodes()),
        ).start()
        print(f"autoscaler: min={scaler.config.min_workers} "
              f"max={scaler.config.max_workers} "
              f"provider={cluster_cfg['provider']['type']}")
        sys.stdout.flush()

    stop = {"flag": False}

    def _term(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop["flag"]:
        time.sleep(0.2)
    if scaler is not None:
        scaler.shutdown(terminate_nodes=True)
    nm.shutdown()
    return 0


def _run_node_blocking(args) -> int:
    """Run a non-head node joined to --address (ref: `ray start
    --address`)."""
    import tempfile
    import uuid

    env = dict(os.environ)
    env["RAY_TPU_GCS_ADDRESS"] = _resolve_address(args)
    env["RAY_TPU_SESSION_DIR"] = os.path.join(
        tempfile.gettempdir(), "ray_tpu",
        f"node-{int(time.time())}-{uuid.uuid4().hex[:8]}",
    )
    res = json.loads(args.resources) if args.resources else {}
    res.setdefault("CPU", args.num_cpus if args.num_cpus is not None
                   else os.cpu_count() or 1)
    if args.num_tpus is not None:
        res["TPU"] = args.num_tpus
    env["RAY_TPU_RESOURCES"] = json.dumps(res)
    _record_pid("node", os.getpid())
    os.execvpe(
        sys.executable,
        [sys.executable, "-m", "ray_tpu.core.node_main"],
        env,
    )
    return 0  # unreachable


def cmd_start(args) -> int:
    if args.block:
        if args.head:
            return _run_head_blocking(args)
        return _run_node_blocking(args)
    # Detach: re-exec this command with --block in a background child.
    os.makedirs(LOG_DIR, exist_ok=True)
    kind = "head" if args.head else "node"
    log_path = os.path.join(LOG_DIR, f"{kind}-{int(time.time())}.log")
    cmd = [sys.executable, "-m", "ray_tpu.scripts.cli", "start", "--block"]
    for flag in ("head",):
        if getattr(args, flag):
            cmd.append(f"--{flag}")
    if args.address:
        cmd += ["--address", args.address]
    cmd += ["--port", str(args.port), "--node-ip", args.node_ip]
    if args.num_cpus is not None:
        cmd += ["--num-cpus", str(args.num_cpus)]
    if args.num_tpus is not None:
        cmd += ["--num-tpus", str(args.num_tpus)]
    if args.resources:
        cmd += ["--resources", args.resources]
    with open(log_path, "ab") as log:
        proc = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True,
        )
    _record_pid(kind, proc.pid)
    # Wait for the head to publish its address.
    if args.head:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            addr = _read_default_address()
            if addr:
                print(f"started head (pid {proc.pid}) at {addr}")
                print(f"logs: {log_path}")
                return 0
            if proc.poll() is not None:
                break
            time.sleep(0.1)
        sys.exit(f"head failed to start; see {log_path}")
    print(f"started node (pid {proc.pid}); logs: {log_path}")
    return 0


def cmd_up(args) -> int:
    """Start a head + autoscaler from a cluster YAML (ref: `ray up`).
    The head process hosts the autoscaler; workers come from the
    config's provider on demand."""
    from ray_tpu.autoscaler.cluster_config import load_cluster_config

    cfg = load_cluster_config(args.cluster_config)  # fail fast on errors
    # A stale address file (crashed head) or inherited RAY_TPU_ADDRESS
    # must not masquerade as the new cluster: clear the file and poll IT,
    # never the env fallback.
    try:
        os.unlink(ADDRESS_FILE)
    except OSError:
        pass
    os.makedirs(LOG_DIR, exist_ok=True)
    log_path = os.path.join(
        LOG_DIR, f"head-{cfg['cluster_name']}-{int(time.time())}.log"
    )
    node_ip = (cfg.get("head") or {}).get("node_ip", args.node_ip)
    cmd = [sys.executable, "-m", "ray_tpu.scripts.cli", "start",
           "--block", "--head",
           "--cluster-config", os.path.abspath(args.cluster_config),
           "--node-ip", str(node_ip), "--port", str(args.port)]
    with open(log_path, "ab") as log:
        proc = subprocess.Popen(cmd, stdout=log,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
    _record_pid("head", proc.pid)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        addr = None
        try:
            with open(ADDRESS_FILE) as f:
                addr = f.read().strip()
        except OSError:
            pass
        if addr:
            print(f"cluster {cfg['cluster_name']!r} up "
                  f"(head pid {proc.pid}) at {addr}")
            print(f"  connect: ray_tpu.init(address={addr!r})")
            print(f"  logs: {log_path}")
            return 0
        if proc.poll() is not None:
            break
        time.sleep(0.1)
    sys.exit(f"cluster failed to start; see {log_path}")


def cmd_down(args) -> int:
    """Tear the cluster down (ref: `ray down`): SIGTERM the head — its
    autoscaler terminates every provider-launched worker on the way
    out — then stop any other recorded local processes."""
    return cmd_stop(args)


def cmd_stop(args) -> int:
    """SIGTERM every recorded head/node process (ref: `ray stop`)."""
    count = 0
    if os.path.isdir(PID_DIR):
        for name in os.listdir(PID_DIR):
            path = os.path.join(PID_DIR, name)
            try:
                with open(path) as f:
                    pid = int(f.read().strip())
                os.kill(pid, signal.SIGTERM)
                count += 1
            except (OSError, ValueError):
                pass
            try:
                os.unlink(path)
            except OSError:
                pass
    try:
        os.unlink(ADDRESS_FILE)
    except OSError:
        pass
    print(f"stopped {count} process(es)")
    return 0


# ---------------------------------------------------------------- status

def _attached(args):
    import ray_tpu

    ray_tpu.init(address=_resolve_address(args), num_cpus=0)
    return ray_tpu


def cmd_status(args) -> int:
    """Cluster summary (ref: `ray status`)."""
    ray_tpu = _attached(args)
    try:
        nodes = ray_tpu.nodes()
        total = ray_tpu.cluster_resources()
        avail = ray_tpu.available_resources()
        print(f"nodes: {sum(1 for n in nodes if n['Alive'])} alive "
              f"/ {len(nodes)} total")
        for n in nodes:
            state = "alive" if n["Alive"] else "dead"
            labels = {k: v for k, v in n.get("Labels", {}).items()}
            print(f"  {n['NodeID'][:8]} {state:5s} host={n.get('Host')} "
                  f"resources={n['Resources']}"
                  + (f" labels={labels}" if labels else ""))
        print("resources:")
        for k in sorted(total):
            print(f"  {k}: {avail.get(k, 0):g}/{total[k]:g} available")
        from ray_tpu.util import state as state_api

        tasks = state_api.summarize_tasks()
        print(f"tasks: {tasks['by_state']} ({tasks['failed']} failed)")
        print(f"actors: {state_api.summarize_actors()}")
        return 0
    finally:
        ray_tpu.shutdown()


def cmd_nodes(args) -> int:
    """Node table with the membership-fence columns: cluster epoch,
    per-node incarnation, state (ref: `ray list nodes`, plus the fence
    plane's epoch/incarnation surface)."""
    ray_tpu = _attached(args)
    try:
        rows = ray_tpu.nodes()
        if args.json:
            print(json.dumps(rows, indent=2, default=str))
            return 0
        epoch = max((int(r.get("Epoch") or 0) for r in rows), default=0)
        print(f"cluster epoch: {epoch}")
        print(f"{'node':10s} {'state':9s} {'inc':>4s} {'head':5s} "
              f"{'host':16s} resources")
        for r in sorted(rows, key=lambda r: not r.get("IsHead", False)):
            print(
                f"{r['NodeID'][:8]:10s} "
                f"{(r.get('State') or ('alive' if r['Alive'] else 'dead')):9s} "
                f"{int(r.get('Incarnation') or 1):4d} "
                f"{'yes' if r.get('IsHead') else 'no':5s} "
                f"{str(r.get('Host') or ''):16s} "
                f"{r.get('Resources')}"
            )
        return 0
    finally:
        ray_tpu.shutdown()


def cmd_state(args) -> int:
    """List live tasks/actors/objects/workers/nodes (ref: `ray list`)."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.util import state as state_api

        fn = {
            "tasks": state_api.list_tasks,
            "actors": state_api.list_actors,
            "objects": state_api.list_objects,
            "workers": state_api.list_workers,
            "nodes": state_api.list_nodes,
        }[args.kind]
        rows = fn(limit=args.limit)
        print(json.dumps(rows, indent=2, default=str))
        return 0
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------- jobs

def cmd_submit(args) -> int:
    """Submit a job and stream its logs (ref: `ray job submit`)."""
    import ray_tpu
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    ray_tpu.init(address=_resolve_address(args), num_cpus=0)
    try:
        client = JobSubmissionClient()
        entrypoint = " ".join(args.entrypoint)
        job_id = client.submit_job(
            entrypoint=entrypoint,
            working_dir=args.working_dir,
        )
        print(f"submitted {job_id}: {entrypoint}")
        if args.no_wait:
            return 0
        for chunk in client.tail_job_logs(job_id):
            sys.stdout.write(chunk)
            sys.stdout.flush()
        status = client.get_job_status(job_id)
        print(f"\njob {job_id} {status.value}")
        return 0 if status == JobStatus.SUCCEEDED else 1
    finally:
        ray_tpu.shutdown()


def cmd_jobs(args) -> int:
    import ray_tpu
    from ray_tpu.job_submission import JobSubmissionClient

    ray_tpu.init(address=_resolve_address(args), num_cpus=0)
    try:
        client = JobSubmissionClient()
        for job_id in client.list_jobs():
            info = client.get_job_info(job_id)
            print(f"{job_id}  {info.get('status'):9s} "
                  f"{info.get('entrypoint', '')}")
        return 0
    finally:
        ray_tpu.shutdown()


def cmd_logs(args) -> int:
    import ray_tpu
    from ray_tpu.job_submission import JobSubmissionClient

    ray_tpu.init(address=_resolve_address(args), num_cpus=0)
    try:
        client = JobSubmissionClient()
        if args.follow:
            for chunk in client.tail_job_logs(args.job_id):
                sys.stdout.write(chunk)
                sys.stdout.flush()
        else:
            sys.stdout.write(client.get_job_logs(args.job_id))
        return 0
    finally:
        ray_tpu.shutdown()


def cmd_stop_job(args) -> int:
    import ray_tpu
    from ray_tpu.job_submission import JobSubmissionClient

    ray_tpu.init(address=_resolve_address(args), num_cpus=0)
    try:
        ok = JobSubmissionClient().stop_job(args.job_id)
        print("stopped" if ok else "stop failed")
        return 0 if ok else 1
    finally:
        ray_tpu.shutdown()


def _census_rows(census: dict) -> List[dict]:
    """Flatten a cluster_objects reply into per-object rows stamped
    with their holder node's hex id."""
    rows: List[dict] = []
    for node in census.get("nodes", ()):
        node_hex = node.get("node_id", "")
        for r in node.get("objects", ()):
            r = dict(r)
            r["node_id"] = node_hex
            rows.append(r)
    return rows


def _census_footer(census: dict) -> None:
    """Shared store/spill totals + unreachable-node footer of
    `rtpu memory` / `rtpu objects`."""
    used = cap = spilled = pulls = 0
    for node in census.get("nodes", ()):
        used += node.get("used_bytes") or 0
        cap += node.get("capacity_bytes") or 0
        spilled += node.get("spilled_bytes") or 0
        pulls += len(node.get("inflight_pulls") or ())
    print(f"store: {used / 1e6:.2f}/{cap / 1e6:.2f} MB used, "
          f"{spilled / 1e6:.2f} MB spilled, {pulls} pull(s) in flight")
    for node_hex, err in (census.get("errors") or {}).items():
        print(f"node {node_hex[:8]}: unreachable ({err})")


def cmd_memory(args) -> int:
    """Cluster object-store memory view (ref: `ray memory` —
    _private/internal_api.py memory_summary), census-backed: every
    node's object index merged, with lifecycle state + producer owner
    per row and totals by state / by owner."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.core import runtime_context

        rt = runtime_context.current_runtime()

        def render():
            try:
                census = rt.cluster_objects(limit=10_000)
            except Exception as e:
                print(f"object census unavailable: {e}")
                return
            rows = _census_rows(census)
            rows.sort(key=lambda r: -(r.get("size_bytes") or 0))
            by_state: dict = {}
            by_owner: dict = {}
            total = 0
            for r in rows:
                size = r.get("size_bytes") or 0
                st = r.get("state") or r.get("where") or "?"
                e = by_state.setdefault(st, [0, 0])
                e[0] += 1
                e[1] += size
                o = by_owner.setdefault(r.get("owner") or "?", [0, 0])
                o[0] += 1
                o[1] += size
                total += size
            shown = rows[:args.limit]
            print(f"{'OBJECT ID':42} {'SIZE':>12} {'REFS':>5} "
                  f"{'STATE':9} {'OWNER':16} NODE")
            for r in shown:
                print(f"{r['object_id']:42} "
                      f"{r.get('size_bytes') or 0:>12} "
                      f"{r.get('refcount', 0):>5} "
                      f"{(r.get('state') or r.get('where') or '?'):9} "
                      f"{(r.get('owner') or '?')[:16]:16} "
                      f"{r['node_id'][:8]}")
            label = f"TOTAL ({len(rows)} objects, {len(shown)} shown)"
            print(f"{label:42} {total:>12}")
            for st, (n, size) in sorted(by_state.items()):
                print(f"  {st}: {n} objects, {size / 1e6:.2f} MB")
            owners = sorted(by_owner.items(), key=lambda kv: -kv[1][1])
            if owners:
                print("by owner: " + "  ".join(
                    f"{name}={n}/{size / 1e6:.2f}MB"
                    for name, (n, size) in owners[:8]))
            _census_footer(census)

        return _watch_loop(render, getattr(args, "watch", None))
    finally:
        ray_tpu.shutdown()


def cmd_objects(args) -> int:
    """Cluster object census (ref: the GCS object table + `ray memory`,
    merged): top-N objects by size, the zero-ref leak candidates, or
    the spilled set — cluster-wide via the ObjectService fan-out."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.core import runtime_context

        rt = runtime_context.current_runtime()

        def render():
            try:
                census = rt.cluster_objects(limit=10_000)
            except Exception as e:
                print(f"object census unavailable: {e}")
                return
            rows = _census_rows(census)
            if args.leaked:
                rows = [r for r in rows
                        if r.get("zero_ref_s") is not None]
                rows.sort(key=lambda r: -(r.get("zero_ref_s") or 0))
                title = "zero-ref (leak-candidate) objects"
            elif args.spilled:
                rows = [r for r in rows if r.get("state") == "spilled"]
                rows.sort(key=lambda r: -(r.get("size_bytes") or 0))
                title = "spilled objects"
            else:
                rows.sort(key=lambda r: -(r.get("size_bytes") or 0))
                title = "objects by size"
            shown = rows[:args.top]
            if args.json:
                print(json.dumps({"objects": shown,
                                  "total": len(rows),
                                  "errors": census.get("errors") or {}},
                                 indent=2, default=str))
                return
            print(f"{title} ({len(shown)}/{len(rows)} shown)")
            print(f"{'OBJECT ID':42} {'SIZE':>12} {'STATE':9} "
                  f"{'REFS':>5} {'OWNER':16} {'AGE(s)':>8} "
                  f"{'0REF(s)':>8} NODE")
            for r in shown:
                age = r.get("age_s")
                zero = r.get("zero_ref_s")
                print(f"{r['object_id']:42} "
                      f"{r.get('size_bytes') or 0:>12} "
                      f"{(r.get('state') or r.get('where') or '?'):9} "
                      f"{r.get('refcount', 0):>5} "
                      f"{(r.get('owner') or '?')[:16]:16} "
                      f"{age if age is not None else '-':>8} "
                      f"{zero if zero is not None else '-':>8} "
                      f"{r['node_id'][:8]}")
            _census_footer(census)

        return _watch_loop(render, getattr(args, "watch", None))
    finally:
        ray_tpu.shutdown()


def cmd_transfers(args) -> int:
    """Data-plane transfer view: the per-link bandwidth matrix derived
    from ``ray_tpu_transfer_link_bytes_total`` in the head TSDB, spill
    churn, live stall gauges, and the in-flight pull aging table from
    the object census."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.core import runtime_context

        rt = runtime_context.current_runtime()
        window_s = float(args.window)

        def query(name):
            try:
                return rt.timeseries_query(name=name)["series"]
            except Exception:
                return []

        def render():
            print(f"rtpu transfers — {time.strftime('%H:%M:%S')} "
                  f"(window {int(window_s)}s)")
            links = []
            for s in query("ray_tpu_transfer_link_bytes_total"):
                tags = dict(tuple(kv) for kv in s.get("tags", []))
                inc, span = _ts_increase(s["samples"], window_s)
                last = s["samples"][-1][1] if s["samples"] else 0
                links.append((tags.get("src", "?"), tags.get("dst", "?"),
                              inc / span if span else 0.0, last))
            if links:
                print(f"\n{'SRC':10} {'DST':10} {'MB/s':>9} "
                      f"{'TOTAL(MB)':>11}")
                for src, dst, rate, total in sorted(
                        links, key=lambda l: -l[2]):
                    print(f"{src[:10]:10} {dst[:10]:10} "
                          f"{rate / 1e6:>9.2f} {total / 1e6:>11.2f}")
            else:
                print("no link traffic recorded")
            spill_bits = []
            for s in query("ray_tpu_spill_bytes_total"):
                tags = dict(tuple(kv) for kv in s.get("tags", []))
                inc, span = _ts_increase(s["samples"], window_s)
                if span and inc:
                    spill_bits.append(f"{tags.get('op', '?')} "
                                      f"{inc / span / 1e6:.2f} MB/s")
            if spill_bits:
                print("spill churn: " + ", ".join(spill_bits))
            stalled = [(dict(tuple(kv) for kv in s.get("tags", []))
                        .get("peer", "?"), s["samples"][-1][1])
                       for s in query("ray_tpu_object_transfer_stalled")
                       if s["samples"] and s["samples"][-1][1] > 0]
            if stalled:
                print("STALLED: " + ", ".join(
                    f"{int(n)} pull(s) from {peer}"
                    for peer, n in stalled))
            try:
                census = rt.cluster_objects(limit=1)
            except Exception as e:
                print(f"inflight pulls unavailable: {e}")
                return
            pulls = []
            for node in census.get("nodes", ()):
                for p in node.get("inflight_pulls", ()):
                    pulls.append((node.get("node_id", ""), p))
            if pulls:
                pulls.sort(key=lambda np: -(np[1].get("age_s") or 0))
                print(f"\n{'OBJECT':18} {'PEER':10} {'SIZE':>12} "
                      f"{'MOVED%':>7} {'AGE(s)':>8} {'IDLE(s)':>8} "
                      f"{'STATE':8} DEST")
                for node_hex, p in pulls:
                    size = p.get("size") or 0
                    pct = (100.0 * (p.get("bytes_moved") or 0) / size
                           if size else 0.0)
                    state = "STALLED" if p.get("stalled") else "moving"
                    print(f"{(p.get('oid') or '?')[:18]:18} "
                          f"{(p.get('peer') or '?')[:10]:10} "
                          f"{size:>12} {pct:>7.1f} "
                          f"{p.get('age_s', 0):>8.1f} "
                          f"{p.get('idle_s', 0):>8.1f} "
                          f"{state:8} {node_hex[:8]}")
            else:
                print("no pulls in flight")

        return _watch_loop(render, getattr(args, "watch", None))
    finally:
        ray_tpu.shutdown()


def cmd_stack(args) -> int:
    """Cluster-wide stack dumps: head + every node manager + every live
    worker (ref: `ray stack`, generalized past the local node)."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.util import profiler

        reply = profiler.cluster_stacks(timeout=args.timeout)
        if args.json:
            print(json.dumps(reply, indent=2, default=str))
            return 0
        for node in reply.get("nodes", ()):
            node_hex = node.get("node_id", "")
            if args.node and not node_hex.startswith(args.node):
                continue
            head = " (head)" if node.get("is_head") else ""
            print(f"=== node {node_hex[:8]}{head}")
            for proc in node.get("procs", ()):
                wid = proc.get("worker_id") or ""
                if args.worker and not wid.startswith(args.worker):
                    continue
                tag = f" worker={wid[:8]}" if wid else ""
                print(f"--- pid {proc.get('pid')} "
                      f"[{proc.get('kind')}]{tag}")
                print(profiler.format_stack_text(
                    proc.get("threads", [])
                ))
            for wid in node.get("missing_workers", ()):
                print(f"--- worker={wid[:8]}: no reply (dead or wedged)")
        for node_hex, err in (reply.get("errors") or {}).items():
            print(f"=== node {node_hex[:8]}: unreachable ({err})",
                  file=sys.stderr)
        return 0
    finally:
        ray_tpu.shutdown()


def cmd_profile(args) -> int:
    """Cluster-wide sampled wall-clock profile, exported as folded
    collapsed stacks or speedscope JSON (ref: the dashboard reporter's
    py-spy profiles, dependency-free and cluster-wide)."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.util import profiler

        reply = profiler.cluster_profile(seconds=args.seconds,
                                         hz=args.hz)
        merged = profiler.merge_cluster_profile(reply)
        for node_hex, err in merged["errors"].items():
            print(f"node {node_hex[:8]}: unreachable ({err})",
                  file=sys.stderr)
        if args.format == "speedscope":
            out = json.dumps(profiler.to_speedscope(
                merged["counts"],
                name=f"rtpu profile ({args.seconds}s @ {args.hz}Hz)",
            ))
        else:
            out = profiler.to_folded(merged["counts"])
        if args.output:
            with open(args.output, "w") as f:
                f.write(out)
            print(f"wrote {merged['samples']} samples across "
                  f"{len(reply.get('nodes', []))} node(s) to "
                  f"{args.output}", file=sys.stderr)
        else:
            sys.stdout.write(out)
        return 0
    finally:
        ray_tpu.shutdown()


def _format_event(e) -> str:
    ts = time.strftime("%H:%M:%S", time.localtime(e.get("ts", 0)))
    node = (e.get("node_id") or "")[:8] or "-"
    msg = e.get("message", "")
    # Events emitted inside an active span carry the request's trace id:
    # copy it straight into `rtpu trace <id>` for the full waterfall.
    trace = e.get("trace_id")
    suffix = f" trace={trace}" if trace else ""
    return (f"{ts} {e.get('severity', '?'):7s} {e.get('source', '?'):12s} "
            f"node={node} {msg}{suffix}")


def cmd_events(args) -> int:
    """Aggregated cluster event log (ref: `ray list cluster-events`),
    optionally following new events live off the pubsub channel."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.util import state as state_api
        from ray_tpu.util.pubsub import CLUSTER_EVENTS, Subscriber

        # Subscribe BEFORE fetching the snapshot so events published in
        # between land in the subscription queue instead of vanishing;
        # overlap is deduped by event_id below.
        sub = Subscriber(channels=[CLUSTER_EVENTS]) if args.follow else None
        rows = state_api.list_cluster_events(
            severity=args.severity, source=args.source, limit=args.limit
        )
        if args.json:
            print(json.dumps(rows, indent=2, default=str))
        else:
            for e in rows:
                print(_format_event(e))
        if sub is None:
            return 0
        seen = {e.get("event_id") for e in rows}
        try:
            while True:
                for ev in sub.poll(timeout=10.0):
                    batch = ev["data"]
                    if not isinstance(batch, list):
                        batch = [batch]
                    for e in batch:
                        if e.get("event_id") in seen:
                            continue
                        if args.severity and \
                                e.get("severity") != args.severity:
                            continue
                        if args.source and e.get("source") != args.source:
                            continue
                        print(json.dumps(e, default=str) if args.json
                              else _format_event(e))
                        sys.stdout.flush()
        except KeyboardInterrupt:
            return 0
        finally:
            sub.close()
    finally:
        ray_tpu.shutdown()


def cmd_trace(args) -> int:
    """Tail-sampled flight recorder: list retained request records
    (slow / shed / deadline-expired / errored / chaos-hit) aggregated
    cluster-wide, or — with a trace id — print that request's full
    waterfall joined from the span timeline."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.util import flight_recorder

        if args.trace_id:
            tree = flight_recorder.waterfall(args.trace_id)
            if args.json:
                print(json.dumps(tree, indent=2, default=str))
            else:
                print(flight_recorder.format_waterfall(tree))
            return 0
        reason = None
        for flag, value in (("slow", "slow"), ("errors", "error"),
                            ("shed", "shed"), ("expired", "expired"),
                            ("chaos", "chaos"), ("slow_ops", "slow_op"),
                            ("stalled", "stalled_pull")):
            if getattr(args, flag, False):
                reason = value
        rows = flight_recorder.list_cluster(reason=reason,
                                            limit=args.limit)
        if args.json:
            print(json.dumps(rows, indent=2, default=str))
            return 0
        if not rows:
            print("flight recorder: no retained requests"
                  + (f" (reason={reason})" if reason else ""))
            return 0
        print(f"{'WHEN':8} {'REASON':8} {'STATUS':18} {'MS':>9} "
              f"{'TRACE':32} NAME")
        for r in rows:
            when = time.strftime("%H:%M:%S", time.localtime(r["ts"]))
            print(f"{when:8} {r['reason']:8} {r['status'][:18]:18} "
                  f"{r['duration_s'] * 1e3:>9.1f} "
                  f"{(r.get('trace_id') or '-'):32} {r['name']}")
        print(f"({len(rows)} record(s); `rtpu trace <trace-id>` for a "
              f"waterfall)")
        return 0
    finally:
        ray_tpu.shutdown()


def cmd_summary(args) -> int:
    """Task/actor/object summaries including the retained failure
    history (ref: `ray summary tasks`)."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.util import state as state_api

        tasks = state_api.summarize_tasks()
        if args.json:
            print(json.dumps({
                "tasks": tasks,
                "actors": state_api.summarize_actors(),
                "objects": state_api.summarize_objects(),
            }, indent=2, default=str))
            return 0
        print(f"tasks: {tasks['total']} total, {tasks['failed']} failed")
        for st, n in sorted(tasks["by_state"].items()):
            print(f"  {st}: {n}")
        if tasks["per_func"]:
            print(f"{'FUNC':30} {'COUNT':>6} {'FAILED':>6} "
                  f"{'MEAN(s)':>10} {'MAX(s)':>10}")
            for name, f in sorted(tasks["per_func"].items()):
                mean = (f"{f['mean_duration_s']:.4f}"
                        if f["mean_duration_s"] is not None else "-")
                mx = (f"{f['max_duration_s']:.4f}"
                      if f["max_duration_s"] is not None else "-")
                print(f"{name[:30]:30} {f['count']:>6} {f['failed']:>6} "
                      f"{mean:>10} {mx:>10}")
        print(f"actors: {state_api.summarize_actors()}")
        objs = state_api.summarize_objects()
        print(f"objects: {objs['total_objects']} "
              f"({objs['total_size_bytes'] / 1e6:.2f} MB) "
              f"by_location={objs['by_location']}")
        return 0
    finally:
        ray_tpu.shutdown()


def _watch_loop(render, interval: Optional[float]) -> int:
    """Shared render loop of `rtpu top` / `rtpu slo` / `rtpu metrics
    --watch`: repaint every ``interval`` seconds until ^C exits cleanly
    (one shot when ``interval`` is falsy). The ANSI home+clear repaint
    keeps a live view flicker-free without curses."""
    if not interval:
        render()
        return 0
    try:
        while True:
            sys.stdout.write("\x1b[H\x1b[2J")
            render()
            sys.stdout.flush()
            time.sleep(interval)
    except KeyboardInterrupt:
        print()
        return 0


def cmd_metrics(args) -> int:
    """Dump the Prometheus exposition document (ref: scraping the
    dashboard's /metrics endpoint, without needing it up): core node
    counters/histograms of the attached node plus cluster-wide user,
    serve, and device series aggregated from the KV pipeline (the
    ``ray_tpu_object_transfer_*`` data-plane series ride the same
    document). ``--transfers`` prints the object-transfer plane and
    ``--actors`` the direct actor-call plane as human-readable sections
    instead; ``--watch N`` refreshes the chosen view every N seconds."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.util import prometheus

        def render():
            if getattr(args, "transfers", False):
                _print_transfer_section()
            elif getattr(args, "actors", False):
                _print_actor_section()
            elif getattr(args, "serve", False):
                _print_serve_section()
            else:
                sys.stdout.write(prometheus.render())

        return _watch_loop(render, getattr(args, "watch", None))
    finally:
        ray_tpu.shutdown()


def _ts_increase(rows: List[List[float]], window_s: float,
                 idx: int = 1) -> tuple:
    """(increase, span_s) of one TSDB sample list over the trailing
    window — reset robust like TSDB.delta, computed client-side from
    the raw ``[ts, ...]`` rows the query RPC returns."""
    if len(rows) < 2:
        return 0.0, 0.0
    start = rows[-1][0] - window_s
    win: List[List[float]] = []
    for r in rows:
        if r[0] < start:
            win[:] = [r]
        else:
            win.append(r)
    if len(win) < 2:
        return 0.0, 0.0
    inc = sum(max(0.0, b[idx] - a[idx]) for a, b in zip(win, win[1:]))
    return inc, max(win[-1][0] - win[0][0], 1e-9)


def _ts_group(series: List[dict], key: str) -> dict:
    """Group a timeseries_query result by one tag value."""
    out: dict = {}
    for s in series:
        tags = dict(tuple(kv) for kv in s.get("tags", []))
        out.setdefault(tags.get(key, ""), []).append(s)
    return out


def _render_top(rt, window_s: float) -> None:
    def query(name, tags=None):
        try:
            return rt.timeseries_query(name=name, tags=tags)["series"]
        except Exception:
            return []

    try:
        stats = rt.timeseries_query()["stats"]
    except Exception:
        stats = {}
    try:
        nodes = [n for n in rt.nodes() if n.get("state") == "alive"]
    except Exception:
        nodes = []
    print(f"rtpu top — {time.strftime('%H:%M:%S')}   "
          f"nodes={len(nodes)}   tsdb: {stats.get('series', 0)}/"
          f"{stats.get('max_series', '?')} series, "
          f"{stats.get('samples', 0)} samples, "
          f"dropped={stats.get('dropped', 0)}")

    # Per-node resources: CPU via counter->rate of the per-process cpu
    # seconds, RSS as the latest per-process sum, HBM from the device
    # gauges (absent off-TPU).
    cpu_by = _ts_group(query("ray_tpu_process_cpu_seconds_total"), "node")
    rss_by = _ts_group(query("ray_tpu_process_rss_bytes"), "node")
    hbm_by = _ts_group(query("ray_tpu_device_memory_bytes_in_use"),
                       "node")
    print(f"\n{'NODE':14} {'PROCS':>5} {'CPU%':>7} {'RSS(MB)':>9} "
          f"{'HBM(MB)':>9}")
    for node in sorted(set(cpu_by) | set(rss_by)):
        inc = span = 0.0
        for s in cpu_by.get(node, ()):
            i, sp = _ts_increase(s["samples"], window_s)
            inc += i
            span = max(span, sp)
        cpu_pct = 100.0 * inc / span if span else 0.0
        rss = sum(s["samples"][-1][1] for s in rss_by.get(node, ())
                  if s["samples"])
        hbm = sum(s["samples"][-1][1] for s in hbm_by.get(node, ())
                  if s["samples"])
        nprocs = max(len(cpu_by.get(node, ())),
                     len(rss_by.get(node, ())))
        hbm_s = f"{hbm / 1e6:>9.1f}" if hbm else f"{'-':>9}"
        print(f"{(node or '<head>')[:14]:14} {nprocs:>5} {cpu_pct:>7.1f} "
              f"{rss / 1e6:>9.1f} {hbm_s}")

    # Serve data path per deployment: qps + p99 from the processing
    # histogram, shed rate from the shed counter.
    lat_by = _ts_group(
        query("ray_tpu_serve_replica_processing_seconds"), "deployment")
    shed_by = _ts_group(query("ray_tpu_serve_shed_total"), "deployment")
    if lat_by or shed_by:
        print(f"\n{'DEPLOYMENT':20} {'QPS':>8} {'p99(ms)':>9} "
              f"{'SHED/s':>8}")
    for dep in sorted(set(lat_by) | set(shed_by)):
        inc = span = 0.0
        for s in lat_by.get(dep, ()):
            i, sp = _ts_increase(s["samples"], window_s)
            inc += i
            span = max(span, sp)
        qps = inc / span if span else 0.0
        shed = shed_span = 0.0
        for s in shed_by.get(dep, ()):
            i, sp = _ts_increase(s["samples"], window_s)
            shed += i
            shed_span = max(shed_span, sp)
        shed_rate = shed / shed_span if shed_span else 0.0
        p99 = None
        try:
            from ray_tpu.util.metrics import get_metrics_report
            from ray_tpu.util.tsdb import quantile_from_histogram

            h = (get_metrics_report()
                 .get("ray_tpu_serve_replica_processing_seconds", {})
                 .get("series", {}))
            bounds: List[float] = []
            buckets: List[float] = []
            for tags_key, v in h.items():
                if dict(tags_key).get("deployment") != dep:
                    continue
                if not isinstance(v, dict):
                    continue
                if not bounds:
                    bounds = list(v.get("bounds", ()))
                    buckets = list(v.get("buckets", ()))
                elif list(v.get("bounds", ())) == bounds:
                    buckets = [a + b for a, b in
                               zip(buckets, v.get("buckets", ()))]
            if bounds:
                p99 = quantile_from_histogram(bounds, buckets, 0.99)
        except Exception:
            p99 = None
        p99_s = f"{p99 * 1e3:>9.1f}" if p99 is not None else f"{'-':>9}"
        print(f"{dep[:20]:20} {qps:>8.1f} {p99_s} {shed_rate:>8.2f}")

    # Dispatch plane: direct actor-call ops/s across the cluster.
    inc = span = 0.0
    for s in query("ray_tpu_actor_call_seconds"):
        i, sp = _ts_increase(s["samples"], window_s)
        inc += i
        span = max(span, sp)
    if span:
        print(f"\ndispatch: {inc / span:.1f} actor-call ops/s "
              f"(last {int(window_s)}s)")

    # Control plane: per-service frame-dispatch rate + backlog, and
    # event-loop health (`rtpu rpc` breaks this down per op).
    svc_rate = {}
    for svc, series in _ts_group(
            query("ray_tpu_rpc_server_seconds"), "service").items():
        inc = span = 0.0
        for s in series:
            tags = dict(tuple(kv) for kv in s.get("tags", []))
            if tags.get("stage") != "handler":
                continue
            i, sp = _ts_increase(s["samples"], window_s)
            inc += i
            span = max(span, sp)
        if span:
            svc_rate[svc] = inc / span
    if svc_rate:
        backlog = {svc: (series[-1]["samples"][-1][1]
                         if series and series[-1]["samples"] else 0.0)
                   for svc, series in _ts_group(
                       query("ray_tpu_rpc_backlog"), "service").items()}
        print("control plane: " + "  ".join(
            f"{svc}={rate:.0f} ops/s"
            + (f" (backlog {backlog[svc]:.0f})"
               if backlog.get(svc) else "")
            for svc, rate in sorted(svc_rate.items())))
    lag_bits = []
    for loop_name, series in sorted(_ts_group(
            query("ray_tpu_event_loop_lag_seconds"), "loop").items()):
        worst = max((s["samples"][-1][1] for s in series
                     if s["samples"]), default=0.0)
        lag_bits.append(f"{loop_name} {worst * 1e3:.1f}ms")
    gil = [s["samples"][-1][1]
           for s in query("ray_tpu_gil_wait_ratio") if s["samples"]]
    if lag_bits or gil:
        gil_s = (f"   gil wait ratio max {max(gil):.2f}" if gil else "")
        print("loops: " + ", ".join(lag_bits) + gil_s)

    # Data plane: aggregate link bandwidth + spill churn + the live
    # stall/leak gauges (`rtpu transfers` / `rtpu objects` break these
    # down per link / per object).
    inc = span = 0.0
    for s in query("ray_tpu_transfer_link_bytes_total"):
        i, sp = _ts_increase(s["samples"], window_s)
        inc += i
        span = max(span, sp)
    spill = spill_span = 0.0
    for s in query("ray_tpu_spill_bytes_total"):
        i, sp = _ts_increase(s["samples"], window_s)
        spill += i
        spill_span = max(spill_span, sp)
    stalled = sum(s["samples"][-1][1]
                  for s in query("ray_tpu_object_transfer_stalled")
                  if s["samples"])
    leaked = max((s["samples"][-1][1]
                  for s in query("ray_tpu_object_leaked_total")
                  if s["samples"]), default=0.0)
    leaked_b = max((s["samples"][-1][1]
                    for s in query("ray_tpu_object_leaked_bytes")
                    if s["samples"]), default=0.0)
    bits = []
    if span and inc:
        bits.append(f"links {inc / span / 1e6:.1f} MB/s")
    if spill_span and spill:
        bits.append(f"spill {spill / spill_span / 1e6:.1f} MB/s")
    if stalled:
        bits.append(f"STALLED pulls {int(stalled)}")
    if leaked:
        bits.append(f"leaked {int(leaked)} obj "
                    f"({leaked_b / 1e6:.1f} MB)")
    if bits:
        print("data plane: " + ", ".join(bits))


def cmd_top(args) -> int:
    """Live refreshing cluster view (ref: `ray status` + the dashboard
    front page, in a terminal): per-node CPU/RSS/HBM from the head
    TSDB, serve qps/p99/shed per deployment, dispatch ops/s."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.core import runtime_context

        rt = runtime_context.current_runtime()
        interval = None if getattr(args, "once", False) else args.interval
        return _watch_loop(
            lambda: _render_top(rt, float(args.window)), interval)
    finally:
        ray_tpu.shutdown()


def _render_rpc(rt, window_s: float, top_n: int,
                as_json: bool = False) -> None:
    """Per-op control-plane dispatch table from the head TSDB: qps +
    per-stage means client-side from the raw count/sum rows, p50/p99
    head-derived from the merged bucket deltas (buckets never leave
    the head)."""
    try:
        series = rt.timeseries_query(
            name="ray_tpu_rpc_server_seconds")["series"]
    except Exception as e:
        print(f"rpc stats unavailable: {e}")
        return
    by_op: dict = {}
    for s in series:
        tags = dict(tuple(kv) for kv in s.get("tags", []))
        key = (tags.get("service", ""), tags.get("op", ""))
        by_op.setdefault(key, {}).setdefault(
            tags.get("stage", ""), []).append(s)
    rows = []
    for (service, op), stages in by_op.items():
        row = {"service": service, "op": op, "qps": 0.0}
        for stage in ("queue_wait", "handler", "reply_send"):
            inc = sum_inc = span = 0.0
            for s in stages.get(stage, ()):
                i, sp = _ts_increase(s["samples"], window_s, idx=1)
                si, _ = _ts_increase(s["samples"], window_s, idx=2)
                inc += i
                sum_inc += si
                span = max(span, sp)
            row[stage + "_ms"] = (sum_inc / inc * 1e3) if inc else 0.0
            if stage == "handler" and span:
                row["qps"] = inc / span
                row["calls"] = inc
        rows.append(row)
    rows.sort(key=lambda r: -r["qps"])
    if top_n and top_n > 0:
        rows = rows[:top_n]
    # Quantiles only for the displayed rows (one derivation RPC per op).
    for row in rows:
        for q, key in ((0.5, "p50_ms"), (0.99, "p99_ms")):
            row[key] = None
            try:
                d = rt.timeseries_query(
                    name="ray_tpu_rpc_server_seconds",
                    tags={"service": row["service"], "op": row["op"],
                          "stage": "handler"},
                    quantile=q, window=window_s).get("derived") or {}
                if d.get("quantile") is not None:
                    row[key] = d["quantile"] * 1e3
            except Exception:
                pass

    def latest_by(name, key):
        try:
            got = rt.timeseries_query(name=name)["series"]
        except Exception:
            return {}
        out = {}
        for s in got:
            tags = dict(tuple(kv) for kv in s.get("tags", []))
            if s["samples"]:
                k = tags.get(key, "")
                out[k] = max(out.get(k, 0.0), s["samples"][-1][1])
        return out

    backlog = latest_by("ray_tpu_rpc_backlog", "service")
    inflight = latest_by("ray_tpu_rpc_inflight", "service")
    lag = latest_by("ray_tpu_event_loop_lag_seconds", "loop")
    gil = latest_by("ray_tpu_gil_wait_ratio", "pid")
    if as_json:
        print(json.dumps({"ops": rows, "backlog": backlog,
                          "inflight": inflight, "loop_lag_s": lag,
                          "gil_wait_ratio": gil},
                         indent=2, sort_keys=True))
        return
    print(f"rtpu rpc — {time.strftime('%H:%M:%S')}   window "
          f"{int(window_s)}s")
    if not rows:
        print("no control-plane ops recorded yet")
    else:
        print(f"\n{'SERVICE':8} {'OP':22} {'QPS':>8} {'p50(ms)':>8} "
              f"{'p99(ms)':>8} {'q-wait':>7} {'handler':>8} "
              f"{'reply':>6}")
        for r in rows:
            p50 = f"{r['p50_ms']:>8.2f}" if r.get("p50_ms") is not None \
                else f"{'-':>8}"
            p99 = f"{r['p99_ms']:>8.2f}" if r.get("p99_ms") is not None \
                else f"{'-':>8}"
            print(f"{r['service'][:8]:8} {r['op'][:22]:22} "
                  f"{r['qps']:>8.1f} {p50} {p99} "
                  f"{r['queue_wait_ms']:>7.2f} {r['handler_ms']:>8.2f} "
                  f"{r['reply_send_ms']:>6.2f}")
    if backlog or inflight:
        print("\nbacklog:  " + "  ".join(
            f"{svc}={int(v)}" for svc, v in sorted(backlog.items()))
            + "   inflight:  " + "  ".join(
            f"{svc}={int(v)}" for svc, v in sorted(inflight.items())))
    if lag:
        print("loop lag: " + "  ".join(
            f"{name}={v * 1e3:.1f}ms" for name, v in sorted(lag.items())))
    if gil:
        print("gil wait: " + "  ".join(
            f"pid {pid}={v:.2f}" for pid, v in sorted(gil.items())))


def cmd_rpc(args) -> int:
    """Control-plane dispatch stats: per-op qps + stage latency
    breakdown (queue-wait/handler/reply-send) from the
    ``ray_tpu_rpc_server_seconds`` histograms, plus backlog/inflight
    gauges, event-loop lag, and the GIL-contention proxy."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.core import runtime_context

        rt = runtime_context.current_runtime()
        return _watch_loop(
            lambda: _render_rpc(rt, float(args.window), args.top,
                                as_json=getattr(args, "json", False)),
            getattr(args, "watch", None))
    finally:
        ray_tpu.shutdown()


def _render_slo(rt, as_json: bool) -> None:
    try:
        status = rt.slo_status()
    except Exception as e:
        print(f"slo status unavailable: {e}")
        return
    deployments = status.get("deployments", {})
    if as_json:
        print(json.dumps(status, indent=2, sort_keys=True))
        return
    if not deployments:
        print("no SLOs declared (serve.deploy(..., slo={...}))")
        return
    print(f"{'DEPLOYMENT':20} {'WINDOW':>8} {'GOODPUT':>9} "
          f"{'BURN':>7}  ALERTS")
    for dep, st in sorted(deployments.items()):
        alerts = ",".join(
            p[:-len("_burn_active")] for p, v in sorted(st.items())
            if p.endswith("_burn_active") and v
        ) or "-"
        first = True
        windows = st.get("goodput", {})
        for w in sorted(windows, key=lambda x: float(x)):
            g = windows[w]
            b = st.get("burn", {}).get(w, 0.0)
            print(f"{(dep if first else '')[:20]:20} {w + 's':>8} "
                  f"{g:>9.4f} {b:>7.2f}  "
                  f"{alerts if first else ''}")
            first = False
        rem = st.get("budget_remaining")
        if rem is not None:
            print(f"{'':20} budget remaining: {rem:.4f}")


def cmd_slo(args) -> int:
    """Per-deployment SLO status: goodput SLIs, multi-window error-
    budget burn rates, alert state (the engine's latest evaluation)."""
    ray_tpu = _attached(args)
    try:
        from ray_tpu.core import runtime_context

        rt = runtime_context.current_runtime()
        return _watch_loop(
            lambda: _render_slo(rt, getattr(args, "json", False)),
            getattr(args, "watch", None))
    finally:
        ray_tpu.shutdown()


def _print_serve_section() -> None:
    """Serve overload-control plane of `rtpu metrics`: shed / deadline /
    breaker / retry counters aggregated cluster-wide from the KV metrics
    pipeline (every proxy, handle and replica process flushes into it),
    plus request/status totals for context."""
    from ray_tpu.util.metrics import get_metrics_report

    try:
        report = get_metrics_report()
    except Exception:
        report = {}

    def series(name):
        return report.get(name, {}).get("series", {})

    def by_tag(name, key):
        out = {}
        for tags_key, v in series(name).items():
            if not isinstance(v, (int, float)):
                continue
            tags = dict(tags_key)
            label = ",".join(
                f"{k}={tags[k]}" for k in sorted(tags) if k != key
            )
            out.setdefault(tags.get(key, "?"), {})[label] = v
        return out

    print("serve overload control:")
    req = series("ray_tpu_serve_requests_total")
    total = sum(v for v in req.values() if isinstance(v, (int, float)))
    print(f"  requests      : total={int(total)}")
    for scope, rows in sorted(by_tag("ray_tpu_serve_shed_total",
                                     "scope").items()):
        n = int(sum(rows.values()))
        print(f"  shed          : scope={scope} total={n}")
    for where, rows in sorted(
            by_tag("ray_tpu_serve_deadline_exceeded_total",
                   "where").items()):
        n = int(sum(rows.values()))
        print(f"  deadline      : where={where} total={n}")
    retries = sum(
        v for v in series("ray_tpu_serve_retries_total").values()
        if isinstance(v, (int, float))
    )
    print(f"  retries       : total={int(retries)}")
    breaker = series("ray_tpu_serve_breaker_state")
    state_names = {0.0: "closed", 1.0: "half_open", 2.0: "open"}
    shown = 0
    for tags_key, v in sorted(breaker.items()):
        if not isinstance(v, (int, float)):
            continue
        tags = dict(tags_key)
        print(f"  breaker       : deployment={tags.get('deployment', '?')} "
              f"replica={tags.get('replica', '?')} "
              f"state={state_names.get(float(v), v)}")
        shown += 1
    if not shown:
        print("  breaker       : no non-default states recorded")


def _print_actor_section() -> None:
    """Actors section of `rtpu metrics`: the direct actor-call plane at
    a glance. The cluster block aggregates the ``ray_tpu_actor_call_*``
    series every caller process flushes through the KV metrics pipeline
    (so it shows real traffic even though this CLI attaches as a fresh,
    idle driver); the per-process block is THIS process's caller-side
    channel state, useful when run inside an actual driver."""
    from ray_tpu.core.runtime_context import current_runtime
    from ray_tpu.util.metrics import get_metrics_report

    print("direct actor-call plane:")
    try:
        report = get_metrics_report()
    except Exception:
        report = {}
    calls = sum(
        v.get("count", 0)
        for v in report.get("ray_tpu_actor_call_seconds", {})
        .get("series", {}).values()
        if isinstance(v, dict)
    )
    inflight = sum(
        v for v in report.get("ray_tpu_actor_call_inflight", {})
        .get("series", {}).values()
        if isinstance(v, (int, float))
    )
    fb = report.get("ray_tpu_actor_call_fallbacks_total", {}).get(
        "series", {})
    fb_total = sum(v for v in fb.values() if isinstance(v, (int, float)))
    print(f"  cluster       : calls={int(calls)} inflight={int(inflight)} "
          f"fallbacks={int(fb_total)}")
    for tags_key, v in sorted(fb.items()):
        labels = ",".join(f"{k}={val}" for k, val in tags_key)
        print(f"  fallbacks     : {labels or 'untagged'} = {int(v)}")

    rt = current_runtime()
    st = rt.direct_stats()
    print(f"  this process  : calls={st['calls']} "
          f"inflight={st['inflight']} fallbacks={st['fallbacks']}")
    nm = getattr(rt, "_nm", None)
    if nm is not None:
        s = nm._stats
        dones = s.get("direct_calls_done", 0)
        batches = s.get("direct_done_batches", 0)
        coalesce = f"{dones / batches:.1f}x" if batches else "-"
        print(f"  this node nm  : dones={dones} batches={batches} "
              f"coalesce={coalesce}")
    gp = st.get("gil_probe")
    if gp and gp.get("frames_in"):
        print(f"  gil probe     : py_entries={gp['py_entries']} "
              f"frames_in={gp['frames_in']} "
              f"completions={gp.get('completions', 0)} "
              f"native_tables={gp.get('native_tables', 0)}")
    if st["channels"]:
        for ch in st["channels"]:
            print(f"  channel       : actor={ch['actor_id'][:8]} "
                  f"status={ch['status']} remote={ch['remote']} "
                  f"calls={ch['calls']}")
    else:
        print("  channel       : none")


def _print_transfer_section() -> None:
    """Transfers section of `rtpu metrics`: the attached node's transfer
    plane at a glance — per-plane byte counters, stripe/fallback counts,
    and per-peer in-flight pulls."""
    from ray_tpu.core.runtime_context import current_runtime

    nm = getattr(current_runtime(), "_nm", None)
    transfer = getattr(nm, "_transfer", None) if nm is not None else None
    if transfer is None:
        print("transfers: no local node manager attached")
        return
    st = dict(transfer.stats)
    print("transfers:")
    print(f"  data plane    : port={getattr(nm, 'data_port', 0) or 'off'} "
          f"streams/peer={transfer.streams_per_peer}")
    print(f"  pulls         : striped={st['striped_pulls']} "
          f"fallback={st['fallback_pulls']} "
          f"chunked_total={st['chunked_pulls']} "
          f"queued_on_memory={st['pulls_queued_on_memory']}")
    print(f"  bytes         : pulled_stream={st['bytes_pulled_stream']} "
          f"served_stream={st['bytes_served_stream']}")
    print(f"  control plane : chunks_pulled={st['chunks_pulled']} "
          f"chunks_served={st['chunks_served']}")
    print(f"  ranges_served : {st['ranges_served']}")
    inflight = transfer.inflight_by_peer()
    if inflight:
        for peer, n in sorted(inflight.items()):
            print(f"  in-flight     : peer={peer} pulls={n}")
    else:
        print("  in-flight     : none")


# ------------------------------------------------------- chaos & drain

def cmd_drain(args) -> int:
    """Drain a node and retire it with zero downtime (serve replicas
    migrate, in-flight work finishes, primary object copies replicate
    off-node, then the node exits — ref: the DrainNode RPC behind
    kuberay's drain-before-delete)."""
    ray_tpu = _attached(args)
    try:
        reply = ray_tpu.drain_node(args.node, timeout=args.timeout)
        print(f"node {args.node} drained: "
              f"replicated {reply.get('replicated', 0)} object(s), "
              f"{reply.get('leftover_actors', 0)} actor(s) died with "
              f"the node")
        return 0
    finally:
        ray_tpu.shutdown()


def _gcs_handle():
    from ray_tpu.core.runtime_context import current_runtime

    nm = getattr(current_runtime(), "_nm", None)
    if nm is None:
        raise SystemExit(
            "rtpu chaos needs a cluster-attached head/driver address "
            "(thin rtpu:// clients cannot drive the chaos plane)"
        )
    return nm, nm._gcs


def cmd_chaos(args) -> int:
    """Deterministic cluster-wide fault injection (util/faults.py):
    ``arm`` appends one spec to the armed plan and pushes it to every
    node + worker; ``disarm`` clears the plan; ``list`` shows it."""
    ray_tpu = _attached(args)
    try:
        nm, gcs = _gcs_handle()
        if args.chaos_cmd == "list":
            reply = nm.call_sync(gcs.chaos_list(), timeout=30)
            if args.json:
                print(json.dumps(reply, indent=2))
            else:
                print(f"chaos plan gen {reply['gen']}: "
                      f"{len(reply['specs'])} spec(s)")
                for s in reply["specs"]:
                    extra = []
                    if s.get("mode") == "every":
                        extra.append(f"n={s['n']}")
                    if s.get("mode") == "once" and s.get("n", 1) != 1:
                        extra.append(f"after={s['n']}")
                    if s.get("mode") == "prob":
                        extra.append(f"p={s['p']} seed={s.get('seed')}")
                    if s.get("action") == "latency":
                        extra.append(f"delay={s['delay_s']}s")
                    if s.get("max_fires"):
                        extra.append(f"max_fires={s['max_fires']}")
                    if s.get("node"):
                        extra.append(f"node={s['node'][:8]}")
                    print(f"  {s['point']:18s} {s['mode']:6s} "
                          f"{s['action']:9s} {' '.join(extra)}")
            return 0
        if args.chaos_cmd == "disarm":
            reply = nm.call_sync(gcs.chaos_disarm(), timeout=30)
            print(f"chaos disarmed (gen {reply['gen']})")
            return 0
        # arm: append one spec to the current plan.
        spec = {
            "point": args.point,
            "mode": args.mode,
            "action": args.action,
            "n": args.n,
            "p": args.p,
            "seed": args.seed,
            "delay_s": args.delay,
            "max_fires": args.max_fires,
            "node": args.node or "",
        }
        current = nm.call_sync(gcs.chaos_list(), timeout=30)["specs"]
        specs = ([] if args.replace else list(current)) + [spec]
        reply = nm.call_sync(gcs.chaos_arm(specs), timeout=30)
        print(f"chaos armed: {len(specs)} spec(s) (gen {reply['gen']})")
        return 0
    finally:
        ray_tpu.shutdown()


# --------------------------------------------------------------- serve

def cmd_serve_deploy(args) -> int:
    """Apply a declarative serve config (ref: `serve deploy`)."""
    ray_tpu = _attached(args)
    try:
        import ray_tpu.serve as serve

        with open(args.config) as f:
            routes = serve.deploy_config(
                f.read(), http_port=args.http_port
            )
        for app, info in routes.items():
            print(f"{app}: route=/{info['route_prefix']} "
                  f"port={info['http_port']} "
                  f"deployment={info['deployment']}")
        return 0
    finally:
        ray_tpu.shutdown()


def cmd_serve_status(args) -> int:
    """Per-deployment replica state (ref: `serve status`)."""
    ray_tpu = _attached(args)
    try:
        import ray_tpu.serve as serve

        print(json.dumps(serve.details(), indent=2, default=str))
        return 0
    finally:
        ray_tpu.shutdown()


def cmd_serve_shutdown(args) -> int:
    """Delete every deployment (ref: `serve shutdown`)."""
    ray_tpu = _attached(args)
    try:
        import ray_tpu.serve as serve

        serve.shutdown()
        print("serve shut down")
        return 0
    finally:
        ray_tpu.shutdown()


# ---------------------------------------------------------------- main

def _add_address(p):
    p.add_argument("--address", default=None,
                   help="cluster GCS address host:port (default: "
                        "$RAY_TPU_ADDRESS or the local head's)")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="rtpu", description="ray_tpu cluster CLI"
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--head", action="store_true")
    _add_address(p)
    p.add_argument("--port", type=int, default=6380)
    p.add_argument("--node-ip", default="127.0.0.1")
    p.add_argument("--num-cpus", type=int, default=None)
    p.add_argument("--num-tpus", type=int, default=None)
    p.add_argument("--resources", default=None, help="JSON dict")
    p.add_argument("--block", action="store_true",
                   help="run in the foreground")
    p.add_argument("--cluster-config", default=None,
                   help="cluster YAML; head runs the autoscaler")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop all locally-started nodes")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("up", help="start a cluster from a YAML config")
    p.add_argument("cluster_config")
    p.add_argument("--port", type=int, default=6380)
    p.add_argument("--node-ip", default="127.0.0.1")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="tear down the cluster")
    p.add_argument("cluster_config", nargs="?")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("status", help="cluster summary")
    _add_address(p)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("nodes",
                       help="node table with membership epoch + "
                            "incarnations (fence plane)")
    p.add_argument("--json", action="store_true")
    _add_address(p)
    p.set_defaults(fn=cmd_nodes)

    p = sub.add_parser("list", help="list cluster state")
    p.add_argument("kind", choices=["tasks", "actors", "objects",
                                    "workers", "nodes"])
    p.add_argument("--limit", type=int, default=100)
    _add_address(p)
    p.set_defaults(fn=cmd_state)

    p = sub.add_parser("submit", help="submit a job: rtpu submit -- cmd…")
    _add_address(p)
    p.add_argument("--working-dir", default=None)
    p.add_argument("--no-wait", action="store_true")
    p.add_argument("entrypoint", nargs=argparse.REMAINDER,
                   help="command to run (after --)")
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("jobs", help="list jobs")
    _add_address(p)
    p.set_defaults(fn=cmd_jobs)

    p = sub.add_parser("logs", help="print or follow a job's logs")
    p.add_argument("job_id")
    p.add_argument("--follow", "-f", action="store_true")
    _add_address(p)
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("stop-job", help="stop a running job")
    p.add_argument("job_id")
    _add_address(p)
    p.set_defaults(fn=cmd_stop_job)

    p = sub.add_parser("metrics",
                       help="dump the Prometheus exposition text")
    p.add_argument("--transfers", action="store_true",
                   help="print the object-transfer plane section "
                        "(human-readable) instead of the full document")
    p.add_argument("--actors", action="store_true",
                   help="print the direct actor-call plane section "
                        "(human-readable) instead of the full document")
    p.add_argument("--serve", action="store_true",
                   help="print the serve overload-control section "
                        "(shed/deadline/breaker/retry counters) instead "
                        "of the full document")
    p.add_argument("--watch", type=float, default=None, metavar="N",
                   help="refresh the chosen view every N seconds "
                        "(^C exits)")
    _add_address(p)
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser("top",
                       help="live cluster view: per-node CPU/RSS/HBM, "
                            "serve qps/p99/shed, dispatch ops/s")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds")
    p.add_argument("--window", type=float, default=30.0,
                   help="trailing window for rates (seconds)")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit")
    _add_address(p)
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser("rpc",
                       help="control-plane dispatch stats: per-op "
                            "qps/p50/p99 + stage breakdown, backlog, "
                            "loop lag, GIL ratio")
    p.add_argument("--top", type=int, default=15, metavar="N",
                   help="show the N busiest ops (default 15)")
    p.add_argument("--window", type=float, default=60.0,
                   help="trailing window for rates/quantiles (seconds)")
    p.add_argument("--watch", type=float, default=None, metavar="N",
                   help="refresh every N seconds (^C exits)")
    p.add_argument("--json", action="store_true")
    _add_address(p)
    p.set_defaults(fn=cmd_rpc)

    p = sub.add_parser("slo",
                       help="per-deployment SLO status: goodput, "
                            "error-budget burn rates, alert state")
    p.add_argument("--watch", type=float, default=None, metavar="N",
                   help="refresh every N seconds (^C exits)")
    p.add_argument("--json", action="store_true")
    _add_address(p)
    p.set_defaults(fn=cmd_slo)

    p = sub.add_parser("events", help="aggregated cluster event log")
    p.add_argument("--severity", default=None,
                   choices=["DEBUG", "INFO", "WARNING", "ERROR", "FATAL"])
    p.add_argument("--source", default=None,
                   help="filter by event source (GCS, RAYLET, WORKER, "
                        "TASK, ACTOR, OBJECT_STORE, AUTOSCALER, SERVE, "
                        "JOB, CHAOS, TRAIN, NODE)")
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--follow", "-f", action="store_true",
                   help="stream new events as they are published")
    p.add_argument("--json", action="store_true")
    _add_address(p)
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser("trace",
                       help="tail-sampled request waterfalls (flight "
                            "recorder)")
    p.add_argument("trace_id", nargs="?", default=None,
                   help="print this trace's waterfall instead of the "
                        "retained-request list")
    p.add_argument("--slow", action="store_true",
                   help="only requests retained as slow (rolling ~p99)")
    p.add_argument("--errors", action="store_true",
                   help="only errored requests")
    p.add_argument("--shed", action="store_true",
                   help="only overload-shed requests")
    p.add_argument("--expired", action="store_true",
                   help="only deadline-expired requests")
    p.add_argument("--chaos", action="store_true",
                   help="only chaos-hit records")
    p.add_argument("--slow-ops", action="store_true",
                   help="only control-plane ops slower than "
                        "rpc_slow_op_s (NM/GCS dispatch stalls)")
    p.add_argument("--stalled", action="store_true",
                   help="only stalled data-plane pulls (no byte "
                        "progress past transfer_stall_warn_s)")
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--json", action="store_true")
    _add_address(p)
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("summary",
                       help="task/actor/object summaries incl. failures")
    p.add_argument("--json", action="store_true")
    _add_address(p)
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("memory",
                       help="cluster object-store memory view "
                            "(census-backed reference table)")
    p.add_argument("--limit", type=int, default=50)
    p.add_argument("--watch", type=float, default=None, metavar="N",
                   help="refresh every N seconds (^C exits)")
    _add_address(p)
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("objects",
                       help="cluster object census: top-N by size, "
                            "leak candidates, spilled set")
    p.add_argument("--top", type=int, default=20, metavar="N",
                   help="show the N top objects (default 20)")
    p.add_argument("--leaked", action="store_true",
                   help="only zero-ref (leak-candidate) objects, "
                        "oldest first")
    p.add_argument("--spilled", action="store_true",
                   help="only spilled objects")
    p.add_argument("--watch", type=float, default=None, metavar="N",
                   help="refresh every N seconds (^C exits)")
    p.add_argument("--json", action="store_true")
    _add_address(p)
    p.set_defaults(fn=cmd_objects)

    p = sub.add_parser("transfers",
                       help="data plane: per-link bandwidth matrix, "
                            "spill churn, in-flight pull aging")
    p.add_argument("--window", type=float, default=30.0,
                   help="trailing window for rates (seconds)")
    p.add_argument("--watch", type=float, default=None, metavar="N",
                   help="refresh every N seconds (^C exits)")
    _add_address(p)
    p.set_defaults(fn=cmd_transfers)

    p = sub.add_parser("stack",
                       help="stack dumps of every process in the cluster")
    p.add_argument("--node", default=None,
                   help="only this node (hex id prefix)")
    p.add_argument("--worker", default=None,
                   help="only this worker (hex id prefix)")
    p.add_argument("--timeout", type=float, default=5.0)
    p.add_argument("--json", action="store_true")
    _add_address(p)
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("profile",
                       help="sampled wall-clock profile of the cluster")
    p.add_argument("--seconds", type=float, default=2.0)
    p.add_argument("--hz", type=int, default=100)
    p.add_argument("--format", choices=["folded", "speedscope"],
                   default="folded")
    p.add_argument("-o", "--output", default=None,
                   help="write to FILE instead of stdout")
    _add_address(p)
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("drain",
                       help="drain a node and retire it (zero downtime)")
    p.add_argument("node", help="node id (full hex or unique prefix)")
    p.add_argument("--timeout", type=float, default=None,
                   help="drain budget in seconds "
                        "(default: drain_timeout_s)")
    _add_address(p)
    p.set_defaults(fn=cmd_drain)

    p = sub.add_parser("chaos",
                       help="deterministic cluster-wide fault injection")
    csub = p.add_subparsers(dest="chaos_cmd", required=True)
    cp = csub.add_parser("arm", help="arm one injection spec "
                                     "(appends to the current plan)")
    cp.add_argument("--point", required=True,
                    help="injection point (peer_send, data_channel_io, "
                         "direct_channel_io, gcs_rpc, worker_spawn, "
                         "heartbeat)")
    cp.add_argument("--mode", default="always",
                    choices=["always", "once", "every", "prob"])
    cp.add_argument("--action", default="error",
                    choices=["error", "partition", "latency"])
    cp.add_argument("--n", type=int, default=1,
                    help="every: period; once: fire on the Nth hit")
    cp.add_argument("--p", type=float, default=1.0,
                    help="prob: firing probability")
    cp.add_argument("--seed", type=int, default=None,
                    help="prob: RNG seed (deterministic replay)")
    cp.add_argument("--delay", type=float, default=0.0,
                    help="latency: injected delay in seconds")
    cp.add_argument("--max-fires", type=int, default=0,
                    help="stop firing after this many (0 = unbounded)")
    cp.add_argument("--node", default=None,
                    help="only fire on this node (hex id prefix)")
    cp.add_argument("--replace", action="store_true",
                    help="replace the whole plan instead of appending")
    _add_address(cp)
    cp.set_defaults(fn=cmd_chaos)
    cp = csub.add_parser("disarm", help="clear the armed plan")
    _add_address(cp)
    cp.set_defaults(fn=cmd_chaos)
    cp = csub.add_parser("list", help="show the armed plan")
    cp.add_argument("--json", action="store_true")
    _add_address(cp)
    cp.set_defaults(fn=cmd_chaos)

    p = sub.add_parser("serve", help="serve: deploy/status/shutdown")
    ssub = p.add_subparsers(dest="serve_cmd", required=True)
    sp = ssub.add_parser("deploy",
                         help="apply a declarative serve config YAML")
    sp.add_argument("config")
    sp.add_argument("--http-port", type=int, default=8000)
    _add_address(sp)
    sp.set_defaults(fn=cmd_serve_deploy)
    sp = ssub.add_parser("status", help="per-deployment replica state")
    _add_address(sp)
    sp.set_defaults(fn=cmd_serve_status)
    sp = ssub.add_parser("shutdown", help="delete every deployment")
    _add_address(sp)
    sp.set_defaults(fn=cmd_serve_shutdown)

    args = parser.parse_args(argv)
    if getattr(args, "entrypoint", None):
        # argparse.REMAINDER keeps the leading "--"; drop it.
        if args.entrypoint and args.entrypoint[0] == "--":
            args.entrypoint = args.entrypoint[1:]
        if not args.entrypoint:
            parser.error("submit needs an entrypoint after --")
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
