"""Data-plane observability (ref analogue: the object manager's
ObjectStoreRunner stats + pull_manager.h's retry/progress bookkeeping,
surfaced instead of buried).

Three instruments over the L2 object layer:

  leak gauges      the head census sweep publishes how many sealed
                   objects are older than ``object_leak_warn_s`` with
                   zero live refs (or a dead/fenced owner), and their
                   byte total, via ``ray_tpu_object_leaked_total`` /
                   ``ray_tpu_object_leaked_bytes``.
  stall watchdog   every in-flight pull carries (started_ts,
                   bytes_moved, last_progress_ts); a pull with no byte
                   progress past ``transfer_stall_warn_s`` raises the
                   LIVE ``ray_tpu_object_transfer_stalled{peer}`` gauge
                   (visible WHILE stuck), emits one deduped WARNING
                   OBJECT_STORE event per stall episode, and drops a
                   flight-recorder record (reason "stalled_pull") so
                   ``rtpu trace --stalled`` joins data-plane stalls to
                   request waterfalls.
  link matrix      per-(src,dst) byte counters
                   (``ray_tpu_transfer_link_bytes_total{src,dst}``)
                   feed the head TSDB so ``rtpu transfers`` /
                   ``rtpu top`` can derive per-link bandwidth; spill
                   churn rides ``ray_tpu_spill_ops_total{op}`` /
                   ``ray_tpu_spill_bytes_total{op}`` next to the
                   ``spill:<oid8>``/``restore:<oid8>`` timeline spans.

The whole plane is one in-process kill switch away:
``RTPU_NO_DATA_OBS=1`` makes every tracker factory return None and
every caller degrades to zero-overhead no-ops (the transfer bench's
``obs_overhead`` row measures exactly this delta, bar <= 3%).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

from .metrics import Counter, Gauge

# Kill switch, read once at import: the bench flips it per-session via a
# fresh interpreter, so a cached check is both correct and free.
ENABLED = os.environ.get("RTPU_NO_DATA_OBS", "") not in ("1", "true")

LEAKED_TOTAL = Gauge(
    "ray_tpu_object_leaked_total",
    "Sealed objects the head census sweep currently considers leaked "
    "(zero live refs past object_leak_warn_s, or a dead/fenced owner).",
)
LEAKED_BYTES = Gauge(
    "ray_tpu_object_leaked_bytes",
    "Byte total of the objects currently flagged leaked by the head "
    "census sweep.",
)
TRANSFER_STALLED = Gauge(
    "ray_tpu_object_transfer_stalled",
    "In-flight pulls from this peer with no byte progress for longer "
    "than transfer_stall_warn_s (live while stuck, zero on recovery).",
    tag_keys=("peer",),
)
LINK_BYTES = Counter(
    "ray_tpu_transfer_link_bytes_total",
    "Data-plane bytes moved per directed (src,dst) node-id link; rate "
    "over the head TSDB gives per-link bandwidth.",
    tag_keys=("src", "dst"),
)
SPILL_OPS = Counter(
    "ray_tpu_spill_ops_total",
    "Spill-plane operations (op=spill|restore) — churn counter for the "
    "disk tier.",
    tag_keys=("op",),
)
SPILL_BYTES = Counter(
    "ray_tpu_spill_bytes_total",
    "Bytes written to (op=spill) or read back from (op=restore) the "
    "spill tier.",
    tag_keys=("op",),
)

# Bound-handle caches (with_tags resolves the tag tuple once; the hot
# path then only does a dict lookup).
_link_handles: Dict[Tuple[str, str], object] = {}
_stalled_handles: Dict[str, object] = {}
_spill_handles: Dict[str, Tuple[object, object]] = {}
# Link-byte publishes are batched: stripe workers add to an int pending
# map under a lock, and a publish drains it at most every
# _LINK_MIN_INTERVAL_S. A counter inc takes the registry lock — at one
# inc per 1 MiB recv window that was a measurable slice of the stripe
# hot path.
_link_pending: Dict[Tuple[str, str], int] = {}
_link_lock = threading.Lock()
_link_last_pub = 0.0
_LINK_MIN_INTERVAL_S = 0.2


def record_link_bytes(src: str, dst: str, nbytes: int,
                      flush: bool = False) -> None:
    """Account data-plane bytes moved over the directed (src,dst) link.
    Batched: counter publishes happen at most every 0.2 s per process,
    or immediately with ``flush=True`` (end of a pull). Never raises."""
    if not ENABLED or (nbytes <= 0 and not flush):
        return
    global _link_last_pub
    try:
        now = time.monotonic()
        with _link_lock:
            if nbytes > 0:
                key = (src[:16] or "?", dst[:16] or "?")
                _link_pending[key] = (_link_pending.get(key, 0)
                                      + int(nbytes))
            if not _link_pending:
                return
            if not flush and now - _link_last_pub < _LINK_MIN_INTERVAL_S:
                return
            _link_last_pub = now
            drained = dict(_link_pending)
            _link_pending.clear()
        for k, v in drained.items():
            h = _link_handles.get(k)
            if h is None:
                h = LINK_BYTES.with_tags(src=k[0], dst=k[1])
                _link_handles[k] = h
            h.inc(v)
    except Exception:  # pragma: no cover - telemetry must not break pulls
        pass


def record_spill(op: str, nbytes: int) -> None:
    """Account one spill-plane operation (op=spill|restore)."""
    if not ENABLED:
        return
    try:
        h = _spill_handles.get(op)
        if h is None:
            h = (SPILL_OPS.with_tags(op=op), SPILL_BYTES.with_tags(op=op))
            _spill_handles[op] = h
        h[0].inc(1)
        h[1].inc(max(0, int(nbytes)))
    except Exception:  # pragma: no cover
        pass


def set_stalled(peer: str, count: int) -> None:
    """Publish the live per-peer stalled-pull gauge (0 clears it)."""
    if not ENABLED:
        return
    try:
        key = peer[:64] or "?"
        h = _stalled_handles.get(key)
        if h is None:
            h = TRANSFER_STALLED.with_tags(peer=key)
            _stalled_handles[key] = h
        h.set(float(count))
    except Exception:  # pragma: no cover
        pass


def set_leaked(count: int, nbytes: int) -> None:
    """Publish the head census sweep's current leak verdict."""
    if not ENABLED:
        return
    try:
        LEAKED_TOTAL.set(float(count))
        LEAKED_BYTES.set(float(nbytes))
    except Exception:  # pragma: no cover
        pass


class PullProgress:
    """One in-flight pull's progress record: (started_ts, bytes_moved,
    last_progress_ts) plus the stall episode flag the watchdog dedupes
    on. Stripe workers bump it from executor threads — the int/float
    stores are GIL-atomic, and the watchdog only reads, so no lock."""

    __slots__ = ("oid", "peer", "size", "started_ts", "bytes_moved",
                 "last_progress_ts", "stalled", "detail", "_id")

    def __init__(self, oid: str, peer: str, size: int):
        now = time.monotonic()
        self.oid = oid
        self.peer = peer
        self.size = int(size)
        self.started_ts = now
        self.bytes_moved = 0
        self.last_progress_ts = now
        # Set by the watchdog when the stall WARNING for this pull has
        # fired; byte progress clears it (so a re-stall warns again).
        self.stalled = False
        # Free-form stripe detail for the flight-recorder record.
        self.detail = ""

    def advance(self, nbytes: int) -> None:
        self.bytes_moved += int(nbytes)
        self.last_progress_ts = time.monotonic()
        self.stalled = False

    def snapshot(self) -> dict:
        now = time.monotonic()
        return {
            "oid": self.oid,
            "peer": self.peer,
            "size": self.size,
            "bytes_moved": self.bytes_moved,
            "age_s": round(now - self.started_ts, 3),
            "idle_s": round(now - self.last_progress_ts, 3),
            "stalled": self.stalled,
        }


class PullTracker:
    """Registry of in-flight PullProgress records for one transfer
    manager, plus the stall watchdog sweep (driven by the owner's
    existing periodic loop — no thread of its own)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pulls: Dict[int, PullProgress] = {}
        self._next = 0
        # peer -> stalled count last published (so recovery publishes 0
        # exactly once instead of spamming the gauge forever).
        self._published: Dict[str, int] = {}

    def start(self, oid: str, peer: str, size: int) -> PullProgress:
        p = PullProgress(oid, peer, size)
        with self._lock:
            self._next += 1
            p_id = self._next
            self._pulls[p_id] = p
        p._id = p_id  # type: ignore[attr-defined]
        return p

    def finish(self, p: Optional[PullProgress]) -> None:
        if p is None:
            return
        with self._lock:
            self._pulls.pop(getattr(p, "_id", -1), None)

    def inflight(self) -> list:
        with self._lock:
            pulls = list(self._pulls.values())
        return [p.snapshot() for p in pulls]

    def sweep(self, stall_warn_s: float) -> list:
        """One watchdog pass: publish per-peer stalled gauges (live
        while stuck, back to zero on recovery) and return the pulls
        that JUST entered a stall episode (caller emits the deduped
        WARNING + flight-recorder record). Never raises."""
        newly_stalled = []
        try:
            now = time.monotonic()
            with self._lock:
                pulls = list(self._pulls.values())
            counts: Dict[str, int] = {}
            for p in pulls:
                idle = now - p.last_progress_ts
                if stall_warn_s > 0 and idle > stall_warn_s:
                    counts[p.peer] = counts.get(p.peer, 0) + 1
                    if not p.stalled:
                        p.stalled = True
                        newly_stalled.append(p)
            with self._lock:
                for peer in set(self._published) | set(counts):
                    n = counts.get(peer, 0)
                    if self._published.get(peer) != n:
                        set_stalled(peer, n)
                        if n:
                            self._published[peer] = n
                        else:
                            self._published.pop(peer, None)
        except Exception:  # pragma: no cover - telemetry must not break
            pass
        return newly_stalled


def pull_tracker() -> Optional[PullTracker]:
    """Tracker factory, or None when the plane is disabled (callers
    treat a None tracker as a full no-op)."""
    if not ENABLED:
        return None
    try:
        return PullTracker()
    except Exception:  # pragma: no cover
        return None
