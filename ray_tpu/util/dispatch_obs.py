"""Control-plane dispatch instrumentation (ref analogue: the
event_stats_ per-handler timers gRPC servers keep in `grpc_server.h` /
`core_worker.cc`).

Every NM/GCS frame op is clocked through three stages:

  queue-wait   frame recv -> handler start (time spent behind other
               frames / waiting for a loop slot; deferred ops fold
               their ensure_future scheduling delay in here too)
  handler      handler start -> handler return
  reply-send   handler return -> reply frame flushed (replying ops only)

into ``ray_tpu_rpc_server_seconds{service,op,stage}`` histograms, with
``ray_tpu_rpc_inflight{service}`` (ops whose handler has started but not
finished) and ``ray_tpu_rpc_backlog{service}`` (received but not yet
started — the queue the 29 ms loaded p99 hides in). Handler-stage
observations carry the active trace id as an OpenMetrics exemplar, and
any op whose total recv->done time exceeds ``rpc_slow_op_s`` drops a
``span_event`` marker plus a flight-recorder record (reason "slow_op")
so ``rtpu trace --slow-ops`` joins control-plane stalls to waterfalls.

The whole plane is a single in-process kill switch away:
``RTPU_NO_DISPATCH_OBS=1`` makes ``op_clock`` return None and every
caller degrades to zero-overhead no-ops (the bench's ``obs_overhead``
row measures exactly this delta).
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional, Tuple

from .metrics import Gauge, Histogram

# Kill switch, read once at import: the bench flips it per-session via a
# fresh interpreter, so a cached check is both correct and free.
ENABLED = os.environ.get("RTPU_NO_DISPATCH_OBS", "") not in ("1", "true")

STAGES = ("queue_wait", "handler", "reply_send")

# Control-plane ops live in the 100 us .. tens-of-ms band; the upper
# buckets exist so a stalled loop is still representable.
_BOUNDARIES = [0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
               0.05, 0.1, 0.25, 0.5, 1.0, 2.5]

SERVER_SECONDS = Histogram(
    "ray_tpu_rpc_server_seconds",
    "Server-side control-plane dispatch stage latency "
    "(stage=queue_wait|handler|reply_send, service=nm|gcs|peer).",
    boundaries=_BOUNDARIES,
    tag_keys=("service", "op", "stage"),
)
INFLIGHT = Gauge(
    "ray_tpu_rpc_inflight",
    "Control-plane ops whose handler is currently executing, per "
    "service.",
    tag_keys=("service",),
)
BACKLOG = Gauge(
    "ray_tpu_rpc_backlog",
    "Control-plane ops received but not yet started (queued behind the "
    "event loop), per service.",
    tag_keys=("service",),
)

# Bound-handle caches: with_tags resolves the tag-key tuple once; the
# dispatch hot path then only does a dict lookup per stage.
_stage_handles: Dict[Tuple[str, str], tuple] = {}
_service_gauges: Dict[str, tuple] = {}
# service -> [inflight, backlog, last_publish_ts, pub_inflight,
# pub_backlog]. The int pair is authoritative; the gauge publishes are
# throttled (every registry set takes the metrics lock — at 4 sets per
# op that was a measurable slice of the dispatch hot path).
_counts: Dict[str, list] = {}
_GAUGE_MIN_INTERVAL_S = 0.05


def _handles(service: str, op: str) -> tuple:
    key = (service, op)
    h = _stage_handles.get(key)
    if h is None:
        h = tuple(SERVER_SECONDS.with_tags(service=service, op=op,
                                           stage=s) for s in STAGES)
        _stage_handles[key] = h
    return h


def _gauges(service: str) -> tuple:
    g = _service_gauges.get(service)
    if g is None:
        g = (INFLIGHT.with_tags(service=service),
             BACKLOG.with_tags(service=service))
        _service_gauges[service] = g
        _counts[service] = [0, 0, 0.0, 0, 0]
    return g


def _publish(service: str, now: float) -> None:
    """Throttled gauge publish: push when the window elapsed, or when
    the counts differ from the published pair AND are back to zero (so
    an idle plane never shows a stale nonzero backlog). The TSDB only
    samples every flush interval anyway — intermediate flickers carry
    no information."""
    c = _counts[service]
    changed = c[0] != c[3] or c[1] != c[4]
    if not changed:
        return
    if now - c[2] < _GAUGE_MIN_INTERVAL_S and (c[0] or c[1]):
        return
    c[2] = now
    c[3], c[4] = c[0], c[1]
    inflight, backlog = _service_gauges[service]
    inflight.set(float(c[0]))
    backlog.set(float(c[1]))


# Lazily-bound collaborators (resolved once, then plain globals on the
# hot path). NOTE: core/__init__ re-exports a timeline() API function
# that shadows the module on attribute access — bind from the module.
_current_span = None
_span_event = None
_get_config = None


def _resolve_lazy() -> None:
    global _current_span, _span_event, _get_config
    from ..core.config import get_config
    from ..core.timeline import current_span, span_event
    _current_span, _span_event = current_span, span_event
    _get_config = get_config


class OpClock:
    """One frame op's stage clock. Lifecycle: construct at frame recv
    (op enters the backlog) -> ``start()`` when the handler begins (may
    be re-stamped by a deferred wrapper; only the last stamp counts) ->
    ``handler_done()`` when the handler returns -> ``done()`` after the
    reply (if any) is flushed. Never raises into the dispatch path."""

    __slots__ = ("service", "op", "recv_ts", "deferred",
                 "_t_start", "_t_handler", "_closed")

    def __init__(self, service: str, op: str, recv_ts: Optional[float]):
        self.service = service
        self.op = op or "?"
        self.recv_ts = recv_ts if recv_ts is not None else time.monotonic()
        # Set by the NM when it hands the op to ensure_future: tells the
        # inline dispatch path NOT to close the clock — the wrapped
        # coroutine owns it from then on.
        self.deferred = False
        self._t_start: Optional[float] = None
        self._t_handler: Optional[float] = None
        self._closed = False
        _gauges(service)
        _counts[service][1] += 1
        _publish(service, self.recv_ts)

    def start(self) -> None:
        first = self._t_start is None
        self._t_start = time.monotonic()
        if first:
            c = _counts[self.service]
            c[1] -= 1
            c[0] += 1
            _publish(self.service, self._t_start)

    def handler_done(self) -> None:
        self._t_handler = time.monotonic()

    def done(self, replied: Optional[bool] = None,
             trace_id: Optional[str] = None) -> None:
        if self._closed:
            return
        self._closed = True
        if replied is None:
            # Default heuristic for frame loops that only stamp
            # handler_done() right before flushing a reply frame (the
            # NM's inline branches): an explicit stamp means a reply
            # followed.
            replied = self._t_handler is not None
        end = time.monotonic()
        t_start = self._t_start if self._t_start is not None else end
        t_handler = self._t_handler if self._t_handler is not None else end
        c = _counts[self.service]
        if self._t_start is None:
            # Never started (e.g. connection died while queued): the op
            # leaves the backlog, not the inflight count.
            c[1] -= 1
        else:
            c[0] -= 1
        _publish(self.service, end)
        try:
            if _current_span is None:
                _resolve_lazy()
            if trace_id is None:
                span = _current_span()
                if span is not None:
                    trace_id = span[0]
            qh, hh, rh = _handles(self.service, self.op)
            qh.observe(max(0.0, t_start - self.recv_ts))
            hh.observe(max(0.0, t_handler - t_start), exemplar=trace_id)
            if replied:
                rh.observe(max(0.0, end - t_handler))
            total = end - self.recv_ts
            slow = _slow_op_s()
            if slow > 0 and total > slow:
                name = f"{self.service}.{self.op}"
                _span_event(f"slow_op:{name}")
                from . import flight_recorder
                flight_recorder.observe_request(
                    name, trace_id or "", end - total, end,
                    status="slow", reason="slow_op",
                    detail=(f"queue_wait={t_start - self.recv_ts:.4f}s "
                            f"handler={t_handler - t_start:.4f}s"),
                    surface="rpc")
        except Exception:  # pragma: no cover - telemetry must not break ops
            pass


# (config object, value): get_config() returns the same object for a
# session, so an identity hit skips the float/attr work per op.
_slow_conf: tuple = (None, 0.0)


def _slow_op_s() -> float:
    global _slow_conf
    try:
        if _get_config is None:
            _resolve_lazy()
        cfg = _get_config()
        cached = _slow_conf
        if cached[0] is cfg:
            return cached[1]
        v = float(cfg.rpc_slow_op_s)
        _slow_conf = (cfg, v)
        return v
    except Exception:  # pragma: no cover
        return 0.0


def op_clock(service: str, op: str,
             recv_ts: Optional[float] = None) -> Optional[OpClock]:
    """Clock for one received frame op, or None when the plane is
    disabled (callers treat a None clock as a full no-op)."""
    if not ENABLED:
        return None
    try:
        return OpClock(service, op, recv_ts)
    except Exception:  # pragma: no cover - telemetry must not break ops
        return None
