"""Distributed progress bars.

Ref analogue: python/ray/experimental/tqdm_ray.py — workers cannot
draw terminal bars, so a worker-side ``tqdm`` proxy ships structured
progress updates to the driver, which renders real bars. The
reference routes updates through magic-token log lines and the log
monitor; here they ride the cluster pubsub (util/pubsub.py, channel
``tqdm``) — same shape, authenticated transport.

Worker side:
    from ray_tpu.util import tqdm as tqdm_ray
    for x in tqdm_ray.tqdm(items, desc="shard"):
        ...

Driver side (optional live rendering):
    with tqdm_ray.driver_progress():
        ray_tpu.get(futs)
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, Iterable, Optional

CHANNEL = "tqdm"


class tqdm:  # noqa: N801 - mirrors the tqdm API name
    """Worker-side progress proxy; publishes rate-limited updates."""

    def __init__(self, iterable: Optional[Iterable] = None,
                 desc: str = "", total: Optional[int] = None,
                 position: Optional[int] = None,
                 flush_interval_s: float = 0.2):
        self._iterable = iterable
        self.desc = desc
        if total is None and iterable is not None:
            try:
                total = len(iterable)  # type: ignore[arg-type]
            except TypeError:
                total = None
        self.total = total
        self.position = position
        self.n = 0
        self._bar_id = uuid.uuid4().hex[:12]
        self._interval = flush_interval_s
        self._last_flush = 0.0
        self._closed = False
        self._flush(force=True)

    def _flush(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_flush < self._interval:
            return
        self._last_flush = now
        try:
            from .pubsub import publish

            publish(CHANNEL, {
                "bar_id": self._bar_id, "desc": self.desc,
                "total": self.total, "n": self.n,
                "closed": self._closed, "pos": self.position,
            }, key=self._bar_id)
        except Exception:
            pass  # progress must never break the workload

    def update(self, n: int = 1):
        self.n += n
        self._flush()

    def set_description(self, desc: str):
        self.desc = desc
        self._flush()

    def close(self):
        if not self._closed:
            self._closed = True
            self._flush(force=True)

    def __iter__(self):
        if self._iterable is None:
            raise TypeError("this tqdm was not given an iterable")
        try:
            for x in self._iterable:
                yield x
                self.update(1)
        finally:
            self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _DriverRenderer:
    """Subscribes to the tqdm channel and renders real tqdm bars."""

    def __init__(self, render: bool = True):
        self._render = render
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.bars: Dict[str, Any] = {}
        self.state: Dict[str, Dict[str, Any]] = {}

    def start(self):
        from .pubsub import Subscriber

        self._sub = Subscriber(channels=[CHANNEL])
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                events = self._sub.poll(timeout=0.5)
            except Exception:
                return
            for e in events:
                self._apply(e["data"])

    def _apply(self, d: Dict[str, Any]):
        bar_id = d["bar_id"]
        self.state[bar_id] = d
        if not self._render:
            return
        try:
            import tqdm as real_tqdm

            bar = self.bars.get(bar_id)
            if bar is None and not d["closed"]:
                bar = real_tqdm.tqdm(
                    desc=d["desc"], total=d["total"],
                    position=d.get("pos"),
                )
                self.bars[bar_id] = bar
            if bar is not None:
                bar.n = d["n"]
                bar.set_description(d["desc"], refresh=False)
                bar.refresh()
                if d["closed"]:
                    bar.close()
                    self.bars.pop(bar_id, None)
        except Exception:
            self._render = False

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        try:
            self._sub.close()
        except Exception:
            pass
        for bar in self.bars.values():
            try:
                bar.close()
            except Exception:
                pass


class driver_progress:  # noqa: N801 - context-manager style
    """Context manager running the driver-side renderer."""

    def __init__(self, render: bool = True):
        self._renderer = _DriverRenderer(render)

    def __enter__(self) -> _DriverRenderer:
        return self._renderer.start()

    def __exit__(self, *exc):
        self._renderer.stop()
