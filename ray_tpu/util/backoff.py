"""Shared jittered-exponential backoff with an optional deadline.

Ref analogue: the reference's ``ExponentialBackoff``
(src/ray/util/exponential_backoff.h) behind GCS reconnect, pull retry
and lease retry — one policy object instead of the ad-hoc fixed sleeps
that used to live in client reconnect, peer redial, object-transfer
admission and direct-plane endpoint re-resolution."""

from __future__ import annotations

import asyncio
import random
import time
from typing import Optional


class Backoff:
    """Exponential backoff: ``base * factor**attempt`` capped at
    ``max_delay``, multiplied by ``1 ± jitter`` (seeded — deterministic
    under test). ``deadline_s`` bounds the whole retry budget; once
    past it :meth:`sleep`/:meth:`async_sleep` return ``False`` without
    sleeping and the caller gives up."""

    def __init__(self, *, base: float = 0.1, factor: float = 2.0,
                 max_delay: float = 5.0, jitter: float = 0.25,
                 deadline_s: Optional[float] = None,
                 seed: Optional[int] = None):
        self._base = max(0.0, base)
        self._factor = max(1.0, factor)
        self._max = max(self._base, max_delay)
        self._jitter = min(1.0, max(0.0, jitter))
        self._rng = random.Random(seed)
        self._attempt = 0
        self._deadline = (
            None if deadline_s is None else time.monotonic() + deadline_s
        )

    @property
    def attempt(self) -> int:
        return self._attempt

    @property
    def expired(self) -> bool:
        return (self._deadline is not None
                and time.monotonic() >= self._deadline)

    def remaining(self) -> Optional[float]:
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def reset(self) -> None:
        """Back to the base delay (a success happened); the deadline, if
        any, keeps running — it bounds the whole operation."""
        self._attempt = 0

    def next_delay(self) -> float:
        """The next delay (advances the attempt counter). Clamped to the
        remaining deadline so a capped sleep never overshoots it."""
        raw = min(self._max, self._base * (self._factor ** self._attempt))
        self._attempt += 1
        if self._jitter:
            raw *= 1.0 + self._jitter * (2.0 * self._rng.random() - 1.0)
        remaining = self.remaining()
        if remaining is not None:
            raw = min(raw, remaining)
        return max(0.0, raw)

    def sleep(self) -> bool:
        """Thread idiom: sleep the next delay; ``False`` = deadline hit
        (nothing slept), the caller should stop retrying."""
        if self.expired:
            return False
        time.sleep(self.next_delay())
        return True

    async def async_sleep(self) -> bool:
        """Event-loop idiom of :meth:`sleep`."""
        if self.expired:
            return False
        await asyncio.sleep(self.next_delay())
        return True
