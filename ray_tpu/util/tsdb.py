"""Head-side bounded time-series store.

Ref analogue: the reference keeps per-series history in its
dashboard/metrics-agent plane (Prometheus behind `ray metrics`); here a
small in-process TSDB lives inside the head GCS so trend queries — "p99
over the last 5 minutes", "shed rate over the last hour" — need no
external collector. The `__metrics__` KV pipeline is the ingest: each
GCS sampling tick aggregates the flushed per-process snapshots
(util/metrics.py) and appends one sample per live series.

Memory is hard-bounded in both dimensions:

- ``samples_per_series``: each series is a ring (deque maxlen) — old
  samples fall off, the store never grows with uptime;
- ``max_series``: a low-cardinality guard — ingest for a NEW series
  beyond the cap is dropped and counted (``stats()["dropped"]``), never
  silently absorbed, so a tag-explosion bug degrades visibly instead of
  eating the head's RAM.

Derivation helpers turn the raw cumulative samples into the quantities
dashboards and the SLO engine actually want: counter→``rate`` (reset
robust: negative steps are treated as process restarts and clamped),
histogram-delta→``quantile``/``fraction_le`` via the shared
:func:`quantile_from_histogram`.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

# Sentinel bound for the overflow bucket in bound-keyed delta maps.
INF = float("inf")


def quantile_from_histogram(bounds: List[float], buckets: List[float],
                            q: float) -> Optional[float]:
    """Prometheus-style ``histogram_quantile``: ``buckets`` are the
    per-bucket (non-cumulative) counts for ``len(bounds) + 1`` buckets
    (the last is the +Inf overflow). Linear interpolation inside the
    containing bucket; an answer landing in the overflow bucket clamps
    to the highest finite bound (the honest "at least this much")."""
    total = sum(buckets)
    if total <= 0:
        return None
    q = min(1.0, max(0.0, q))
    rank = q * total
    cum = 0.0
    for i, count in enumerate(buckets):
        if count <= 0:
            continue
        if cum + count >= rank:
            if i >= len(bounds):  # overflow bucket
                return bounds[-1] if bounds else None
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - cum) / count
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        cum += count
    return bounds[-1] if bounds else None


def fraction_le(bounds: List[float], buckets: List[float],
                x: float) -> Optional[float]:
    """Fraction of observations <= ``x`` (the latency-goodness SLI),
    linearly interpolated inside the bucket containing ``x``. The
    overflow bucket counts as entirely above any finite ``x``."""
    total = sum(buckets)
    if total <= 0:
        return None
    cum = 0.0
    for i, b in enumerate(bounds):
        lo = bounds[i - 1] if i > 0 else 0.0
        if x >= b:
            cum += buckets[i]
            continue
        if x > lo and b > lo:
            cum += buckets[i] * (x - lo) / (b - lo)
        break
    return min(1.0, cum / total)


class _Series:
    __slots__ = ("kind", "samples")

    def __init__(self, kind: str, maxlen: int):
        self.kind = kind
        # scalar sample: (ts, value);
        # histogram sample: (ts, count, sum, bounds_tuple, buckets_tuple)
        self.samples: deque = deque(maxlen=maxlen)


class TSDB:
    def __init__(self, samples_per_series: int = 240,
                 max_series: int = 2000):
        self.samples_per_series = max(2, int(samples_per_series))
        self.max_series = max(1, int(max_series))
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, tuple], _Series] = {}
        self._dropped = 0  # samples refused by the series cap

    # -- ingest --------------------------------------------------------------

    def ingest(self, name: str, kind: str, tags_key: tuple, value: Any,
               ts: float) -> bool:
        """Append one sample; returns False when the series cap dropped
        it. ``value`` is the cumulative counter value, the gauge value,
        or a histogram point ({count, sum, bounds, buckets})."""
        key = (name, tuple(tags_key))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    self._dropped += 1
                    return False
                s = _Series(kind, self.samples_per_series)
                self._series[key] = s
            if kind == "histogram":
                s.samples.append((
                    ts, float(value.get("count", 0)),
                    float(value.get("sum", 0.0)),
                    tuple(value.get("bounds", ())),
                    tuple(value.get("buckets", ())),
                ))
            else:
                try:
                    s.samples.append((ts, float(value)))
                except (TypeError, ValueError):
                    return False
            return True

    def ingest_report(self, report: Dict[str, Dict], ts: float) -> None:
        """One sampling tick over a ``get_metrics_report()``-shaped
        aggregate: every (name, tags) series gets one sample."""
        for name, m in report.items():
            kind = m.get("type", "gauge")
            for tags_key, value in m.get("series", {}).items():
                self.ingest(name, kind, tags_key, value, ts)

    def forget(self, name: str, tags: Optional[Dict[str, str]] = None
               ) -> int:
        """Drop matching series (used when the source — a deployment, a
        dead node's processes — goes away); returns the count removed."""
        with self._lock:
            victims = [k for k in self._series
                       if k[0] == name and self._tags_match(k[1], tags)]
            for k in victims:
                del self._series[k]
            return len(victims)

    # -- query ---------------------------------------------------------------

    @staticmethod
    def _tags_match(tags_key: tuple, tags: Optional[Dict[str, str]]
                    ) -> bool:
        if not tags:
            return True
        have = dict(tags_key)
        return all(have.get(k) == v for k, v in tags.items())

    def _matching(self, name: str, tags: Optional[Dict[str, str]]
                  ) -> List[Tuple[tuple, _Series]]:
        with self._lock:
            return [(k[1], s) for k, s in self._series.items()
                    if k[0] == name and self._tags_match(k[1], tags)]

    def names(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k in self._series})

    def query(self, name: str, tags: Optional[Dict[str, str]] = None,
              since: float = 0.0, limit: int = 0) -> List[Dict[str, Any]]:
        """Raw samples for every matching series, JSON-shaped: scalar
        samples as ``[ts, value]`` pairs, histogram samples as
        ``[ts, count, sum]`` triples (bucket vectors stay head-side —
        consumers wanting quantiles use the derivation RPC fields)."""
        out = []
        for tags_key, s in self._matching(name, tags):
            with self._lock:
                samples = list(s.samples)
            if since:
                samples = [p for p in samples if p[0] >= since]
            if limit and limit > 0:
                samples = samples[-limit:]
            rows: List[List[float]] = []
            for p in samples:
                if s.kind == "histogram":
                    rows.append([p[0], p[1], p[2]])
                else:
                    rows.append([p[0], p[1]])
            out.append({"name": name, "kind": s.kind,
                        "tags": [list(kv) for kv in tags_key],
                        "samples": rows})
        return out

    def latest(self, name: str, tags: Optional[Dict[str, str]] = None
               ) -> Optional[float]:
        """Newest scalar value summed across matching series (gauge
        semantics: sum over identity tags for the total)."""
        total, seen = 0.0, False
        for _tags_key, s in self._matching(name, tags):
            with self._lock:
                if s.samples and s.kind != "histogram":
                    total += s.samples[-1][1]
                    seen = True
        return total if seen else None

    @staticmethod
    def _window_samples(samples: List[tuple], start: float) -> List[tuple]:
        """Samples at/after ``start`` plus the one immediately before it
        (the baseline a delta needs)."""
        out: List[tuple] = []
        for p in samples:
            if p[0] < start:
                out[:] = [p]  # keep only the newest pre-window sample
            else:
                out.append(p)
        return out

    def rate(self, name: str, tags: Optional[Dict[str, str]] = None,
             window_s: float = 60.0,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second increase over the window, summed across matching
        counter series. Negative steps (process restart zeroed the
        cumulative value) contribute nothing instead of poisoning the
        rate."""
        delta = self.delta(name, tags, window_s, now)
        if delta is None:
            return None
        return delta / max(window_s, 1e-9)

    def delta(self, name: str, tags: Optional[Dict[str, str]] = None,
              window_s: float = 60.0,
              now: Optional[float] = None) -> Optional[float]:
        """Total increase over the window (reset robust), summed across
        matching series; None when no series has >= 2 window samples."""
        total, seen = 0.0, False
        for _tags_key, s in self._matching(name, tags):
            with self._lock:
                samples = list(s.samples)
            if now is None and samples:
                now = samples[-1][0]
            win = self._window_samples(samples, (now or 0.0) - window_s)
            if len(win) < 2:
                continue
            seen = True
            for prev, cur in zip(win, win[1:]):
                total += max(0.0, cur[1] - prev[1])
        return total if seen else None

    def hist_delta(self, name: str,
                   tags: Optional[Dict[str, str]] = None,
                   window_s: float = 60.0, now: Optional[float] = None
                   ) -> Optional[Dict[str, Any]]:
        """Histogram increase over the window, merged across matching
        series into one bound-keyed delta: ``{count, sum, bounds,
        buckets}``. Consecutive samples whose bounds differ (a
        rebucketing merge upstream) are skipped for the bucket vector
        but still contribute count/sum."""
        by_bound: Dict[float, float] = {}
        count = sum_ = 0.0
        seen = False
        for _tags_key, s in self._matching(name, tags):
            if s.kind != "histogram":
                continue
            with self._lock:
                samples = list(s.samples)
            if now is None and samples:
                now = samples[-1][0]
            win = self._window_samples(samples, (now or 0.0) - window_s)
            if len(win) < 2:
                continue
            seen = True
            for prev, cur in zip(win, win[1:]):
                count += max(0.0, cur[1] - prev[1])
                sum_ += max(0.0, cur[2] - prev[2])
                if prev[3] != cur[3]:
                    continue
                bounds = cur[3]
                for i, (a, b) in enumerate(zip(prev[4], cur[4])):
                    bound = bounds[i] if i < len(bounds) else INF
                    inc = max(0.0, b - a)
                    if inc:
                        by_bound[bound] = by_bound.get(bound, 0.0) + inc
        if not seen:
            return None
        bounds = sorted(b for b in by_bound if b != INF)
        buckets = [by_bound.get(b, 0.0) for b in bounds]
        buckets.append(by_bound.get(INF, 0.0))
        return {"count": count, "sum": sum_, "bounds": bounds,
                "buckets": buckets}

    def quantile(self, name: str, q: float,
                 tags: Optional[Dict[str, str]] = None,
                 window_s: float = 60.0,
                 now: Optional[float] = None) -> Optional[float]:
        d = self.hist_delta(name, tags, window_s, now)
        if d is None:
            return None
        return quantile_from_histogram(d["bounds"], d["buckets"], q)

    # -- accounting ----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "series": len(self._series),
                "samples": sum(len(s.samples)
                               for s in self._series.values()),
                "max_series": self.max_series,
                "samples_per_series": self.samples_per_series,
                "dropped": self._dropped,
            }
