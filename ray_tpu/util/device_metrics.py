"""JAX/TPU device telemetry.

Ref analogue: the reference's per-node metrics agents export GPU/GRAM
gauges from the resource monitor (src/ray/stats/metric_defs.h) — a
TPU-native runtime needs the same visibility into the accelerator plane:
HBM in use/peak/limit per device, jit compile count and cumulative
compile seconds, and collective traffic. Everything publishes through the
util/metrics.py KV pipeline, so ``util/prometheus.render()`` exposes the
series with no extra plumbing, tagged ``{node, device}``.

Sampling is passive and cheap: nothing here imports jax — ``sample()``
is a no-op unless the calling process already imported it (workers that
never touch the accelerator pay nothing). Callers on natural edges
(replica request completion, ``/metrics`` render, ``/api/devices``)
invoke :func:`maybe_sample`, which throttles to one backend query per
``min_interval_s`` per process.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, List, Optional

from .metrics import Counter, Gauge

DEVICE_COUNT = Gauge(
    "ray_tpu_device_count",
    "Local JAX devices visible to this process.",
    tag_keys=("node", "platform"),
)
MEMORY_IN_USE = Gauge(
    "ray_tpu_device_memory_bytes_in_use",
    "Device (HBM) bytes currently allocated, per device.",
    tag_keys=("node", "device"),
)
MEMORY_PEAK = Gauge(
    "ray_tpu_device_memory_peak_bytes",
    "Peak device (HBM) bytes allocated, per device.",
    tag_keys=("node", "device"),
)
MEMORY_LIMIT = Gauge(
    "ray_tpu_device_memory_limit_bytes",
    "Device (HBM) capacity visible to the allocator, per device.",
    tag_keys=("node", "device"),
)
MEMORY_FRAGMENTATION = Gauge(
    "ray_tpu_device_memory_fragmentation_ratio",
    "Allocator fragmentation per device: reserved-but-not-live fraction "
    "of the arena (1 - live/reserved at peak). High values mean the "
    "allocator holds far more HBM than live buffers need — the failure "
    "mode that OOMs deep scan schedules.",
    tag_keys=("node", "device"),
)
JIT_COMPILES = Counter(
    "ray_tpu_device_jit_compiles_total",
    "XLA compilations observed through instrumented_jit().",
    tag_keys=("node", "fn"),
)
JIT_COMPILE_SECONDS = Counter(
    "ray_tpu_device_jit_compile_seconds_total",
    "Wall seconds spent in calls that triggered an XLA compile.",
    tag_keys=("node", "fn"),
)
COLLECTIVE_CALLS = Counter(
    "ray_tpu_device_collective_calls_total",
    "Collective ops issued through parallel.collectives (in-graph ops "
    "count once per trace, host-level ops once per call).",
    tag_keys=("node", "op"),
)
COLLECTIVE_BYTES = Counter(
    "ray_tpu_device_collective_bytes_total",
    "Payload bytes moved by host-level collectives (barrier/broadcast "
    "over the control-plane KV).",
    tag_keys=("node", "op"),
)

_lock = threading.Lock()
_last_sample = 0.0


def node_tag() -> str:
    """Short hex id of this process's node, or "local" off-cluster."""
    try:
        from ..core import runtime_context

        rt = runtime_context.current_runtime_or_none()
        if rt is not None:
            return rt.node_id.hex()[:8]
    except Exception:
        pass
    return "local"


def _memory_stats(device) -> Optional[Dict[str, Any]]:
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    return stats if isinstance(stats, dict) else None


def fragmentation_from_stats(stats: Dict[str, Any]) -> Optional[float]:
    """Allocator fragmentation ratio from a PJRT ``memory_stats()`` dict,
    or None when the backend exposes too little. Preference order:

    1. ``peak_bytes_in_use`` vs ``peak_bytes_reserved`` — the reserved
       arena the allocator grew to versus the live bytes it actually
       held at peak (the "43-46% fragmentation" number in XLA's own OOM
       diagnostics).
    2. ``bytes_in_use`` vs ``bytes_reserved`` — the instantaneous pair.
    3. ``largest_free_block_bytes`` vs free bytes under ``bytes_limit``
       — how shattered the remaining arena is.
    """
    peak_live = stats.get("peak_bytes_in_use")
    peak_reserved = stats.get("peak_bytes_reserved")
    if peak_reserved and peak_live is not None and peak_reserved > 0:
        return max(0.0, 1.0 - float(peak_live) / float(peak_reserved))
    live = stats.get("bytes_in_use")
    reserved = stats.get("bytes_reserved")
    if reserved and live is not None and reserved > 0:
        return max(0.0, 1.0 - float(live) / float(reserved))
    limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    largest_free = stats.get("largest_free_block_bytes")
    if limit and live is not None and largest_free is not None:
        free = float(limit) - float(live)
        if free > 0:
            return max(0.0, 1.0 - float(largest_free) / free)
    return None


def hbm_snapshot(device=None) -> Dict[str, Any]:
    """One device's allocator state as a plain dict — the bench's
    fragmentation probe (recorded into BENCH ab_matrix rows) and the
    payload behind the fragmentation gauge. Empty dict when the backend
    exposes no memory_stats (CPU)."""
    if device is None:
        try:
            import jax

            device = jax.local_devices()[0]
        except Exception:
            return {}
    stats = _memory_stats(device)
    if not stats:
        return {}
    out: Dict[str, Any] = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_reserved",
                "peak_bytes_reserved", "bytes_limit",
                "bytes_reservable_limit", "largest_free_block_bytes",
                "largest_alloc_size", "num_allocs"):
        if key in stats:
            try:
                out[key] = int(stats[key])
            except (TypeError, ValueError):
                pass
    frag = fragmentation_from_stats(stats)
    if frag is not None:
        out["fragmentation"] = round(frag, 4)
    return out


def sample(force: bool = False) -> List[Dict[str, Any]]:
    """Publish per-device gauges for this process and return the device
    snapshot (also the payload of the dashboard's ``/api/devices``).
    Unless ``force``, does nothing in processes that never imported jax
    — sampling must not be the thing that drags the backend in."""
    if not force and "jax" not in sys.modules:
        return []
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return []
    node = node_tag()
    by_platform: Dict[str, int] = {}
    out: List[Dict[str, Any]] = []
    for d in devices:
        platform = getattr(d, "platform", "unknown")
        by_platform[platform] = by_platform.get(platform, 0) + 1
        dev_tag = f"{platform}:{getattr(d, 'id', len(out))}"
        info: Dict[str, Any] = {"device": dev_tag, "platform": platform}
        stats = _memory_stats(d)
        if stats:
            tags = {"node": node, "device": dev_tag}
            in_use = stats.get("bytes_in_use")
            peak = stats.get("peak_bytes_in_use")
            limit = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit"
            )
            if in_use is not None:
                MEMORY_IN_USE.set(float(in_use), tags=tags)
                info["bytes_in_use"] = int(in_use)
            if peak is not None:
                MEMORY_PEAK.set(float(peak), tags=tags)
                info["peak_bytes_in_use"] = int(peak)
            if limit is not None:
                MEMORY_LIMIT.set(float(limit), tags=tags)
                info["bytes_limit"] = int(limit)
            frag = fragmentation_from_stats(stats)
            if frag is not None:
                MEMORY_FRAGMENTATION.set(frag, tags=tags)
                info["fragmentation"] = round(frag, 4)
        out.append(info)
    for platform, n in by_platform.items():
        DEVICE_COUNT.set(float(n), tags={"node": node,
                                         "platform": platform})
    return out


def maybe_sample(min_interval_s: float = 5.0) -> None:
    """Throttled :func:`sample` for hot paths (request completion,
    exposition render): at most one backend query per interval."""
    global _last_sample
    now = time.monotonic()
    with _lock:
        if now - _last_sample < min_interval_s:
            return
        _last_sample = now
    try:
        sample()
    except Exception:
        pass


def record_collective(op: str, nbytes: Optional[int] = None) -> None:
    """Count one collective op (and payload bytes when known). Called by
    parallel/collectives.py; in-graph ops fire at trace time."""
    tags = {"node": node_tag(), "op": op}
    COLLECTIVE_CALLS.inc(1, tags=tags)
    if nbytes:
        COLLECTIVE_BYTES.inc(float(nbytes), tags=tags)


def instrumented_jit(fn, *, sample_memory: bool = False,
                     tap_stride: int = 1, **jit_kwargs):
    """``jax.jit`` with compile telemetry: calls that grow the jitted
    function's executable cache (a trace+compile happened) bump the
    compile counter and attribute the call's wall time to cumulative
    compile seconds. This is the runtime-controlled compile path — the
    serving stack jits through here so recompiles (new batch shape, new
    model) are visible in ``/metrics`` instead of silent latency spikes.

    ``sample_memory=True`` additionally publishes the per-device HBM
    gauges (in-use / peak / limit / fragmentation) right after every
    compile and, throttled through :func:`maybe_sample`, on steady-state
    calls — the train-step wiring, so ``rtpu metrics`` shows train
    compile cache behavior AND the step's device footprint. It defaults
    off: the decode hot loop calls this wrapper once per generated token
    and must not pay a lock per call (the 695→652 tok/s regression).

    ``tap_stride=N`` (N>1) batches the per-call tap into a ring flushed
    once every N calls — the decode-loop wiring (ISSUE 12 satellite):
    instead of polling the executable cache around EVERY token step,
    the wrapper accumulates the window's slowest call and polls once
    per flush. A compile inside the window is still detected (cache
    growth is persistent) and its wall time attributed from the
    window's slowest call — which IS the compiling call, orders of
    magnitude over a steady step. ``wrapped.flush_taps()`` forces a
    flush at a burst boundary (the serve engine calls it when the
    decode loop goes idle), so telemetry lags by at most one burst,
    never indefinitely.

    The wrapper sits INSIDE decode hot loops (one call per generated
    token), so the steady-state tap is kept minimal: metric handles and
    tags resolve once (``with_tags`` bound recorders, created lazily on
    the first compile — by then the runtime's node id is known), and the
    executable-cache size is polled against a remembered value instead
    of twice around each call. The serve regression traced to exactly
    this tap (695 -> 652 tok/s when it re-resolved handles per token).
    """
    import functools

    import jax

    jitted = jax.jit(fn, **jit_kwargs)
    name = getattr(fn, "__name__", "jit")
    cache_size = getattr(jitted, "_cache_size", None)

    if cache_size is None:
        # No cache introspection on this jax version: passthrough, zero
        # per-call overhead (memory still sampled on the throttled path
        # when requested — train steps are seconds-long, the lock is
        # noise there).
        if sample_memory:
            @functools.wraps(fn)
            def wrapped(*args, **kwargs):
                out = jitted(*args, **kwargs)
                maybe_sample()
                return out
        else:
            wrapped = functools.wraps(fn)(
                lambda *args, **kwargs: jitted(*args, **kwargs)
            )
        wrapped.__wrapped_jit__ = jitted
        wrapped.flush_taps = lambda: None
        return wrapped

    # [last_seen_cache_size, bound_compiles, bound_seconds, countdown,
    # window_max_dt]; a mutable cell instead of nonlocal keeps the
    # closure allocation-free per call. The flush (stride boundary OR
    # an external stats()/shutdown thread) serializes on _flush_lock so
    # two concurrent flushes cannot double-count a compile against the
    # same stale before-size — the per-call path stays lock-free.
    state = [None, None, None, tap_stride, 0.0]
    _flush_lock = threading.Lock()

    def _flush_taps():
        """Poll the executable cache once for the whole window and
        publish any compile it detected. Safe to call from any thread
        at any burst boundary; resets the ring."""
        with _flush_lock:
            _flush_taps_locked()

    def _flush_taps_locked():
        state[3] = tap_stride
        before = state[0]
        if before is None or before < 0:
            return
        try:
            after = cache_size()
        except Exception:
            state[0] = -1
            return
        state[0] = after
        window_dt, state[4] = state[4], 0.0
        if after > before:
            if state[1] is None:
                tags = {"node": node_tag(), "fn": name}
                state[1] = JIT_COMPILES.with_tags(**tags)
                state[2] = JIT_COMPILE_SECONDS.with_tags(**tags)
            state[1].inc(after - before)
            state[2].inc(window_dt)
            if sample_memory:
                try:
                    sample(force=True)
                except Exception:
                    pass
        elif sample_memory:
            maybe_sample()

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        before = state[0]
        if before is None:
            try:
                before = state[0] = cache_size()
            except Exception:
                # Introspection broken: record nothing, stop polling.
                state[0] = -1
                before = -1
        if before < 0:
            return jitted(*args, **kwargs)
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        dt = time.perf_counter() - t0
        if dt > state[4]:
            state[4] = dt
        state[3] -= 1
        if state[3] <= 0:
            _flush_taps()
        return out

    wrapped.__wrapped_jit__ = jitted  # AOT API (lower/compile) passthrough
    wrapped.flush_taps = _flush_taps
    return wrapped
