"""Overload-control primitives: deadlines, adaptive concurrency limits,
circuit breakers and retry budgets.

Ref analogues: the reference serve stack's end-to-end request timeouts
(``request_timeout_s`` propagated proxy -> router -> replica), its
queue-length-based proxy admission, and the SRE-canon overload patterns
the serve layer composes them with — AIMD concurrency limiting fed by
observed latency (Netflix concurrency-limits), per-endpoint circuit
breaking with half-open probes (envoy outlier detection) and token-bucket
retry budgets capping retry amplification (finagle's RetryBudget).

One module owns the mechanisms; policy (which knob feeds which limiter)
lives with the callers:

- **Deadline propagation** — an ambient per-thread absolute deadline
  (``time.time()`` based so it survives process hops). Ingresses install
  it, ``core/actor.py``/``core/remote_function.py`` stamp it onto every
  task spec submitted under it, and ``core/executor.py`` re-installs it
  around user code on the executing worker — so a nested call three
  deployments deep still carries the original request's remaining
  budget, and an expired request is REFUSED before it ever occupies a
  worker thread (or a TPU).
- :class:`AIMDLimiter` + :class:`AdmissionGate` — adaptive concurrency
  with a bounded wait queue behind it; excess sheds *before* queueing.
- :class:`CircuitBreaker` — rolling error/latency window per endpoint,
  jittered-exponential half-open probe schedule via
  :class:`~ray_tpu.util.backoff.Backoff`.
- :class:`RetryBudget` — retries spend tokens deposited by requests, so
  a dying backend sees load shrink instead of multiply.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from ..core.exceptions import DeadlineExceededError, OverloadedError
from .backoff import Backoff

# --------------------------------------------------------------- deadlines

_tls = threading.local()


def ambient_deadline() -> float:
    """The absolute wall-clock deadline (``time.time()`` seconds)
    governing the current thread's work; ``0.0`` = none."""
    return getattr(_tls, "deadline_ts", 0.0)


def set_ambient_deadline(deadline_ts: float) -> float:
    """Install ``deadline_ts`` as this thread's deadline (0 clears);
    returns the previous value so callers can restore it."""
    prev = getattr(_tls, "deadline_ts", 0.0)
    _tls.deadline_ts = float(deadline_ts or 0.0)
    return prev


class deadline_scope:
    """``with deadline_scope(ts):`` — install/restore idiom for the
    ambient deadline (0 clears for the scope's duration)."""

    def __init__(self, deadline_ts: float):
        self._ts = float(deadline_ts or 0.0)
        self._prev = 0.0

    def __enter__(self):
        self._prev = set_ambient_deadline(self._ts)
        return self

    def __exit__(self, *exc):
        set_ambient_deadline(self._prev)
        return False


def remaining(default: Optional[float] = None) -> Optional[float]:
    """Seconds left in the ambient budget (clamped at 0), or ``default``
    when no deadline is installed. The drop-in replacement for the
    hard-coded ``timeout=`` constants the serve layer used to carry."""
    dl = ambient_deadline()
    if not dl:
        return default
    return max(0.0, dl - time.time())


def check_deadline(what: str = "") -> None:
    """Cooperative cancellation point: raise
    :class:`DeadlineExceededError` if the ambient budget is spent.
    Replicas call it before execution (refuse expired queued work) and
    long-running user code may call it mid-computation."""
    dl = ambient_deadline()
    if dl and time.time() >= dl:
        raise DeadlineExceededError(
            f"deadline exceeded{f' in {what}' if what else ''} "
            f"(budget expired {time.time() - dl:.3f}s ago)"
        )


# ------------------------------------------------- adaptive concurrency

class AIMDLimiter:
    """Additive-increase / multiplicative-decrease concurrency limit fed
    by observed latency DEGRADATION. A completion is an overload signal
    when it is slower than ``max(latency_target_s, degradation_ratio *
    rolling baseline)`` — the baseline tracks the service's own natural
    latency (fast downward, slow upward, so sustained queueing cannot
    inflate it), which keeps a slow-but-healthy service (a 3s TPU
    forward pass) growing its limit while genuine queueing (latency
    inflating vs its own baseline) still shrinks it. Overload
    multiplies the limit by ``decrease_ratio`` (debounced to once per
    ``decrease_interval_s`` so one burst of in-flight stragglers costs
    one step, not a collapse); other completions grow it by
    ``increase/limit`` (one full step per limit-worth)."""

    def __init__(self, *, initial: int = 32, min_limit: int = 1,
                 max_limit: int = 1024, latency_target_s: float = 2.0,
                 increase: float = 1.0, decrease_ratio: float = 0.7,
                 decrease_interval_s: float = 0.1,
                 degradation_ratio: float = 2.0,
                 clock: Callable[[], float] = time.monotonic):
        self._min = max(1, int(min_limit))
        self._max = max(self._min, int(max_limit))
        self._limit = float(min(max(int(initial), self._min), self._max))
        self._target = float(latency_target_s)
        self._increase = float(increase)
        self._ratio = min(1.0, max(0.1, float(decrease_ratio)))
        self._interval = float(decrease_interval_s)
        self._degradation = max(1.0, float(degradation_ratio))
        self._clock = clock
        self._lock = threading.Lock()
        self._inflight = 0
        self._last_decrease = 0.0
        self._ewma = 0.0
        self._baseline = 0.0
        self.sheds = 0

    @property
    def limit(self) -> int:
        return int(self._limit)

    @property
    def inflight(self) -> int:
        return self._inflight

    @property
    def ewma_latency_s(self) -> float:
        return self._ewma

    def try_acquire(self) -> bool:
        with self._lock:
            if self._inflight < int(self._limit):
                self._inflight += 1
                return True
            self.sheds += 1
            return False

    def _decrease(self, now: float) -> None:
        if now - self._last_decrease >= self._interval:
            self._limit = max(float(self._min), self._limit * self._ratio)
            self._last_decrease = now

    def on_reject(self) -> None:
        """Downstream pushed back (queue full, replica shed): treat as
        an overload signal even though nothing completed."""
        with self._lock:
            self._decrease(self._clock())

    def release(self, latency_s: Optional[float] = None,
                overloaded: bool = False) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            degraded = False
            if latency_s is not None:
                self._ewma = (latency_s if self._ewma == 0.0
                              else 0.8 * self._ewma + 0.2 * latency_s)
                # Baseline follows improvements quickly and degradation
                # slowly: a queueing episode cannot talk its way into
                # the baseline before the limiter reacts to it.
                if self._baseline == 0.0:
                    self._baseline = latency_s
                elif latency_s < self._baseline:
                    self._baseline += 0.2 * (latency_s - self._baseline)
                else:
                    self._baseline += 0.02 * (latency_s - self._baseline)
                degraded = latency_s > max(
                    self._target, self._degradation * self._baseline
                )
            if overloaded or degraded:
                self._decrease(self._clock())
            elif latency_s is not None:
                self._limit = min(
                    float(self._max),
                    self._limit + self._increase / max(1.0, self._limit),
                )


class AdmissionGate:
    """An :class:`AIMDLimiter` with a BOUNDED wait queue behind it.

    ``acquire`` admits immediately while the limiter has room; past the
    limit the caller queues — but only up to ``max_queue`` waiters, and
    a queued request is EVICTED by age the moment its deadline passes
    (or after ``max_wait_s``). Everything beyond sheds instantly with
    :class:`OverloadedError` carrying a ``retry_after_s`` hint — the
    proxy turns that into ``503 + Retry-After`` *before* any work
    queues, which is what keeps an overloaded ingress at a bounded p99
    instead of melting."""

    def __init__(self, limiter: AIMDLimiter, *, max_queue: int = 64,
                 max_wait_s: float = 10.0,
                 default_retry_after_s: float = 1.0):
        self.limiter = limiter
        self._max_queue = max(0, int(max_queue))
        self._max_wait = float(max_wait_s)
        self._default_retry = float(default_retry_after_s)
        self._cv = threading.Condition()
        self._waiting = 0
        self.shed_full = 0
        self.shed_expired = 0

    @property
    def queued(self) -> int:
        return self._waiting

    def retry_after_s(self) -> float:
        ewma = self.limiter.ewma_latency_s
        return max(0.1, min(30.0, 2.0 * ewma)) if ewma else \
            self._default_retry

    def acquire(self, deadline_ts: float = 0.0) -> None:
        if self.limiter.try_acquire():
            return
        with self._cv:
            if self._waiting >= self._max_queue:
                self.shed_full += 1
                self.limiter.on_reject()
                raise OverloadedError(
                    f"admission queue full ({self._waiting} waiting, "
                    f"limit {self.limiter.limit})",
                    retry_after_s=self.retry_after_s(),
                )
            self._waiting += 1
        try:
            started = time.monotonic()
            while True:
                if self.limiter.try_acquire():
                    return
                now = time.time()
                if deadline_ts and now >= deadline_ts:
                    self.shed_expired += 1
                    raise OverloadedError(
                        "shed from admission queue: request deadline "
                        "expired before a slot freed",
                        retry_after_s=self.retry_after_s(),
                    )
                if time.monotonic() - started >= self._max_wait:
                    self.shed_expired += 1
                    raise OverloadedError(
                        f"shed from admission queue after "
                        f"{self._max_wait:.1f}s",
                        retry_after_s=self.retry_after_s(),
                    )
                with self._cv:
                    self._cv.wait(0.02)
        finally:
            with self._cv:
                self._waiting -= 1

    def release(self, latency_s: Optional[float] = None,
                overloaded: bool = False) -> None:
        self.limiter.release(latency_s, overloaded=overloaded)
        with self._cv:
            self._cv.notify()


def gate_from_config(cfg) -> "AdmissionGate":
    """The ingress admission gate (HTTP proxy + gRPC share this): AIMD
    concurrency limit fed by observed end-to-end latency, bounded wait
    queue with age-based eviction behind it. Excess sheds with
    retry-after BEFORE any work queues."""
    return AdmissionGate(
        AIMDLimiter(
            initial=cfg.serve_proxy_concurrency,
            min_limit=1,
            max_limit=cfg.serve_proxy_concurrency,
            latency_target_s=cfg.serve_aimd_latency_target_s,
        ),
        max_queue=cfg.serve_shed_queue_len,
    )


class GateRegistry:
    """Per-key admission gates constructed on first use (the HTTP and
    gRPC ingresses keep one per deployment)."""

    def __init__(self, factory: Callable[[str], AdmissionGate]):
        self._factory = factory
        self._gates: Dict[str, AdmissionGate] = {}
        self._lock = threading.Lock()

    def get(self, name: str) -> AdmissionGate:
        with self._lock:
            gate = self._gates.get(name)
            if gate is None:
                gate = self._factory(name)
                self._gates[name] = gate
            return gate

    def snapshot(self) -> Dict[str, AdmissionGate]:
        with self._lock:
            return dict(self._gates)

    def clear(self) -> None:
        with self._lock:
            self._gates.clear()


# --------------------------------------------------------- circuit breaker

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

# Numeric encoding for the breaker-state gauge.
BREAKER_STATE_VALUES = {
    BREAKER_CLOSED: 0.0, BREAKER_HALF_OPEN: 1.0, BREAKER_OPEN: 2.0,
}


class CircuitBreaker:
    """Per-endpoint breaker over a rolling error/latency window.

    CLOSED: outcomes accumulate in a ``window_s`` deque; once at least
    ``min_volume`` outcomes show an error rate >= ``error_threshold``
    (completions slower than ``latency_trip_s``, when set, count as
    errors) the breaker OPENS. OPEN: ``probe_due`` turns true after a
    jittered-exponential delay (:class:`Backoff`, so a flapping endpoint
    gets probed less and less often); the router then claims ONE
    half-open probe with ``begin_probe``. HALF_OPEN: the probe's
    ``record`` closes (success, backoff resets) or re-opens (failure,
    next probe further out). A probe lost for ``probe_timeout_s``
    (caller died) becomes claimable again."""

    def __init__(self, *, error_threshold: float = 0.5,
                 min_volume: int = 5, window_s: float = 10.0,
                 open_base_s: float = 1.0, open_max_s: float = 30.0,
                 probe_timeout_s: float = 15.0,
                 latency_trip_s: float = 0.0,
                 clock: Callable[[], float] = time.monotonic,
                 seed: Optional[int] = None,
                 on_transition: Optional[Callable[[str], None]] = None):
        self._threshold = min(1.0, max(0.0, float(error_threshold)))
        self._min_volume = max(1, int(min_volume))
        self._window = float(window_s)
        self._latency_trip = float(latency_trip_s)
        self._probe_timeout = float(probe_timeout_s)
        self._bo = Backoff(base=open_base_s, factor=2.0,
                           max_delay=open_max_s, jitter=0.25, seed=seed)
        self._clock = clock
        self._lock = threading.Lock()
        self._events: deque = deque()  # (ts, ok)
        self._state = BREAKER_CLOSED
        self._next_probe_at = 0.0
        self._probe_started = 0.0
        self._on_transition = on_transition
        self.opens = 0

    @property
    def state(self) -> str:
        return self._state

    def allow(self) -> bool:
        """True iff the endpoint is routable without claiming a probe."""
        return self._state == BREAKER_CLOSED

    def probe_due(self) -> bool:
        with self._lock:
            now = self._clock()
            if self._state == BREAKER_OPEN:
                return now >= self._next_probe_at
            if self._state == BREAKER_HALF_OPEN:
                # The claimed probe never reported back: reclaimable.
                return now - self._probe_started >= self._probe_timeout
            return False

    def begin_probe(self) -> None:
        """Claim the single half-open probe slot (router sends exactly
        one request to the sick endpoint)."""
        with self._lock:
            self._state = BREAKER_HALF_OPEN
            self._probe_started = self._clock()
        self._notify(BREAKER_HALF_OPEN)

    def record(self, ok: bool, latency_s: Optional[float] = None) -> None:
        transition = None
        with self._lock:
            now = self._clock()
            if self._latency_trip > 0 and ok and latency_s is not None \
                    and latency_s > self._latency_trip:
                ok = False  # too slow counts against the endpoint
            if self._state == BREAKER_HALF_OPEN:
                if ok:
                    self._state = BREAKER_CLOSED
                    self._events.clear()
                    self._bo.reset()
                    transition = BREAKER_CLOSED
                else:
                    self._state = BREAKER_OPEN
                    self._next_probe_at = now + self._bo.next_delay()
                    transition = BREAKER_OPEN
            elif self._state == BREAKER_CLOSED:
                self._events.append((now, ok))
                while self._events and \
                        now - self._events[0][0] > self._window:
                    self._events.popleft()
                volume = len(self._events)
                errors = sum(1 for _, e_ok in self._events if not e_ok)
                if volume >= self._min_volume and \
                        errors / volume >= self._threshold:
                    self._state = BREAKER_OPEN
                    self.opens += 1
                    self._next_probe_at = now + self._bo.next_delay()
                    transition = BREAKER_OPEN
            # OPEN: a straggler completion from before the open; ignore.
        if transition is not None:
            self._notify(transition)

    def _notify(self, state: str) -> None:
        if self._on_transition is not None:
            try:
                self._on_transition(state)
            except Exception:
                pass  # breaker correctness never depends on observers


# ------------------------------------------------------------ retry budget

class RetryBudget:
    """Token-bucket retry budget: each first-try request deposits
    ``ratio`` tokens, each retry withdraws one — cluster-wide retry
    volume stays <= ``ratio`` of request volume (plus the ``reserve``
    float that keeps low-traffic retries alive), so retries cannot
    amplify an outage."""

    def __init__(self, *, ratio: float = 0.2, reserve: float = 3.0,
                 cap: float = 100.0):
        self._ratio = max(0.0, float(ratio))
        self._cap = max(1.0, float(cap))
        self._tokens = min(self._cap, max(0.0, float(reserve)))
        self._lock = threading.Lock()

    @property
    def tokens(self) -> float:
        return self._tokens

    def record_request(self) -> None:
        with self._lock:
            self._tokens = min(self._cap, self._tokens + self._ratio)

    def try_spend(self, cost: float = 1.0) -> bool:
        with self._lock:
            if self._tokens >= cost:
                self._tokens -= cost
                return True
            return False
