"""Tail-sampled flight recorder for request waterfalls.

Ref analogue: Dapper-style always-on sampling with tail retention — the
trace plane records spans for every request (core/timeline.py), but FULL
request records are kept only for the requests worth a postmortem: slow
(beyond a rolling ~p99 threshold), shed by overload control, expired
deadlines, errored, or chaos-hit. Each process keeps a bounded ring
(:class:`FlightRecorder`); retained records also flush to the cluster KV
(``__flightrec__/<node8>/<pid>``, the timeline/metrics pipeline pattern)
so worker-side retention is visible cluster-wide.

Surfaces: ``rtpu trace [--slow|--errors|--shed|--chaos]``, dashboard
``/api/traces``, and the GCS ``ProfileService.traces_dump`` fan-out
(core/gcs.py) that collects every node manager's ring like
``stacks_dump`` does. :func:`waterfall` joins a retained record back to
its spans in the timeline KV — the one-hop path from a recorded request
to its full proxy→replica→nested tree.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

import cloudpickle

from .metrics import Counter, Gauge

KV_PREFIX = "__flightrec__/"
FLUSH_INTERVAL_S = 1.0

# Retention reasons, in severity order for display. "slow" is decided by
# the rolling threshold; "slow_op" is a control-plane op that exceeded
# rpc_slow_op_s; "stalled_pull" is a data-plane pull with no byte
# progress past transfer_stall_warn_s; the rest are asserted by the
# observing surface.
REASONS = ("chaos", "error", "expired", "shed", "slow", "slow_op",
           "stalled_pull")

# ---- metric surface (validated by the rtlint obs pass) ---------------------

_REQUESTS_TOTAL = Counter(
    "ray_tpu_trace_requests_total",
    "Requests observed by the flight recorder, retained or not "
    "(surface=http|grpc|actor|other).",
    tag_keys=("surface",),
)
_RETAINED_TOTAL = Counter(
    "ray_tpu_trace_retained_total",
    "Requests whose record was retained by the tail-sampled flight "
    "recorder (reason=slow|shed|expired|error|chaos).",
    tag_keys=("reason",),
)
_ENTRIES = Gauge(
    "ray_tpu_flight_recorder_entries",
    "Request records currently held in this process's flight-recorder "
    "ring.",
    tag_keys=("pid",),
)
_ENTRIES_GAUGE = _ENTRIES.with_tags(pid=str(os.getpid()))
_RETAINED = {r: _RETAINED_TOTAL.with_tags(reason=r) for r in REASONS}


class FlightRecorder:
    """Per-process bounded ring of retained request records plus the
    rolling latency window backing the "slow" decision."""

    def __init__(self, size: int = 256, slow_floor_s: float = 1.0):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(8, int(size)))
        # Recent request durations (retained or not): the ~p99 estimate
        # is the sorted 99th of this window, floored by slow_floor_s so
        # a quiet service doesn't retain its every request.
        self._durations: deque = deque(maxlen=512)
        self._slow_floor_s = float(slow_floor_s)
        self._dirty = False
        self._flusher: Optional[threading.Thread] = None

    # -- retention decision --------------------------------------------------

    def slow_threshold_s(self) -> float:
        with self._lock:
            window = sorted(self._durations)
        if len(window) < 50:
            return self._slow_floor_s
        p99 = window[min(len(window) - 1, int(len(window) * 0.99))]
        return max(self._slow_floor_s, p99)

    def observe(self, name: str, trace_id: str, started: float,
                ended: float, *, status: Any = "ok",
                reason: Optional[str] = None, detail: str = "",
                surface: str = "other") -> Optional[Dict[str, Any]]:
        """One completed request. ``reason`` asserts retention
        (shed/expired/error/chaos); with reason=None the rolling slow
        threshold decides. Returns the retained record, or None."""
        duration = max(0.0, ended - started)
        _REQUESTS_TOTAL.inc(1, tags={"surface": surface})
        with self._lock:
            self._durations.append(duration)
        if reason is None and duration > self.slow_threshold_s():
            reason = "slow"
        if reason is None:
            return None
        return self._retain({
            "id": uuid.uuid4().hex[:16],
            "ts": started,
            "duration_s": round(duration, 6),
            "trace_id": trace_id or "",
            "name": name,
            "status": str(status),
            "reason": reason,
            "detail": detail,
            "surface": surface,
            "pid": os.getpid(),
            "node": _node8(),
        })

    def note_chaos(self, point: str, trace_id: str = "",
                   detail: str = "") -> Dict[str, Any]:
        """A chaos injection fired inside (or near) a request: retain a
        record immediately — the request side may never complete."""
        now = time.time()
        return self._retain({
            "id": uuid.uuid4().hex[:16],
            "ts": now,
            "duration_s": 0.0,
            "trace_id": trace_id or "",
            "name": f"chaos:{point}",
            "status": "chaos",
            "reason": "chaos",
            "detail": detail,
            "surface": "chaos",
            "pid": os.getpid(),
            "node": _node8(),
        })

    def _retain(self, record: Dict[str, Any]) -> Dict[str, Any]:
        handle = _RETAINED.get(record["reason"])
        if handle is not None:
            handle.inc()
        else:  # pragma: no cover - unknown reason still counted
            _RETAINED_TOTAL.inc(1, tags={"reason": record["reason"]})
        with self._lock:
            self._ring.append(record)
            self._dirty = True
            n = len(self._ring)
        _ENTRIES_GAUGE.set(float(n))
        # NEVER flush inline: retain sites include chaos firings on the
        # NM/GCS event loops, where a blocking kv_put round-trip would
        # deadlock the loop it needs to answer. The KV mirror runs on a
        # dedicated flusher thread (metrics.py's pattern).
        self._ensure_flusher()
        return record

    def _ensure_flusher(self) -> None:
        with self._lock:
            if self._flusher is not None:
                return
            self._flusher = threading.Thread(
                target=self._flush_loop,
                name="ray_tpu-flightrec-flusher", daemon=True,
            )
            self._flusher.start()

    def _flush_loop(self) -> None:
        while True:
            time.sleep(FLUSH_INTERVAL_S)
            try:
                self.maybe_flush()
            except Exception:
                pass

    # -- read side -----------------------------------------------------------

    def list(self, reason: Optional[str] = None,
             limit: int = 100) -> List[Dict[str, Any]]:
        """Retained records oldest-first; ``limit`` keeps the newest."""
        with self._lock:
            rows = list(self._ring)
        if reason:
            rows = [r for r in rows if r.get("reason") == reason]
        if limit and limit > 0:
            rows = rows[-limit:]
        return rows

    def stats(self) -> Dict[str, Any]:
        threshold = self.slow_threshold_s()
        with self._lock:
            return {
                "entries": len(self._ring),
                "window": len(self._durations),
                "slow_threshold_s": round(threshold, 6),
            }

    # -- KV mirror -----------------------------------------------------------

    def maybe_flush(self) -> None:
        """Mirror the ring to the cluster KV if dirty. Runs on the
        flusher thread (or a test caller) — never on a request path or
        an event loop: kv_put blocks."""
        with self._lock:
            if not self._dirty:
                return
            self._dirty = False
            rows = list(self._ring)
        from ..core import runtime_context

        rt = runtime_context.current_runtime_or_none()
        if rt is None:
            with self._lock:
                self._dirty = True  # retry once a runtime exists
            return
        try:
            rt.kv_put(f"{KV_PREFIX}{_node8()}/{os.getpid()}",
                      cloudpickle.dumps(rows))
        except Exception:
            with self._lock:
                self._dirty = True


def _node8() -> str:
    from ..core import runtime_context

    rt = runtime_context.current_runtime_or_none()
    if rt is not None and getattr(rt, "node_id", None) is not None:
        return rt.node_id.hex()[:8]
    return "local"


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                from ..core.config import get_config

                cfg = get_config()
                _recorder = FlightRecorder(
                    size=getattr(cfg, "flight_recorder_size", 256),
                    slow_floor_s=getattr(cfg, "flight_recorder_slow_s",
                                         1.0),
                )
    return _recorder


def observe_request(name: str, trace_id: str, started: float,
                    ended: float, *, status: Any = "ok",
                    reason: Optional[str] = None, detail: str = "",
                    surface: str = "other") -> Optional[Dict[str, Any]]:
    """Module-level convenience over :meth:`FlightRecorder.observe`;
    never raises — the recorder must not fail the request it records."""
    try:
        return get_recorder().observe(
            name, trace_id, started, ended, status=status, reason=reason,
            detail=detail, surface=surface,
        )
    except Exception:
        return None


def note_chaos(point: str, trace_id: str = "", detail: str = "") -> None:
    try:
        get_recorder().note_chaos(point, trace_id=trace_id, detail=detail)
    except Exception:
        pass


# ---------------------------------------------------------- aggregation

def list_cluster(reason: Optional[str] = None, limit: int = 200,
                 include_gcs: bool = True) -> List[Dict[str, Any]]:
    """Retained records cluster-wide: this process's ring, every ring
    mirrored to the KV (workers/replicas), and — when a GCS is reachable
    — the ``traces_dump`` fan-out over the node peer channels (the
    ProfileService pattern; unreachable nodes degrade to a partial
    result). Deduped by record id, oldest-first, newest ``limit`` kept."""
    from ..core import runtime_context

    rows: Dict[str, Dict[str, Any]] = {}

    def absorb(batch):
        for r in batch or ():
            if isinstance(r, dict) and r.get("id"):
                rows[r["id"]] = r

    absorb(get_recorder().list(limit=0))
    rt = runtime_context.current_runtime_or_none()
    if rt is not None:
        try:
            for key in rt.kv_keys(KV_PREFIX):
                blob = rt.kv_get(key)
                if blob is not None:
                    absorb(cloudpickle.loads(blob))
        except Exception:
            pass
        if include_gcs and hasattr(rt, "cluster_traces"):
            try:
                reply = rt.cluster_traces()
                for node in reply.get("nodes", ()):
                    absorb(node.get("records"))
            except Exception:
                pass
    out = sorted(rows.values(), key=lambda r: r.get("ts", 0.0))
    if reason:
        out = [r for r in out if r.get("reason") == reason]
    if limit and limit > 0:
        out = out[-limit:]
    return out


def waterfall(trace_id: str) -> Dict[str, Any]:
    """Join one trace id back to its spans: every timeline event across
    the cluster carrying ``trace_id``, sorted by start time, plus any
    retained flight-recorder records for it."""
    from ..core.timeline import timeline as _cluster_spans

    spans = [
        {
            "name": ev["name"],
            "start": ev["ts"] / 1e6,
            "duration_s": ev["dur"] / 1e6,
            "span_id": ev["args"].get("span_id", ""),
            "parent_id": ev["args"].get("parent_id", ""),
            "task_id": ev["args"].get("task_id", ""),
            "where": f"{ev.get('pid', '')}/{ev.get('tid', '')}",
        }
        for ev in _cluster_spans()
        if ev.get("args", {}).get("trace_id") == trace_id
    ]
    spans.sort(key=lambda s: s["start"])
    records = [r for r in list_cluster(limit=0, include_gcs=False)
               if r.get("trace_id") == trace_id]
    return {"trace_id": trace_id, "spans": spans, "records": records}


def format_waterfall(tree: Dict[str, Any]) -> str:
    """Render a waterfall as indented text (parents before children,
    indent by parent-link depth; offsets relative to the first span)."""
    spans = tree.get("spans", [])
    if not spans:
        return f"trace {tree.get('trace_id', '?')}: no spans recorded"
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}

    def depth(s, seen=None):
        seen = seen or set()
        d = 0
        parent = s.get("parent_id")
        while parent and parent in by_id and parent not in seen:
            seen.add(parent)
            d += 1
            parent = by_id[parent].get("parent_id")
        return d

    t0 = spans[0]["start"]
    lines = [f"trace {tree['trace_id']} ({len(spans)} span(s))"]
    for s in spans:
        indent = "  " * (1 + depth(s))
        off_ms = (s["start"] - t0) * 1e3
        dur_ms = s["duration_s"] * 1e3
        lines.append(f"{indent}{s['name']}  +{off_ms:.1f}ms "
                     f"{dur_ms:.1f}ms  [{s['where']}]")
    for r in tree.get("records", ()):
        lines.append(f"  retained: reason={r['reason']} "
                     f"status={r['status']} "
                     f"duration={r['duration_s'] * 1e3:.1f}ms "
                     f"({r['name']})")
    return "\n".join(lines)
