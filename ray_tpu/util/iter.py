"""ParallelIterator: lazy sharded iterators over cluster actors.

Ref analogue: python/ray/util/iter.py — ``from_items/from_range/
from_iterators`` build a sharded iterator; ``for_each/filter/batch/
flatten`` chain lazily; ``gather_sync/gather_async`` materialize shard
actors and pull items to the driver (sync = round-robin order,
async = completion order). The heavier data plane lives in
ray_tpu.data; this is the lightweight actor-iterator utility the
reference keeps alongside it.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Iterator, List, Optional

_STOP = "__parallel_iter_stop__"


class _ShardActor:
    """Owns one shard's source iterator + the op chain."""

    def __init__(self, builder_blob: bytes):
        import cloudpickle

        builder, ops = cloudpickle.loads(builder_blob)
        it = iter(builder())
        for kind, fn in ops:
            if kind == "for_each":
                it = map(fn, it)
            elif kind == "filter":
                it = filter(fn, it)
            elif kind == "flatten":
                it = (x for batch in it for x in batch)
            elif kind == "batch":
                it = self._batched(it, fn)
            else:
                raise ValueError(f"unknown op {kind}")
        self._it = it

    @staticmethod
    def _batched(it: Iterator, n: int) -> Iterator[List[Any]]:
        while True:
            chunk = list(itertools.islice(it, n))
            if not chunk:
                return
            yield chunk

    def next_items(self, n: int) -> List[Any]:
        """Up to n items; trailing _STOP marks exhaustion."""
        out = list(itertools.islice(self._it, n))
        if len(out) < n:
            out.append(_STOP)
        return out


class LocalIterator:
    """Driver-side iterator over gathered shard output."""

    def __init__(self, gen: Iterable):
        self._gen = iter(gen)

    def __iter__(self):
        return self._gen

    def __next__(self):
        return next(self._gen)

    def take(self, n: int) -> List[Any]:
        return list(itertools.islice(self._gen, n))


class ParallelIterator:
    def __init__(self, builders: List[Callable[[], Iterable]],
                 ops: Optional[List] = None):
        self._builders = builders
        self._ops = list(ops or [])

    # -- constructors -------------------------------------------------

    @staticmethod
    def from_items(items: List[Any],
                   num_shards: int = 2) -> "ParallelIterator":
        shards: List[List[Any]] = [[] for _ in range(num_shards)]
        for i, x in enumerate(items):
            shards[i % num_shards].append(x)
        return ParallelIterator(
            [(lambda s=s: list(s)) for s in shards]
        )

    @staticmethod
    def from_range(n: int, num_shards: int = 2) -> "ParallelIterator":
        def make(shard):
            return lambda: range(shard, n, num_shards)

        return ParallelIterator([make(s) for s in range(num_shards)])

    @staticmethod
    def from_iterators(generators: List[Callable[[], Iterable]]
                       ) -> "ParallelIterator":
        return ParallelIterator(list(generators))

    # -- lazy transforms ----------------------------------------------

    def _chain(self, kind: str, fn) -> "ParallelIterator":
        return ParallelIterator(self._builders,
                                self._ops + [(kind, fn)])

    def for_each(self, fn: Callable[[Any], Any]) -> "ParallelIterator":
        return self._chain("for_each", fn)

    def filter(self, fn: Callable[[Any], bool]) -> "ParallelIterator":
        return self._chain("filter", fn)

    def batch(self, n: int) -> "ParallelIterator":
        return self._chain("batch", n)

    def flatten(self) -> "ParallelIterator":
        return self._chain("flatten", None)

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        """Valid when both sides share the same op chain (gather first
        otherwise — ops apply per-shard)."""
        if self._ops != other._ops:
            raise ValueError(
                "union requires identical op chains; call gather first"
            )
        return ParallelIterator(self._builders + other._builders,
                                self._ops)

    @property
    def num_shards(self) -> int:
        return len(self._builders)

    # -- materialization ----------------------------------------------

    def _spawn(self):
        import cloudpickle

        import ray_tpu

        actor_cls = ray_tpu.remote(_ShardActor)
        return [
            actor_cls.remote(cloudpickle.dumps((b, self._ops)))
            for b in self._builders
        ]

    def gather_sync(self, batch: int = 64) -> LocalIterator:
        """Round-robin over shards, preserving per-shard order."""
        import ray_tpu

        actors = self._spawn()

        def gen():
            try:
                live = {i: a for i, a in enumerate(actors)}
                buffers = {
                    i: a.next_items.remote(batch)
                    for i, a in live.items()
                }
                while live:
                    for i in sorted(list(live)):
                        if i not in live:
                            continue
                        items = ray_tpu.get(buffers[i])
                        done = items and items[-1] == _STOP
                        if done:
                            items = items[:-1]
                            del live[i]
                            del buffers[i]
                        else:
                            buffers[i] = live[i].next_items.remote(
                                batch
                            )
                        for x in items:
                            yield x
            finally:
                for a in actors:
                    try:
                        ray_tpu.kill(a)
                    except Exception:
                        pass

        return LocalIterator(gen())

    def gather_async(self, batch: int = 64) -> LocalIterator:
        """Completion order across shards (faster shards stream first)."""
        import ray_tpu

        actors = self._spawn()

        def gen():
            try:
                owner = {}
                for a in actors:
                    ref = a.next_items.remote(batch)
                    owner[ref] = a
                while owner:
                    ready, _ = ray_tpu.wait(list(owner), num_returns=1)
                    ref = ready[0]
                    a = owner.pop(ref)
                    items = ray_tpu.get(ref)
                    if items and items[-1] == _STOP:
                        items = items[:-1]
                    else:
                        owner[a.next_items.remote(batch)] = a
                    for x in items:
                        yield x
            finally:
                for a in actors:
                    try:
                        ray_tpu.kill(a)
                    except Exception:
                        pass

        return LocalIterator(gen())

    def take(self, n: int) -> List[Any]:
        return self.gather_sync().take(n)

    def count(self) -> int:
        return sum(1 for _ in self.gather_sync())

    def show(self, n: int = 20):
        for x in self.take(n):
            print(x)

    def __repr__(self):
        return (f"ParallelIterator[{self.num_shards} shards, "
                f"{len(self._ops)} ops]")


from_items = ParallelIterator.from_items
from_range = ParallelIterator.from_range
from_iterators = ParallelIterator.from_iterators
