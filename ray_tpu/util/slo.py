"""Per-deployment SLO engine: goodput SLIs + error-budget burn rates.

Ref analogue: the multi-window, multi-burn-rate alerting pattern of the
Google SRE workbook (ch. 5), applied to the serve telemetry the
`__metrics__` KV pipeline already aggregates. The head GCS evaluates
every declared spec against the in-process TSDB (util/tsdb.py) each
``slo_eval_interval_s``:

- **goodput SLI** over a window: requests that completed successfully
  AND within ``latency_target_s``, over all requests (sheds, deadline
  kills, and non-2xx responses count as bad);
- **objective**: the spec's two halves combine additively — allowed
  badness is ``(1 - availability) + (1 - latency_percentile)``, i.e.
  a p99<=500ms + 99.9% availability spec tolerates 1.1% bad requests;
- **burn rate**: ``(1 - goodput) / (1 - objective)`` — 1.0 means the
  error budget is being spent exactly at the sustainable pace;
- **multi-window alerts**: a pair fires only when BOTH its short and
  long windows exceed the threshold (fast 5m/1h @ 14.4x for paging,
  slow 30m/6h @ 6x for ticketing), deduped while the condition
  persists: one WARNING ``SLO`` cluster event on crossing, one INFO on
  clearing, nothing in between.

Specs are declared at ``serve.deploy(..., slo={...})``; the controller
publishes them under ``__slo__/<deployment>`` in the cluster KV, and
the engine publishes its status back under ``__slo_status__`` where the
controller's autoscaling loop and the cluster Autoscaler read it.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional, Tuple

from .metrics import Gauge
from .tsdb import TSDB, fraction_le, quantile_from_histogram  # noqa: F401

# KV keys of the spec/status exchange (controller <-> GCS engine).
SPEC_PREFIX = "__slo__/"
STATUS_KEY = "__slo_status__"

# Latency SLI sources, most- to least-preferred: the ingress histogram
# sees end-to-end latency but only exists for HTTP/gRPC traffic; the
# replica-processing histogram covers handle-driven deployments too
# (chaos-injected replica latency lands inside its measured window).
LATENCY_SOURCES = (
    "ray_tpu_serve_request_latency_seconds",
    "ray_tpu_serve_replica_processing_seconds",
)
REQUESTS_TOTAL = "ray_tpu_serve_requests_total"
SHED_TOTAL = "ray_tpu_serve_shed_total"
DEADLINE_TOTAL = "ray_tpu_serve_deadline_exceeded_total"

GOODPUT_RATIO = Gauge(
    "ray_tpu_slo_goodput_ratio",
    "Fraction of requests meeting the deployment's SLO over one "
    "evaluation window (1.0 with no traffic).",
    tag_keys=("deployment", "window"),
)
BURN_RATE = Gauge(
    "ray_tpu_slo_burn_rate",
    "Error-budget burn rate over one evaluation window (1.0 = spending "
    "the budget exactly at the sustainable pace).",
    tag_keys=("deployment", "window"),
)
BUDGET_REMAINING = Gauge(
    "ray_tpu_slo_budget_remaining",
    "Fraction of the error budget left over the longest window "
    "(clamped to [0, 1]).",
    tag_keys=("deployment",),
)

_SPEC_KEYS = {
    "latency_target_s", "latency_percentile", "availability",
    "windows", "burn_thresholds",
}
DEFAULT_WINDOWS = {"fast": (300.0, 3600.0), "slow": (1800.0, 21600.0)}
DEFAULT_THRESHOLDS = {"fast": 14.4, "slow": 6.0}


def normalize_spec(slo: Dict[str, Any]) -> Dict[str, Any]:
    """Validate + default one ``serve.deploy(..., slo={...})`` spec.
    Raises ValueError at deploy time, not eval time — a typo'd key must
    fail the deploy, not silently disable the objective."""
    if not isinstance(slo, dict):
        raise ValueError(f"slo spec must be a dict, got {type(slo).__name__}")
    unknown = set(slo) - _SPEC_KEYS
    if unknown:
        raise ValueError(
            f"unknown slo spec key(s) {sorted(unknown)} "
            f"(allowed: {sorted(_SPEC_KEYS)})"
        )
    target = float(slo.get("latency_target_s", 0.5))
    pctl = float(slo.get("latency_percentile", 0.99))
    avail = float(slo.get("availability", 0.999))
    if target <= 0:
        raise ValueError("latency_target_s must be > 0")
    if not 0.0 < pctl <= 1.0:
        raise ValueError("latency_percentile must be in (0, 1]")
    if not 0.0 < avail <= 1.0:
        raise ValueError("availability must be in (0, 1]")
    windows: Dict[str, Tuple[float, float]] = {}
    for pair, default in DEFAULT_WINDOWS.items():
        w = (slo.get("windows") or {}).get(pair, default)
        short, long_ = float(w[0]), float(w[1])
        if not 0 < short < long_:
            raise ValueError(
                f"windows[{pair!r}] must be [short, long] with "
                f"0 < short < long, got {list(w)}"
            )
        windows[pair] = (short, long_)
    thresholds = {
        pair: float((slo.get("burn_thresholds") or {}).get(pair, default))
        for pair, default in DEFAULT_THRESHOLDS.items()
    }
    objective = max(0.0, avail + pctl - 1.0)
    return {
        "latency_target_s": target,
        "latency_percentile": pctl,
        "availability": avail,
        "objective": objective,
        "windows": {k: list(v) for k, v in windows.items()},
        "burn_thresholds": thresholds,
    }


class SloEngine:
    """Evaluate declared specs against a TSDB; dedup alert events.

    ``emit_event(severity, message, custom_fields)`` is the event
    transport (the GCS wires it to its cluster-event recorder; unit
    tests pass a list collector).
    """

    def __init__(self, emit_event: Optional[Callable] = None):
        self._emit = emit_event
        # (deployment, pair) -> True while the alert condition holds.
        self._active: Dict[Tuple[str, str], bool] = {}
        self.status: Dict[str, Dict[str, Any]] = {}

    # -- SLI math ------------------------------------------------------------

    def _window_sli(self, tsdb: TSDB, deployment: str, spec: Dict,
                    window_s: float, now: float) -> Tuple[float, float]:
        """(goodput, total_requests) over one window."""
        tags = {"deployment": deployment}
        lat = None
        for source in LATENCY_SOURCES:
            lat = tsdb.hist_delta(source, tags, window_s, now)
            if lat is not None and lat["count"] > 0:
                break
        count = lat["count"] if lat else 0.0
        good = count
        if lat and count > 0:
            frac = fraction_le(lat["bounds"], lat["buckets"],
                               spec["latency_target_s"])
            if frac is not None:
                good = count * frac
        bad_extra = 0.0
        for name in (SHED_TOTAL, DEADLINE_TOTAL):
            bad_extra += tsdb.delta(name, tags, window_s, now) or 0.0
        # Non-2xx ingress responses that DID reach the latency histogram
        # (5xx at the proxy): count them as bad on top of slowness.
        errors = 0.0
        for row in tsdb.query(REQUESTS_TOTAL, tags):
            row_tags = dict(row["tags"])
            code = str(row_tags.get("code", ""))
            if code and not (code.startswith("2") or
                             code.lower() in ("ok", "200")):
                errors += tsdb.delta(
                    REQUESTS_TOTAL, dict(row_tags), window_s, now) or 0.0
        good = max(0.0, good - errors)
        total = count + bad_extra
        if total <= 0:
            return 1.0, 0.0
        return min(1.0, good / total), total

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, tsdb: TSDB, specs: Dict[str, Dict[str, Any]],
                 now: float) -> Dict[str, Dict[str, Any]]:
        """One eval tick over every declared spec; returns (and retains
        as ``self.status``) the per-deployment status map the KV blob /
        ``slo_status`` RPC / autoscalers consume."""
        status: Dict[str, Dict[str, Any]] = {}
        for dep, spec in sorted(specs.items()):
            status[dep] = self._evaluate_one(tsdb, dep, spec, now)
        # Deployments whose spec vanished: clear alert state + gauges.
        for dep, pair in [k for k in self._active if k[0] not in specs]:
            self._active.pop((dep, pair), None)
        self.status = status
        return status

    def _evaluate_one(self, tsdb: TSDB, dep: str, spec: Dict,
                      now: float) -> Dict[str, Any]:
        budget = max(1e-9, 1.0 - spec["objective"])
        goodput: Dict[str, float] = {}
        burn: Dict[str, float] = {}
        totals: Dict[str, float] = {}
        window_set = sorted({w for pair in spec["windows"].values()
                             for w in pair})
        for w in window_set:
            g, total = self._window_sli(tsdb, dep, spec, w, now)
            key = str(int(w))
            goodput[key] = round(g, 6)
            burn[key] = round((1.0 - g) / budget, 4)
            totals[key] = total
            tags = {"deployment": dep, "window": key}
            GOODPUT_RATIO.set(goodput[key], tags=tags)
            BURN_RATE.set(burn[key], tags=tags)
        longest = str(int(window_set[-1])) if window_set else None
        remaining = 1.0
        if longest is not None:
            remaining = min(1.0, max(0.0, 1.0 - burn[longest]))
        BUDGET_REMAINING.set(remaining, tags={"deployment": dep})

        out: Dict[str, Any] = {
            "spec": spec, "goodput": goodput, "burn": burn,
            "budget_remaining": round(remaining, 6), "ts": now,
        }
        for pair, (short, long_) in spec["windows"].items():
            thr = spec["burn_thresholds"][pair]
            b_short = burn[str(int(short))]
            b_long = burn[str(int(long_))]
            firing = b_short > thr and b_long > thr
            out[f"{pair}_burn_active"] = firing
            self._transition(dep, pair, firing, thr, b_short, b_long)
        return out

    def _transition(self, dep: str, pair: str, firing: bool,
                    thr: float, b_short: float, b_long: float) -> None:
        was = self._active.get((dep, pair), False)
        if firing == was:
            return  # condition persists (or stays clear): stay silent
        self._active[(dep, pair)] = firing
        if self._emit is None:
            return
        fields = {"deployment": dep, "pair": pair, "threshold": thr,
                  "burn_short": b_short, "burn_long": b_long}
        if firing:
            self._emit(
                "WARNING",
                f"SLO burn-rate alert: deployment {dep!r} {pair} pair "
                f"burning at {b_short:.1f}x/{b_long:.1f}x "
                f"(threshold {thr}x)",
                fields,
            )
        else:
            self._emit(
                "INFO",
                f"SLO burn-rate alert cleared: deployment {dep!r} "
                f"{pair} pair back to {b_short:.1f}x/{b_long:.1f}x",
                fields,
            )


def decode_specs(kv_items: Dict[str, bytes]) -> Dict[str, Dict[str, Any]]:
    """``{key: blob}`` for keys under SPEC_PREFIX -> {deployment: spec}.
    Specs are JSON (the controller writes them; a corrupt blob is
    skipped, not fatal — the deploy-time validation already ran)."""
    specs: Dict[str, Dict[str, Any]] = {}
    for key, blob in kv_items.items():
        dep = key[len(SPEC_PREFIX):]
        try:
            spec = json.loads(blob.decode())
        except Exception:
            continue
        if isinstance(spec, dict) and "objective" in spec:
            specs[dep] = spec
    return specs


def read_status(kv_get: Callable[[str], Optional[bytes]]
                ) -> Dict[str, Dict[str, Any]]:
    """Decode the engine's published status blob via any kv_get-shaped
    callable (driver runtime, worker runtime, controller actor). {}
    when absent or unreadable."""
    try:
        blob = kv_get(STATUS_KEY)
        if not blob:
            return {}
        status = json.loads(blob.decode())
        return status if isinstance(status, dict) else {}
    except Exception:
        return {}
