"""Event-loop lag watchdogs (ref analogue: the raylet's
``event_stats`` loop-lag probes / Python's ``asyncio`` debug-mode slow
callback log, made continuous and exported as telemetry).

Each asyncio loop the system owns attaches one :class:`LoopMonitor`: a
self-scheduling ``call_later`` tick that does nothing but stamp the
clock (the tick MUST stay non-blocking — this module is in rtlint's
loop-blocking root set). A single shared daemon thread scans every
monitor ~5x/s and

- publishes ``ray_tpu_event_loop_lag_seconds{loop,pid}``: the max of
  the recent observed tick lag and the LIVE overdue time, so an
  ongoing stall is visible in ``rtpu rpc --watch`` while it happens,
  not only after the loop recovers;
- on overdue > ``loop_stall_warn_s`` emits ONE deduped WARNING
  ``SYSTEM`` event per stall episode, carrying the stalled loop
  thread's stack (util/profiler.thread_stack) and the asyncio task
  running on it — the dedup flag clears when the tick resumes.

The registry also answers :func:`thread_annotations` so ``rtpu stack``
can name the loop and current task for event-loop threads.
"""
from __future__ import annotations

import asyncio
import os
import threading
import time
from typing import Any, Dict, Optional

from .metrics import Gauge

SCAN_INTERVAL_S = 0.2

LOOP_LAG = Gauge(
    "ray_tpu_event_loop_lag_seconds",
    "Scheduling lag of an owned asyncio event loop: max of the recent "
    "watchdog tick lag and the current overdue time (loop=nm|gcs|"
    "serve_asgi|actor_asyncio|...).",
    tag_keys=("loop", "pid"),
)

_lock = threading.Lock()
_monitors: Dict[str, "LoopMonitor"] = {}
_watchdog: Optional[threading.Thread] = None


class LoopMonitor:
    """Watchdog state for one loop. All mutation of the stamp fields
    happens on the monitored loop's own thread; the watchdog thread
    only reads (benign races — a torn read costs one scan's sample)."""

    def __init__(self, name: str, loop: asyncio.AbstractEventLoop,
                 interval_s: float = 0.25):
        self.name = name
        self.loop = loop
        self.interval_s = float(interval_s)
        self.thread_id: Optional[int] = None
        self.last_tick: float = time.monotonic()
        self.max_lag: float = 0.0          # worst tick lag since last scan
        self.stalled = False               # inside a stall episode?
        self.stopped = False
        self._handle = None
        self._gauge = LOOP_LAG.with_tags(loop=name, pid=str(os.getpid()))
        try:
            loop.call_soon_threadsafe(self._tick)
        except RuntimeError:  # loop already closed
            self.stopped = True

    # -- on the monitored loop (must never block) -----------------------

    def _tick(self) -> None:
        if self.stopped:
            self._handle = None
            return
        now = time.monotonic()
        if self.thread_id is None:
            self.thread_id = threading.get_ident()
        lag = now - self.last_tick - self.interval_s
        if lag > self.max_lag:
            self.max_lag = lag
        self.last_tick = now
        self.stalled = False
        self._handle = self.loop.call_later(self.interval_s, self._tick)

    # -- on the watchdog thread -----------------------------------------

    def _scan(self, now: float) -> None:
        overdue = now - self.last_tick - self.interval_s
        lag = max(0.0, self.max_lag, overdue)
        self.max_lag = 0.0
        self._gauge.set(round(lag, 6))
        warn_s = _stall_warn_s()
        if warn_s > 0 and overdue > warn_s and not self.stalled:
            self.stalled = True  # dedup until the tick resumes
            self._emit_stall(overdue)

    def current_task_name(self) -> Optional[str]:
        try:
            task = asyncio.tasks._current_tasks.get(self.loop)
            return task.get_name() if task is not None else None
        except Exception:
            return None

    def _emit_stall(self, overdue: float) -> None:
        try:
            from . import events, profiler
            stack = (profiler.thread_stack(self.thread_id)
                     if self.thread_id else None)
            stack_text = (profiler.format_stack_text([stack])
                          if stack else "<thread not yet identified>")
            task = self.current_task_name()
            events.emit(
                events.WARNING, events.SYSTEM,
                f"event loop '{self.name}' stalled: watchdog tick "
                f"overdue {overdue:.2f}s"
                + (f" (task {task})" if task else ""),
                custom_fields={
                    "loop": self.name,
                    "overdue_s": round(overdue, 3),
                    "asyncio_task": task or "",
                    "stack": stack_text,
                },
            )
        except Exception:  # pragma: no cover - telemetry must not raise
            pass

    # -- detach ----------------------------------------------------------

    def stop(self) -> None:
        """Cancel the pending tick so a closed loop holds no stale
        callback (safe from any thread; idempotent)."""
        self.stopped = True

        def _cancel():
            if self._handle is not None:
                self._handle.cancel()
                self._handle = None

        try:
            if not self.loop.is_closed():
                self.loop.call_soon_threadsafe(_cancel)
        except RuntimeError:
            pass


def _stall_warn_s() -> float:
    try:
        from ..core.config import get_config
        return float(get_config().loop_stall_warn_s)
    except Exception:  # pragma: no cover
        return 0.0


def _watchdog_loop() -> None:
    while True:
        time.sleep(SCAN_INTERVAL_S)
        now = time.monotonic()
        with _lock:
            monitors = [m for m in _monitors.values() if not m.stopped]
        for m in monitors:
            try:
                m._scan(now)
            except Exception:  # pragma: no cover
                pass


def attach(name: str, loop: asyncio.AbstractEventLoop,
           interval_s: float = 0.25) -> LoopMonitor:
    """Attach (idempotently, by name) a watchdog to ``loop`` and make
    sure the shared scan thread runs."""
    global _watchdog
    with _lock:
        existing = _monitors.get(name)
        if existing is not None and not existing.stopped:
            return existing
        m = LoopMonitor(name, loop, interval_s)
        _monitors[name] = m
        if _watchdog is None:
            _watchdog = threading.Thread(
                target=_watchdog_loop,
                name="ray_tpu-loop-watchdog", daemon=True)
            _watchdog.start()
    return m


def detach(name: str) -> None:
    with _lock:
        m = _monitors.pop(name, None)
    if m is not None:
        m.stop()


def monitors() -> Dict[str, "LoopMonitor"]:
    with _lock:
        return dict(_monitors)


def thread_annotations() -> Dict[int, Dict[str, Any]]:
    """{thread_id: {"loop": name, "asyncio_task": name-or-None}} for
    every live monitored loop — consumed by profiler.dump_stacks so
    ``rtpu stack`` names the handler a stalled loop is stuck in."""
    out: Dict[int, Dict[str, Any]] = {}
    with _lock:
        ms = list(_monitors.values())
    for m in sorted(ms, key=lambda m: m.name):
        if m.stopped or m.thread_id is None:
            continue
        prev = out.get(m.thread_id)
        if prev is not None:
            # Several monitors can watch one loop (single-node mode
            # runs the GCS on the NM's loop): one annotation, all names.
            prev["loop"] += f"+{m.name}"
            continue
        out[m.thread_id] = {"loop": m.name,
                            "asyncio_task": m.current_task_name()}
    return out
