"""Cluster-wide profiling & hang-diagnosis plane.

Ref analogue: ``ray stack`` (python/ray/scripts) + the dashboard
reporter's ``profile_manager.py`` (py-spy wall profiles of any worker)
— here a dependency-free in-process sampler built on
``sys._current_frames()`` + ``threading.enumerate()``. Three layers sit
on top of this module:

- workers answer ``stack_dump``/``profile`` control frames on their
  reader thread (core/worker_main.py);
- each node manager fans a request out to its live workers plus itself
  and merges the replies (core/node_manager.py ``stacks_dump`` /
  ``profile_run``);
- the GCS ``ProfileService`` RPC fans out cluster-wide over the
  existing node peer channels with a timeout, so dead nodes degrade
  the reply to a partial result instead of a hang (core/gcs.py).

Surfaces: ``rtpu stack`` / ``rtpu profile``, dashboard ``/api/stacks``
+ ``/api/profile``, and the :func:`cluster_stacks` /
:func:`cluster_profile` helpers below.

Profiles aggregate to collapsed-stack counts and export as folded text
(:func:`to_folded`, flamegraph.pl-compatible) or speedscope JSON
(:func:`to_speedscope`). :class:`TaskResourceSampler` is the light
per-task CPU/RSS delta sampler workers attach to terminal task records.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

# Frames deeper than this are truncated in dumps and samples (a runaway
# recursion should not turn one sample into megabytes of strings).
MAX_STACK_DEPTH = 60

# Hard ceilings every entry point clamps to — a typo'd ?seconds=3000
# must not pin a sampling thread (or a dashboard request) for an hour.
MAX_SAMPLE_SECONDS = 30.0
MAX_SAMPLE_HZ = 250


def _frame_label(frame) -> str:
    code = frame.f_code
    return f"{code.co_filename.rsplit('/', 1)[-1]}:{code.co_name}"


# ------------------------------------------------------ one-shot dumps


def _walk_frames(frame) -> List[Dict[str, Any]]:
    frames = []
    f = frame
    depth = 0
    while f is not None and depth < MAX_STACK_DEPTH:
        code = f.f_code
        frames.append({
            "file": code.co_filename,
            "line": f.f_lineno,
            "function": code.co_name,
        })
        f = f.f_back
        depth += 1
    frames.reverse()  # outermost first, like a traceback
    return frames


def thread_stack(thread_id: int) -> Optional[Dict[str, Any]]:
    """One thread's current stack in :func:`dump_stacks` record shape,
    or None if the thread is gone (used by the loop-stall watchdog to
    capture exactly the stalled loop's thread)."""
    frame = sys._current_frames().get(thread_id)
    if frame is None:
        return None
    name, daemon = str(thread_id), False
    for t in threading.enumerate():
        if t.ident == thread_id:
            name, daemon = t.name, t.daemon
            break
    return {"thread_id": thread_id, "name": name, "daemon": daemon,
            "frames": _walk_frames(frame)}


def dump_stacks() -> List[Dict[str, Any]]:
    """Stack dump of every thread in this process (ref: ``ray stack``).

    Returns plain dicts (picklable for the control-plane frames):
    ``{"thread_id", "name", "daemon", "frames": [{"file", "line",
    "function"}, ...]}`` with frames outermost-first. Threads running a
    monitored asyncio loop additionally carry ``loop`` (the monitor
    name) and ``asyncio_task`` (the task currently executing, if any)
    so a stalled-loop stack names the offending handler.
    """
    names = {}
    for t in threading.enumerate():
        names[t.ident] = (t.name, t.daemon)
    try:
        from . import loop_monitor
        annotations = loop_monitor.thread_annotations()
    except Exception:  # pragma: no cover
        annotations = {}
    threads = []
    for tid, frame in sys._current_frames().items():
        name, daemon = names.get(tid, (str(tid), False))
        rec = {
            "thread_id": tid,
            "name": name,
            "daemon": daemon,
            "frames": _walk_frames(frame),
        }
        ann = annotations.get(tid)
        if ann:
            rec["loop"] = ann.get("loop")
            rec["asyncio_task"] = ann.get("asyncio_task")
        threads.append(rec)
    threads.sort(key=lambda t: t["name"])
    return threads


def format_stack_text(threads: List[Dict[str, Any]]) -> str:
    """Human/log rendering of a :func:`dump_stacks` result (one thread
    header + one indented line per frame, innermost last — the same
    shape as a traceback, so eyes trained on those parse it)."""
    out = []
    for t in threads:
        daemon = " daemon" if t.get("daemon") else ""
        loop = ""
        if t.get("loop"):
            task = t.get("asyncio_task")
            loop = (f" [loop {t['loop']}"
                    + (f", task {task}" if task else "") + "]")
        out.append(f"Thread {t['thread_id']} ({t['name']}){daemon}{loop}:")
        for fr in t.get("frames", ()):
            out.append(
                f"  File \"{fr['file']}\", line {fr['line']}, "
                f"in {fr['function']}"
            )
    return "\n".join(out)


# ------------------------------------------------- sampling profiles


def sample(seconds: float, hz: int = 100,
           _stop: Optional[threading.Event] = None) -> Dict[str, Any]:
    """Wall-clock stack sampling of every thread in this process,
    aggregated to collapsed-stack counts.

    Returns ``{"counts": {"<thread>;<f0>;<f1>;...": n}, "samples": N,
    "seconds": s, "hz": hz, "pid": pid}`` — keys are root-first folded
    stacks prefixed with the thread name. The calling thread excludes
    itself (it would only ever observe this loop).
    """
    seconds = max(0.0, min(float(seconds), MAX_SAMPLE_SECONDS))
    hz = max(1, min(int(hz), MAX_SAMPLE_HZ))
    interval = 1.0 / hz
    counts: Dict[str, int] = {}
    me = threading.get_ident()
    samples = 0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            parts = []
            f = frame
            depth = 0
            while f is not None and depth < MAX_STACK_DEPTH:
                parts.append(_frame_label(f))
                f = f.f_back
                depth += 1
            stack = (names.get(tid, str(tid)) + ";"
                     + ";".join(reversed(parts)))
            counts[stack] = counts.get(stack, 0) + 1
        samples += 1
        if _stop is not None and _stop.is_set():
            break
        time.sleep(interval)
    return {"counts": counts, "samples": samples, "seconds": seconds,
            "hz": hz, "pid": os.getpid()}


def sample_in_thread(seconds: float, hz: int = 100) -> Dict[str, Any]:
    """Run :func:`sample` on a dedicated thread and wait for the result.

    This is the entry point request handlers (dashboard, agent) must
    use: the sampling loop never runs ON the caller's thread, so the
    caller shows up in the profile like any other thread instead of
    polluting every sample with its own loop (``make check-obs`` lints
    dashboard handlers for direct ``sample``/``_sample_stacks`` calls).
    """
    out: Dict[str, Any] = {}

    def run():
        out.update(sample(seconds, hz))

    t = threading.Thread(target=run, name="ray_tpu-profiler", daemon=True)
    t.start()
    t.join(min(float(seconds), MAX_SAMPLE_SECONDS) + 10.0)
    return out or {"counts": {}, "samples": 0, "seconds": seconds,
                   "hz": hz, "pid": os.getpid()}


# --------------------------------------------------------- exporters


def to_folded(counts: Dict[str, int]) -> str:
    """Collapsed-stack ("folded") text: ``stack count`` per line,
    heaviest first — pipe straight into flamegraph.pl / speedscope."""
    lines = [f"{stack} {n}"
             for stack, n in sorted(counts.items(), key=lambda kv: -kv[1])]
    return "\n".join(lines) + ("\n" if lines else "")


def to_speedscope(counts: Dict[str, int],
                  name: str = "ray_tpu profile") -> Dict[str, Any]:
    """Speedscope file-format JSON (one "sampled" profile; weights are
    sample counts). Round-trips through ``json.dumps``/``loads`` and
    opens directly at speedscope.app."""
    frame_index: Dict[str, int] = {}
    frames: List[Dict[str, str]] = []
    samples: List[List[int]] = []
    weights: List[int] = []
    for stack, weight in sorted(counts.items(), key=lambda kv: -kv[1]):
        idxs = []
        for part in stack.split(";"):
            if not part:
                continue
            idx = frame_index.get(part)
            if idx is None:
                idx = frame_index[part] = len(frames)
                frames.append({"name": part})
            idxs.append(idx)
        samples.append(idxs)
        weights.append(int(weight))
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled",
            "name": name,
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
        "name": name,
        "activeProfileIndex": 0,
        "exporter": "ray_tpu.util.profiler",
    }


def merge_cluster_profile(reply: Dict[str, Any]) -> Dict[str, Any]:
    """Merge a cluster ``profile_run`` reply (per-node payloads) into
    one counts dict, prefixing each stack with its node so one
    flamegraph shows the whole cluster."""
    counts: Dict[str, int] = {}
    samples = 0
    for node in reply.get("nodes", ()):
        node8 = (node.get("node_id") or "?")[:8]
        for stack, n in (node.get("counts") or {}).items():
            key = f"node:{node8};{stack}"
            counts[key] = counts.get(key, 0) + n
        samples += node.get("samples", 0)
    return {"counts": counts, "samples": samples,
            "errors": dict(reply.get("errors") or {})}


# ------------------------------------------------ per-task resources


class TaskResourceSampler:
    """CPU-time + RSS delta of one task execution (ref analogue: the
    reporter's per-worker cpu/mem stats, scoped to a task). One
    getrusage(2) per side carries BOTH the cpu clock (ru_utime+ru_stime,
    process-wide — exactly right for single-task-at-a-time workers and
    an honest upper bound for concurrent actors) and ru_maxrss; the old
    os.times()+getrusage pair doubled the syscall count on the per-task
    hot path (syscalls run ~50us on sandboxed kernels)."""

    __slots__ = ("_t0", "_rss0")

    def start(self) -> "TaskResourceSampler":
        self._t0, self._rss0 = _cpu_and_rss()
        return self

    def finish(self) -> Dict[str, Any]:
        cpu, rss = _cpu_and_rss()
        return {
            "cpu_s": round(max(0.0, cpu - self._t0), 6),
            "max_rss_bytes": rss,
            "rss_delta_bytes": max(0, rss - self._rss0),
        }


def _cpu_and_rss() -> "tuple[float, int]":
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss is KiB on Linux, bytes on macOS.
        rss = ru.ru_maxrss if sys.platform == "darwin" else ru.ru_maxrss * 1024
        return ru.ru_utime + ru.ru_stime, rss
    except Exception:
        t = os.times()
        return t.user + t.system, 0


def _max_rss_bytes() -> int:
    try:
        import resource

        # ru_maxrss is KiB on Linux, bytes on macOS.
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return rss if sys.platform == "darwin" else rss * 1024
    except Exception:
        return 0


def process_stats(pid: int) -> Dict[str, Any]:
    """Live cpu-seconds + RSS of another process from /proc (psutil-free;
    feeds the ``list_workers()`` activity columns). Empty dict off-Linux
    or for a process that already exited."""
    out: Dict[str, Any] = {}
    try:
        with open(f"/proc/{pid}/stat") as f:
            parts = f.read().rsplit(")", 1)[-1].split()
        tick = os.sysconf("SC_CLK_TCK")
        # utime/stime are fields 14/15 of the full line = 11/12 here
        # (the split above dropped pid and (comm)).
        out["cpu_seconds"] = round((int(parts[11]) + int(parts[12])) / tick, 3)
        with open(f"/proc/{pid}/statm") as f:
            pages = int(f.read().split()[1])
        out["rss_bytes"] = pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        pass
    return out


# ------------------------------------------------- cluster entrypoints


def cluster_stacks(timeout: float = 5.0) -> Dict[str, Any]:
    """Stack dumps of every process in the cluster: head + every node
    manager + every live worker (ref: ``ray stack``, cluster-wide).
    Returns ``{"nodes": [{"node_id", "is_head", "procs": [{"pid",
    "kind", "worker_id", "threads"}]}], "errors": {node_hex: reason}}``
    — unreachable nodes land in ``errors``, never hang the call."""
    from ..core import runtime_context

    rt = runtime_context.current_runtime()
    return rt.cluster_stacks(timeout=timeout)


def cluster_profile(seconds: float = 2.0, hz: int = 100) -> Dict[str, Any]:
    """Sampled wall-clock profile of every process in the cluster over
    ``seconds``. Per-node payloads carry collapsed-stack counts keyed
    ``pid:<pid>(<kind>);<thread>;<frames...>``; merge with
    :func:`merge_cluster_profile`, export with :func:`to_folded` /
    :func:`to_speedscope`."""
    from ..core import runtime_context

    rt = runtime_context.current_runtime()
    return rt.cluster_profile(seconds=seconds, hz=hz)


# ------------------------------------------------- GIL contention proxy


from .metrics import Gauge as _Gauge  # noqa: E402 - no import cycle

GIL_WAIT_RATIO = _Gauge(
    "ray_tpu_gil_wait_ratio",
    "Sampled GIL-contention proxy: mean thread-wakeup overshoot of a "
    "short sleep, normalized by sys.getswitchinterval() and clamped "
    "to [0, 1]. ~0 idle; rises toward 1 as CPU-bound threads keep the "
    "GIL held past the switch interval.",
    tag_keys=("pid",),
)


class GilMonitor:
    """Cheap periodic GIL-contention probe.

    A ``time.sleep(probe)`` wakeup cannot re-enter Python until the GIL
    is reacquired, so ``measured - requested`` approximates the GIL
    wait this thread just paid. N probes every ``interval_s``, mean
    overshoot divided by ``sys.getswitchinterval()`` (the cadence at
    which a holder is asked to release), clamped to [0, 1] and
    published as ``ray_tpu_gil_wait_ratio{pid}``. Probe cost is
    N * probe_s of SLEEP per interval — idle CPU, not work.
    """

    PROBE_S = 0.001
    PROBES = 10

    def __init__(self, interval_s: float = 2.0):
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_ratio = 0.0
        self._gauge = GIL_WAIT_RATIO.with_tags(pid=str(os.getpid()))

    def start(self) -> "GilMonitor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="ray_tpu-gil-probe", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def sample_once(self) -> float:
        switch = max(1e-6, sys.getswitchinterval())
        excess = 0.0
        for _ in range(self.PROBES):
            t0 = time.monotonic()
            time.sleep(self.PROBE_S)
            excess += max(0.0, time.monotonic() - t0 - self.PROBE_S)
        ratio = min(1.0, (excess / self.PROBES) / switch)
        self.last_ratio = ratio
        self._gauge.set(round(ratio, 4))
        return ratio

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:  # pragma: no cover
                pass


_gil_monitor: Optional[GilMonitor] = None


def start_gil_monitor(interval_s: float = 2.0) -> GilMonitor:
    """Idempotent per-process starter (NM + workers call this)."""
    global _gil_monitor
    if _gil_monitor is None:
        _gil_monitor = GilMonitor(interval_s).start()
    return _gil_monitor
