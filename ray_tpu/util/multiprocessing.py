"""multiprocessing.Pool API over cluster tasks.

Ref analogue: python/ray/util/multiprocessing/pool.py — a drop-in
``Pool`` whose workers are cluster actors instead of forked processes,
so a pool can span nodes and survives with the cluster's fault
handling. API parity targets the stdlib surface the reference covers:
apply/apply_async, map/map_async, starmap/starmap_async,
imap/imap_unordered (chunked, lazy), close/terminate/join, context
manager.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Sequence


class TimeoutError(Exception):  # noqa: A001 - stdlib-compatible name
    pass


class AsyncResult:
    """multiprocessing.pool.AsyncResult-compatible handle over object
    refs; ``_collect`` post-processes the chunked results."""

    def __init__(self, refs: List[Any],
                 collect: Optional[Callable[[List[Any]], Any]] = None,
                 callback: Optional[Callable[[Any], None]] = None,
                 error_callback: Optional[Callable[[Exception], None]]
                 = None):
        self._refs = refs
        self._collect = collect or (lambda parts: parts)
        self._value = None
        self._error: Optional[Exception] = None
        self._done = False
        self._lock = threading.Lock()
        self._callback = callback
        self._error_callback = error_callback
        if callback is not None or error_callback is not None:
            # multiprocessing fires callbacks from a result thread the
            # moment work lands (joblib's dispatch depends on it) — not
            # lazily inside get().
            threading.Thread(target=self._resolve, daemon=True).start()

    def _resolve(self, timeout: Optional[float] = None):
        with self._lock:
            if self._done:
                return
            import ray_tpu

            try:
                parts = ray_tpu.get(self._refs, timeout=timeout)
                self._value = self._collect(parts)
                if self._callback is not None:
                    self._callback(self._value)
            except Exception as e:
                from ray_tpu.core.exceptions import GetTimeoutError

                if isinstance(e, GetTimeoutError):
                    raise TimeoutError(str(e)) from e
                self._error = e
                if self._error_callback is not None:
                    self._error_callback(e)
            self._done = True

    def get(self, timeout: Optional[float] = None):
        self._resolve(timeout)
        if self._error is not None:
            raise self._error
        return self._value

    def wait(self, timeout: Optional[float] = None):
        try:
            self._resolve(timeout)
        except TimeoutError:
            pass

    def ready(self) -> bool:
        if self._done:
            return True
        import ray_tpu

        ready, _ = ray_tpu.wait(self._refs,
                                num_returns=len(self._refs), timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self._done:
            raise ValueError("result is not ready")
        return self._error is None


def _chunks(seq: Sequence, size: int):
    it = iter(seq)
    while True:
        chunk = list(itertools.islice(it, size))
        if not chunk:
            return
        yield chunk


class Pool:
    """Task-backed process pool. ``processes`` bounds in-flight chunks
    (the cluster scheduler does the real placement)."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (),
                 ray_remote_args: Optional[dict] = None):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        cpus = ray_tpu.cluster_resources().get("CPU", 1)
        self._processes = processes or max(1, int(cpus))
        self._initializer = initializer
        self._initargs = initargs
        self._remote_args = dict(ray_remote_args or {})
        self._closed = False

    # -- internals ----------------------------------------------------

    def _submit_chunk(self, func, chunk, star: bool):
        import ray_tpu

        initializer = self._initializer
        initargs = self._initargs

        def run_chunk(items):
            if initializer is not None:
                initializer(*initargs)
            if star:
                return [func(*args) for args in items]
            return [func(x) for x in items]

        opts = self._remote_args
        task = (ray_tpu.remote(**opts)(run_chunk) if opts
                else ray_tpu.remote(run_chunk))
        return task.remote(chunk)

    def _map_refs(self, func, iterable, chunksize, star):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [self._submit_chunk(func, c, star)
                for c in _chunks(items, chunksize)]

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    # -- public api ---------------------------------------------------

    def apply(self, func, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args: tuple = (),
                    kwds: Optional[dict] = None,
                    callback=None, error_callback=None) -> AsyncResult:
        self._check_open()
        import ray_tpu

        kwds = kwds or {}

        def call():
            return func(*args, **kwds)

        ref = ray_tpu.remote(call).remote()
        return AsyncResult([ref], collect=lambda parts: parts[0],
                           callback=callback,
                           error_callback=error_callback)

    def map(self, func, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable: Iterable,
                  chunksize: Optional[int] = None,
                  callback=None, error_callback=None) -> AsyncResult:
        self._check_open()
        refs = self._map_refs(func, iterable, chunksize, star=False)
        return AsyncResult(
            refs,
            collect=lambda parts: [x for c in parts for x in c],
            callback=callback, error_callback=error_callback,
        )

    def starmap(self, func, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        return self.starmap_async(func, iterable, chunksize).get()

    def starmap_async(self, func, iterable: Iterable,
                      chunksize: Optional[int] = None,
                      callback=None, error_callback=None) -> AsyncResult:
        self._check_open()
        refs = self._map_refs(func, iterable, chunksize, star=True)
        return AsyncResult(
            refs,
            collect=lambda parts: [x for c in parts for x in c],
            callback=callback, error_callback=error_callback,
        )

    def imap(self, func, iterable: Iterable,
             chunksize: Optional[int] = None):
        """Lazy ordered iterator; chunks resolve as they finish but
        yield in submission order."""
        self._check_open()
        import ray_tpu

        refs = self._map_refs(func, iterable, chunksize, star=False)
        for ref in refs:
            for x in ray_tpu.get(ref):
                yield x

    def imap_unordered(self, func, iterable: Iterable,
                       chunksize: Optional[int] = None):
        """Lazy unordered iterator: chunks yield in COMPLETION order."""
        self._check_open()
        import ray_tpu

        pending = self._map_refs(func, iterable, chunksize, star=False)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            for x in ray_tpu.get(ready[0]):
                yield x

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
