"""Usage stats: opt-out usage reporting, local-only.

Ref analogue: python/ray/_private/usage/usage_lib.py — the reference
collects which libraries/features a cluster used and (opt-out) pings
a telemetry endpoint. This environment has zero egress, so the report
is only ever WRITTEN LOCALLY to the session directory at shutdown;
``RAY_TPU_USAGE_STATS_ENABLED=0`` disables even that. The shape
mirrors the reference's payload: schema version, runtime versions,
cluster size, and the set of libraries touched
(``record_library_usage`` calls are sprinkled the same way).
"""

from __future__ import annotations

import json
import os
import platform
import threading
import time
from typing import Any, Dict, List

_lock = threading.Lock()
_libraries: set = set()
_features: set = set()


def enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") not in (
        "0", "false", "False",
    )


def record_library_usage(name: str) -> None:
    """Mark a library as used this session (ref:
    usage_lib.record_library_usage)."""
    with _lock:
        _libraries.add(name)


def record_extra_usage_tag(key: str, value: str = "") -> None:
    with _lock:
        _features.add(f"{key}={value}" if value else key)


def build_report() -> Dict[str, Any]:
    from .._version import __version__

    report: Dict[str, Any] = {
        "schema_version": "0.1",
        "ray_tpu_version": __version__,
        "python_version": platform.python_version(),
        "os": platform.system().lower(),
        "collected_at": time.time(),
    }
    try:
        import jax

        report["jax_version"] = jax.__version__
    except Exception:
        pass
    with _lock:
        report["libraries_used"] = sorted(_libraries)
        report["extra_usage_tags"] = sorted(_features)
    try:
        from ..core import runtime_context

        if runtime_context.is_initialized():
            rt = runtime_context.current_runtime()
            nodes: List[Any] = rt.nodes()
            report["num_nodes"] = len(nodes)
            report["total_resources"] = rt.cluster_resources()
    except Exception:
        pass
    return report


def write_report(directory: str) -> str:
    """Write the usage report as JSON (local file; NOTHING is sent
    anywhere). Returns the path, or "" when disabled."""
    if not enabled():
        return ""
    path = os.path.join(directory, "usage_stats.json")
    try:
        os.makedirs(directory, exist_ok=True)
        with open(path, "w") as f:
            json.dump(build_report(), f, indent=2)
    except Exception:
        return ""
    return path
