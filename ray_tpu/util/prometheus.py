"""Prometheus text-format exposition.

Ref analogue: python/ray/_private/prometheus_exporter.py +
_private/metrics_agent.py — the reference exports OpenCensus metrics from
every process through a per-node agent; here the dashboard process
renders ONE text endpoint (`/metrics`) combining:

- core runtime counters (tasks dispatched/finished/failed, workers,
  actors, object-store bytes, spill bytes, transfer chunks — the subset
  of src/ray/stats/metric_defs.h:46-120 this runtime tracks), read
  directly from the in-process NodeManager, and
- user metrics (util/metrics.py Counter/Gauge/Histogram) aggregated
  across processes via the cluster KV.

Histograms render cumulative `_bucket{le=...}` series plus `_sum` and
`_count`, counters get the `_total` suffix — standard exposition rules,
so a stock Prometheus scraper ingests it unchanged.
"""

from __future__ import annotations

from typing import Dict, List, Optional

CORE_PREFIX = "ray_tpu"


def _escape_label_value(v) -> str:
    # Exposition-format label escaping: backslash, double-quote, AND
    # newline (a raw newline in a label value corrupts the document).
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    # HELP lines escape backslash and newline only (not quotes).
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(tags) -> str:
    if not tags:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in tags
    )
    return "{" + inner + "}"


def _exemplar_suffix(value, le) -> str:
    """OpenMetrics exemplar rendering for one bucket: `` # {trace_id=
    "..."} <value> <ts>`` — the one-hop link from a latency bucket to a
    recorded request waterfall (`rtpu trace <id>`). Empty string when
    the bucket has no exemplar, which standard Prometheus text-format
    consumers simply never see."""
    ex = (value.get("exemplars") or {}).get(le)
    if not ex or not ex.get("trace_id"):
        return ""
    return (f' # {{trace_id="{_escape_label_value(ex["trace_id"])}"}} '
            f'{ex.get("value", 0.0)} {ex.get("ts", 0.0)}')


def _hist_lines(pname: str, tags, value) -> List[str]:
    """Cumulative `_bucket{le=...}` series plus `_sum`/`_count` for one
    histogram series point ({count, sum, bounds, buckets[, exemplars]})."""
    lines: List[str] = []

    def lbl(extra=None):
        items = list(tags) + ([extra] if extra else [])
        return _fmt_labels(items)

    cum = 0
    for b, c in zip(value.get("bounds", []), value["buckets"]):
        cum += c
        lines.append(f'{pname}_bucket{lbl(("le", b))} {cum}'
                     f'{_exemplar_suffix(value, b)}')
    lines.append(f'{pname}_bucket{lbl(("le", "+Inf"))} {value["count"]}'
                 f'{_exemplar_suffix(value, "+Inf")}')
    lines.append(f"{pname}_sum{lbl()} {value['sum']}")
    lines.append(f"{pname}_count{lbl()} {value['count']}")
    return lines


def _core_lines(nm) -> List[str]:
    lines: List[str] = []

    def emit(name: str, kind: str, value, help_: str, labels=""):
        full = f"{CORE_PREFIX}_{name}"
        lines.append(f"# HELP {full} {help_}")
        lines.append(f"# TYPE {full} {kind}")
        lines.append(f"{full}{labels} {value}")

    stats = dict(nm._stats)
    emit("tasks_submitted_total", "counter",
         stats.get("tasks_submitted", 0),
         "Tasks submitted to this node manager.")
    emit("tasks_finished_total", "counter",
         stats.get("tasks_finished", 0), "Tasks finished successfully.")
    emit("tasks_failed_total", "counter",
         stats.get("tasks_failed", 0), "Tasks that failed.")
    emit("tasks_retried_total", "counter",
         stats.get("tasks_retried", 0), "Task retry attempts.")
    emit("workers_started_total", "counter",
         stats.get("workers_started", 0), "Worker processes started.")
    emit("actors_created_total", "counter",
         stats.get("actors_created", 0), "Actors created.")
    emit("workers_alive", "gauge",
         sum(1 for w in nm._workers.values() if w.state != "dead"),
         "Live worker processes on this node.")
    emit("object_store_used_bytes", "gauge", nm.directory.used_bytes,
         "Bytes held in the shared-memory object store.")
    emit("object_directory_entries", "gauge", len(nm.directory._entries),
         "Objects tracked in the location directory.")
    spill = getattr(nm, "spill_manager", None)
    if spill is not None and hasattr(spill, "used_bytes"):
        try:
            emit("spilled_bytes", "gauge", spill.used_bytes(),
                 "Bytes currently spilled to external storage.")
        except Exception:
            pass
    transfer = getattr(nm, "_transfer", None)
    if transfer is not None:
        for key, val in transfer.stats.items():
            emit(f"transfer_{key}_total", "counter", val,
                 "Inter-node object transfer counter (chunk = control "
                 "plane, range/stripe = data plane).")
        # Per-peer in-flight streamed pulls of THIS node (the
        # cluster-wide KV series covers driver-resident processes; this
        # keeps the attached node authoritative even where the KV
        # pipeline has no runtime to flush through).
        inflight = getattr(transfer, "inflight_by_peer", None)
        if callable(inflight):
            rows = sorted(inflight().items())
            if rows:
                full = f"{CORE_PREFIX}_transfer_inflight_pulls"
                lines.append(f"# HELP {full} Large-object pulls currently "
                             "streaming, per source peer.")
                lines.append(f"# TYPE {full} gauge")
                for peer, n in rows:
                    lines.append(f'{full}{{peer="{peer}"}} {n}')
    hist = getattr(nm, "_task_duration", None)
    if hist is not None:
        full = f"{CORE_PREFIX}_task_duration_seconds"
        lines.append(f"# HELP {full} Dispatch-to-completion wall time of "
                     "tasks executed on this node manager.")
        lines.append(f"# TYPE {full} histogram")
        lines += _hist_lines(full, [], hist)
    return lines


def _user_lines(report: Dict[str, Dict]) -> List[str]:
    lines: List[str] = []
    for name, m in sorted(report.items()):
        kind = m["type"]
        ptype = {"counter": "counter", "gauge": "gauge",
                 "histogram": "histogram"}[kind]
        pname = name if kind != "counter" or name.endswith("_total") \
            else f"{name}_total"
        help_ = m.get("help", "")
        if help_:
            lines.append(f"# HELP {pname} {_escape_help(help_)}")
        lines.append(f"# TYPE {pname} {ptype}")
        for tags_key, value in m["series"].items():
            if kind == "histogram":
                lines += _hist_lines(pname, tags_key, value)
            else:
                lines.append(f"{pname}{_fmt_labels(tags_key)} {value}")
    return lines


def render(nm=None) -> str:
    """Full exposition document. ``nm`` defaults to the in-process node
    manager of the current driver runtime."""
    from ..core import runtime_context
    from . import metrics as user_metrics

    lines: List[str] = []
    if nm is None:
        rt = runtime_context.current_runtime_or_none()
        nm = getattr(rt, "_nm", None) if rt is not None else None
    if nm is not None:
        try:
            lines += _core_lines(nm)
        except Exception:
            pass
    try:
        # Rendering is a natural sampling edge: refresh this process's
        # device gauges (no-op unless jax is already imported here).
        from . import device_metrics

        device_metrics.maybe_sample()
    except Exception:
        pass
    try:
        lines += _user_lines(user_metrics.get_metrics_report())
    except Exception:
        pass
    return "\n".join(lines) + "\n"
