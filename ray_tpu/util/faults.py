"""Deterministic cluster-wide fault injection — the chaos plane.

Ref analogue: the reference treats failure handling as a subsystem, not
a test trick (SURVEY §5: heartbeat/death broadcast in the GCS, bounded
pull retry in ``pull_manager.h``, chaos tests driven by
``_private/test_utils.py`` resource killers). This module gives every
degradation path in ray_tpu a first-class, *deterministic* trigger:

- **Injection points** are declared once in :data:`FAULT_POINTS`; each
  subsystem calls :func:`fire` at exactly the place where a real
  network/process fault would surface (``tools/check_metric_names.py``
  lints that every registered point has a firing site and every firing
  site names a registered point).
- **Disarmed is free**: with no plan armed, :func:`fire` is one tuple
  truth-test — safe on the direct-call and data-plane hot paths.
- **Armed cluster-wide**: a plan (list of specs, see
  :func:`validate_spec`) is armed through the GCS ``ChaosService`` and
  pushed to every node manager and worker (``chaos_update`` frames);
  late joiners receive it in their registration reply. ``rtpu chaos
  arm/disarm/list`` is the operator surface.
- **Deterministic schedules**: ``once`` (the Nth eligible hit),
  ``every`` (every Nth hit), ``prob`` (seeded RNG), ``always`` —
  per-process counters, so a seeded run replays identically.
- **Observable**: every firing publishes a WARNING CHAOS cluster event
  (PR-2 event plane), so ``rtpu events --source CHAOS`` shows exactly
  what was injected where.

Actions: ``error``/``partition`` raise :class:`InjectedFault` (a
``ConnectionError``, so existing failure paths treat it as a real
transport fault); ``latency`` returns a delay the call site sleeps.

``partition`` is STICKY where ``error`` is per-schedule: once a
partition spec fires, the whole (point, matched-context) scope the
spec names is down — every subsequent hit matching the spec's
``node``/``match`` scope fails immediately, WITHOUT consuming the
spec's mode counters, until the plan is disarmed or replaced (the
"heal"). That is what a real partition is: a link that stays down, not
a link that drops every Nth frame. A ``mode="once"`` partition
therefore models "the network cable is cut at hit N and stays cut",
while ``mode="once"`` error models a single dropped frame. Sticky
refires raise but do not re-emit a CHAOS event per hit (the arm and
the first firing are the observable records; at heartbeat rates
per-hit events would flood the store).
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional, Tuple

# ------------------------------------------------------ injection points

PEER_SEND = "peer_send"
DATA_CHANNEL_IO = "data_channel_io"
DIRECT_CHANNEL_IO = "direct_channel_io"
GCS_RPC = "gcs_rpc"
WORKER_SPAWN = "worker_spawn"
HEARTBEAT = "heartbeat"
SERVE_REPLICA = "serve_replica"
TRAIN_WORKER = "train_worker"
CHECKPOINT_IO = "checkpoint_io"

# name -> (description, advertised degradation path). The lint enforces
# exactly-once registration here and at least one fire() site per name.
FAULT_POINTS: Dict[str, str] = {
    PEER_SEND: "node<->node peer control-channel request/notify "
               "(degradation: spillback retry, peer fast-fail, partial "
               "profile fan-out)",
    DATA_CHANNEL_IO: "striped data-plane range pull "
                     "(degradation: fall back to control-plane chunks)",
    DIRECT_CHANNEL_IO: "direct actor-call channel send "
                       "(degradation: exactly-once replay over the NM "
                       "route, channel re-engages)",
    GCS_RPC: "node-manager -> GCS request "
             "(degradation: caller-side retry/backoff, reconnect window)",
    WORKER_SPAWN: "worker process spawn "
                  "(degradation: scheduler retries the spawn on the "
                  "next pass)",
    HEARTBEAT: "node load-report heartbeat "
               "(degradation: the GCS FENCES the node at a new "
               "membership epoch — node_fenced broadcast, peers tear "
               "down direct/data channels and refuse the fenced "
               "incarnation's frames, restartable actors restart on "
               "surviving nodes, lineage re-executes lost objects; on "
               "heal the zombie self-terminates its workers and "
               "re-registers as a fresh incarnation with empty state)",
    SERVE_REPLICA: "serve replica request execution "
                   "(degradation: handle retries another replica under "
                   "the retry budget, the sick replica's circuit "
                   "breaker opens, proxies shed under sustained "
                   "latency; scope to one replica via "
                   "match={'replica': ...})",
    TRAIN_WORKER: "train worker step boundary (session.report) "
                  "(degradation: the rank dies mid-step, the gang "
                  "supervisor aborts the whole gang and restarts it "
                  "from the last committed checkpoint, bounded by "
                  "FailureConfig.max_failures; scope to one rank via "
                  "match={'rank': ...})",
    CHECKPOINT_IO: "checkpoint save/restore I/O "
                   "(degradation: the half-written .tmp- directory "
                   "never becomes a committed checkpoint; restore "
                   "falls back to the previous committed entry; "
                   "scope via match={'op': 'save'|'restore'})",
}

MODES = ("always", "once", "every", "prob")
ACTIONS = ("error", "partition", "latency")


class InjectedFault(ConnectionError):
    """Raised at an armed injection point. A ``ConnectionError`` so the
    surrounding failure handling treats it exactly like a real
    transport fault (that is the point: the *recovery* code runs)."""


class _ArmedSpec:
    """Per-process state of one armed spec (hit/fire counters + RNG)."""

    __slots__ = ("point", "mode", "action", "n", "p", "seed", "delay_s",
                 "max_fires", "node", "match", "hits", "fires", "rng",
                 "spec_dict", "partitioned", "sticky_hits")

    def __init__(self, spec: Dict[str, Any]):
        self.spec_dict = dict(spec)
        self.point = spec["point"]
        self.mode = spec["mode"]
        self.action = spec["action"]
        self.n = int(spec.get("n", 1))
        self.p = float(spec.get("p", 1.0))
        self.seed = spec.get("seed")
        self.delay_s = float(spec.get("delay_s", 0.0))
        self.max_fires = int(spec.get("max_fires", 0))
        self.node = spec.get("node") or ""
        self.match = dict(spec.get("match") or {})
        self.hits = 0
        self.fires = 0
        self.rng = random.Random(self.seed)
        # Sticky partition state: once a partition spec fires, the
        # whole (point, match-scope) it names is DOWN — every
        # subsequent matching hit fails without consuming hits/fires,
        # until disarm/heal replaces the armed plan.
        self.partitioned = False
        self.sticky_hits = 0


def validate_spec(spec: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize one chaos spec; raises ``ValueError`` on anything the
    registry does not declare (the GCS validates at arm time so a typo
    fails the ``rtpu chaos arm`` call, not silently no-ops forever).

    Fields: ``point`` (required, a registered injection point),
    ``mode`` (default ``always``), ``action`` (default ``error``),
    ``n`` (every-Nth), ``p`` + ``seed`` (probabilistic), ``delay_s``
    (latency action), ``max_fires`` (0 = unbounded), ``node`` (hex
    prefix — only processes on that node fire), ``match`` ({ctx key:
    value prefix} — the fire site's context must match every entry,
    e.g. ``{"replica": "nodehex:pid"}`` scopes a serve_replica spec to
    ONE replica of a deployment)."""
    if not isinstance(spec, dict):
        raise ValueError(f"chaos spec must be a dict, got {type(spec)}")
    point = spec.get("point")
    if point not in FAULT_POINTS:
        raise ValueError(
            f"unknown injection point {point!r} "
            f"(one of {sorted(FAULT_POINTS)})"
        )
    mode = spec.get("mode", "always")
    if mode not in MODES:
        raise ValueError(f"unknown chaos mode {mode!r} (one of {MODES})")
    action = spec.get("action", "error")
    if action not in ACTIONS:
        raise ValueError(
            f"unknown chaos action {action!r} (one of {ACTIONS})"
        )
    out = {
        "point": point,
        "mode": mode,
        "action": action,
        "n": max(1, int(spec.get("n", 1))),
        "p": min(1.0, max(0.0, float(spec.get("p", 1.0)))),
        "seed": spec.get("seed"),
        "delay_s": max(0.0, float(spec.get("delay_s", 0.0))),
        "max_fires": max(0, int(spec.get("max_fires", 0))),
        "node": str(spec.get("node") or ""),
        "match": {
            str(k): str(v) for k, v in (spec.get("match") or {}).items()
        },
        # Stable identity stamped by the GCS at arm time (None for
        # direct local plans): entries retained across a plan append
        # keep their counters in apply_plan.
        "id": spec.get("id"),
    }
    if action == "latency" and out["delay_s"] <= 0:
        raise ValueError("latency action needs delay_s > 0")
    return out


# ------------------------------------------------------- armed plan state

_lock = threading.Lock()
# () when disarmed — fire()'s whole hot-path cost is this truth test.
_armed: Tuple[_ArmedSpec, ...] = ()
_plan: List[Dict[str, Any]] = []
_gen = 0
_local_node = ""


def set_local_node(node_hex: str) -> None:
    """Record which node this process belongs to (``node``-filtered
    specs only fire on matching nodes)."""
    global _local_node
    _local_node = node_hex or ""


def apply_plan(specs: List[Dict[str, Any]],
               gen: Optional[int] = None) -> None:
    """Install ``specs`` as THIS process's armed plan (replacing any
    previous one). Specs WITHOUT an ``id`` (direct local plans) always
    start from zero — determinism: re-applying an identical seeded
    plan replays identically. Specs WITH an ``id`` (stamped by the GCS
    at arm time) that match a currently-armed entry keep that entry's
    counters/RNG, so appending a new spec to the cluster plan never
    resurrects an already-exhausted ``once``/``max_fires`` spec.
    Invalid specs are dropped rather than poisoning the rest (the GCS
    already validated at arm time; this guards skewed senders)."""
    global _armed, _plan, _gen
    normalized = []
    for spec in specs or []:
        try:
            normalized.append(validate_spec(spec))
        except ValueError:
            continue
    with _lock:
        retained: Dict[Any, _ArmedSpec] = {
            a.spec_dict["id"]: a for a in _armed
            if a.spec_dict.get("id") is not None
        }
        new_armed = []
        for s in normalized:
            old = retained.get(s["id"]) if s.get("id") is not None else None
            if old is not None and old.spec_dict == s:
                new_armed.append(old)
            else:
                new_armed.append(_ArmedSpec(s))
        _plan = normalized
        _armed = tuple(new_armed)
        if gen is not None:
            _gen = int(gen)
        else:
            _gen += 1


def clear() -> None:
    """Disarm every injection point in this process."""
    apply_plan([])


def current_plan() -> List[Dict[str, Any]]:
    with _lock:
        return [dict(s) for s in _plan]


def generation() -> int:
    return _gen


def armed() -> bool:
    return bool(_armed)


def fired_counts() -> Dict[str, int]:
    """Per-point firing counts in THIS process (tests/diagnostics)."""
    with _lock:
        out: Dict[str, int] = {}
        for a in _armed:
            out[a.point] = out.get(a.point, 0) + a.fires
        return out


# ----------------------------------------------------------------- firing


def fire(point: str, **ctx: Any) -> float:
    """The injection point hook. Returns a latency delay in seconds
    (0.0 almost always; the call site sleeps it in its own idiom —
    ``time.sleep`` on threads, ``asyncio.sleep`` on loops) or raises
    :class:`InjectedFault` for error/partition actions. Disarmed cost:
    one truth test."""
    if not _armed:
        return 0.0
    return _fire_armed(point, ctx)


def _fire_armed(point: str, ctx: Dict[str, Any]) -> float:
    to_fire: List[_ArmedSpec] = []
    sticky: Optional[_ArmedSpec] = None
    with _lock:
        for a in _armed:
            if a.point != point:
                continue
            if a.node and not _local_node.startswith(a.node):
                continue
            if a.match and not all(
                str(ctx.get(k, "")).startswith(v)
                for k, v in a.match.items()
            ):
                continue  # fire-site context doesn't match the scope
            if a.action == "partition" and a.partitioned:
                # Sticky: after the first (scheduled) fire, every
                # subsequent hit matching this spec's scope fails
                # WITHOUT consuming mode counters — the cut link stays
                # cut until disarm/heal replaces the armed plan.
                a.sticky_hits += 1
                sticky = a
                continue
            a.hits += 1
            if a.max_fires and a.fires >= a.max_fires:
                continue
            if a.mode == "always":
                hit = True
            elif a.mode == "once":
                hit = a.fires == 0 and a.hits >= a.n
            elif a.mode == "every":
                hit = a.hits % a.n == 0
            else:  # prob
                hit = a.rng.random() < a.p
            if hit:
                a.fires += 1
                if a.action == "partition":
                    a.partitioned = True
                to_fire.append(a)
    if not to_fire:
        if sticky is not None:
            # No event per sticky refire (the first firing was the
            # observable record; at heartbeat rates per-hit events
            # would flood the store).
            raise InjectedFault(
                f"injected partition at {point} (sticky, "
                f"hit #{sticky.sticky_hits} after fire #{sticky.fires})"
            )
        return 0.0
    delay = 0.0
    fault: Optional[_ArmedSpec] = None
    for a in to_fire:
        _emit_chaos_event(a, ctx)
        if a.action == "latency":
            delay = max(delay, a.delay_s)
        else:
            fault = a
    if fault is None and sticky is not None:
        fault = sticky
    if fault is not None:
        raise InjectedFault(
            f"injected {fault.action} at {point} "
            f"(mode={fault.mode}, fire #{fault.fires})"
        )
    return delay


def _emit_chaos_event(a: _ArmedSpec, ctx: Dict[str, Any]) -> None:
    """Every firing is a first-class cluster event: `rtpu events
    --source CHAOS` reconstructs exactly what was injected where."""
    from . import events

    try:
        fields: Dict[str, Any] = {
            "point": a.point, "action": a.action, "mode": a.mode,
            "fire_number": a.fires, "hits": a.hits,
        }
        for k, v in ctx.items():
            fields.setdefault(k, v)
        event = events.emit(
            events.WARNING, events.CHAOS,
            f"CHAOS fired: {a.action} at {a.point} "
            f"(mode={a.mode}, fire #{a.fires})",
            custom_fields=fields,
        )
        # Tail retention: a chaos-hit request must stay retrievable from
        # the flight recorder even if its request side never completes.
        from . import flight_recorder

        flight_recorder.note_chaos(
            a.point, trace_id=event.get("trace_id") or "",
            detail=f"{a.action} mode={a.mode} fire#{a.fires}",
        )
    except Exception:
        pass  # injection must never fail because observability did
