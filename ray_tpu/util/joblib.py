"""joblib backend: scikit-learn parallelism on the cluster.

Ref analogue: python/ray/util/joblib/ (register_ray +
ray_backend.RayBackend subclassing joblib's MultiprocessingBackend
over ray.util.multiprocessing.Pool). After ``register_ray()``,

    import joblib
    from ray_tpu.util.joblib import register_ray
    register_ray()
    with joblib.parallel_backend("ray_tpu"):
        GridSearchCV(...).fit(X, y)   # fans out as cluster tasks

any joblib.Parallel user (scikit-learn's n_jobs plumbing included)
runs its batches as cluster tasks through the Pool shim.
"""

from __future__ import annotations


def register_ray() -> None:
    """Register the ``"ray_tpu"`` joblib parallel backend."""
    from joblib._parallel_backends import MultiprocessingBackend
    from joblib.parallel import register_parallel_backend

    from .multiprocessing import Pool

    class RayTpuBackend(MultiprocessingBackend):
        supports_timeout = True

        def effective_n_jobs(self, n_jobs):
            import ray_tpu

            if not ray_tpu.is_initialized():
                ray_tpu.init()
            cpus = max(1, int(
                ray_tpu.cluster_resources().get("CPU", 1)
            ))
            if n_jobs is None or n_jobs == -1:
                return cpus
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 has no meaning")
            return min(n_jobs, cpus) if n_jobs > 0 else cpus

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **memmapping_args):
            n_jobs = self.effective_n_jobs(n_jobs)
            self._pool = Pool(processes=n_jobs)
            self.parallel = parallel
            return n_jobs

        def terminate(self):
            if getattr(self, "_pool", None) is not None:
                self._pool.terminate()
                self._pool = None

    register_parallel_backend("ray_tpu", RayTpuBackend)
