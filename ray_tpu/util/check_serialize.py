"""Serializability inspector.

Ref analogue: python/ray/util/check_serialize.py
``inspect_serializability`` — when a task/actor argument fails to
pickle, walk its closure/attributes and report WHICH inner member is
the culprit instead of surfacing cloudpickle's opaque error.
"""

from __future__ import annotations

import inspect
from typing import Any, Optional, Set, Tuple

import cloudpickle


class FailureTuple:
    """One unserializable member: the object, its name, and the parent
    that carried it."""

    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailureTuple(obj={self.obj!r}, name={self.name!r})"


def _try_pickle(obj: Any) -> Optional[Exception]:
    try:
        cloudpickle.dumps(obj)
        return None
    except Exception as e:
        return e


def _scan_children(obj: Any):
    """(name, child) pairs worth blaming: closure cells, globals used
    by the function, instance attributes."""
    if inspect.isfunction(obj):
        if obj.__closure__:
            for name, cell in zip(obj.__code__.co_freevars,
                                  obj.__closure__):
                try:
                    yield name, cell.cell_contents
                except ValueError:
                    pass
        for name in obj.__code__.co_names:
            if name in obj.__globals__:
                yield name, obj.__globals__[name]
    elif hasattr(obj, "__dict__") and isinstance(obj.__dict__, dict):
        yield from obj.__dict__.items()


def inspect_serializability(
    obj: Any, name: Optional[str] = None, depth: int = 3,
    _failures: Optional[list] = None, _seen: Optional[Set[int]] = None,
    print_report: bool = True,
) -> Tuple[bool, list]:
    """Returns (serializable, [FailureTuple...]); recursively descends
    into the members of unserializable objects to find leaf culprits."""
    top = _failures is None
    failures = [] if top else _failures
    seen = set() if _seen is None else _seen
    name = name or getattr(obj, "__name__", repr(obj)[:40])

    err = _try_pickle(obj)
    if err is None:
        return True, failures

    if id(obj) in seen:
        return False, failures
    seen.add(id(obj))

    blamed_child = False
    if depth > 0:
        for child_name, child in _scan_children(obj):
            if _try_pickle(child) is not None:
                blamed_child = True
                ok, _ = inspect_serializability(
                    child, name=child_name, depth=depth - 1,
                    _failures=failures, _seen=seen,
                    print_report=False,
                )
    if not blamed_child:
        failures.append(FailureTuple(obj, name, parent=None))

    if top and print_report:
        print(f"Serialization check for {name!r}: FAILED ({err})")
        for f in failures:
            print(f"  culprit: {f.name} = {f.obj!r}")
    return False, failures
