"""Driver/worker-side pubsub subscriber API.

Ref analogue: python/ray/_private/gcs_pubsub.py (GcsSubscriber family —
the sync long-poll clients the dashboard, log monitor and autoscaler
use). A ``Subscriber`` registers a server-side queue on the GCS
publisher (core/pubsub.py) through the node manager's authenticated
proxy channel and drains it with blocking ``poll`` calls; ``publish``
fans a user event out to every subscriber of the channel.

Built-in channels: ``node_state`` (node added/dead), ``actor_state``
(named-actor registered/dropped), ``error_info``, ``logs`` — plus any
user-chosen channel name.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

from ..core.pubsub import (  # noqa: F401
    ACTOR_STATE,
    CLUSTER_EVENTS,
    ERROR_INFO,
    LOGS,
    NODE_STATE,
)


def _runtime():
    from ..core import runtime_context

    return runtime_context.current_runtime()


def publish(channel: str, data: Any, key: Optional[str] = None) -> int:
    """Publish ``data`` to every subscriber of ``channel``; returns the
    event's sequence number (0 when nobody is subscribed)."""
    return _runtime().pubsub_op(
        {"op": "publish", "channel": channel, "data": data, "key": key}
    )["seq"]


def describe_services() -> Dict[str, Any]:
    """The GCS's typed service schemas (the .proto equivalent)."""
    return _runtime().pubsub_op({"op": "describe"})["services"]


class Subscriber:
    """Blocking subscriber over the cluster pubsub.

    >>> sub = Subscriber(channels=["node_state"])
    >>> events = sub.poll(timeout=5.0)   # [] on timeout
    >>> sub.close()
    """

    def __init__(self, channels: List[str],
                 subscriber_id: Optional[str] = None):
        self.subscriber_id = subscriber_id or uuid.uuid4().hex
        self._channels = list(channels)
        _runtime().pubsub_op({
            "op": "subscribe", "subscriber_id": self.subscriber_id,
            "channels": self._channels,
        })
        self._closed = False
        self.dropped_total = 0

    def poll(self, timeout: float = 30.0,
             max_events: int = 1000) -> List[Dict[str, Any]]:
        """Long-poll: returns buffered events, or [] after ``timeout``
        with nothing published. Each event is
        {seq, channel, key, data, ts}."""
        if self._closed:
            raise RuntimeError("subscriber closed")
        reply = _runtime().pubsub_op({
            "op": "poll", "subscriber_id": self.subscriber_id,
            "timeout": timeout, "max_events": max_events,
        })
        self.dropped_total += reply.get("dropped", 0)
        return reply["events"]

    def subscribe(self, channels: List[str]):
        """Add channels to this subscription."""
        self._channels.extend(channels)
        _runtime().pubsub_op({
            "op": "subscribe", "subscriber_id": self.subscriber_id,
            "channels": list(channels),
        })

    def close(self):
        if self._closed:
            return
        self._closed = True
        try:
            _runtime().pubsub_op({
                "op": "unsubscribe",
                "subscriber_id": self.subscriber_id, "channels": None,
            })
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
