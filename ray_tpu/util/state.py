"""Cluster state API.

Ref analogue: python/ray/util/state/api.py (list_tasks / list_actors /
list_objects / list_nodes / list_workers / list_placement_groups /
list_cluster_events / summarize_*). Backed by a fan-out state query: the
local node manager merges its own live tables (plus its bounded
terminal-task history) with a ``state_snapshot`` peer RPC to every
alive node (api.py:1473's StateApiClient → raylet/GCS sources);
cluster events come from the head GCS's aggregated event store.

Every ``list_*`` takes ``filters``: a list of (key, predicate, value)
tuples with predicate "=" or "!=" (the reference's filter syntax).
Unsupported predicates raise ``ValueError`` uniformly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..core import runtime_context

Filter = Tuple[str, str, Any]


def _apply_filters(rows: List[Dict[str, Any]],
                   filters: Optional[List[Filter]]) -> List[Dict[str, Any]]:
    for key, pred, value in filters or []:
        if pred == "=":
            rows = [r for r in rows if r.get(key) == value]
        elif pred == "!=":
            rows = [r for r in rows if r.get(key) != value]
        else:
            raise ValueError(f"unsupported filter predicate {pred!r}")
    return rows


def _query(kind: str, filters: Optional[List[Filter]],
           limit: int) -> List[Dict[str, Any]]:
    rt = runtime_context.current_runtime()
    state = rt.cluster_state()
    rows = _apply_filters(state.get(kind, []), filters)
    return rows[:limit]


def list_tasks(filters: Optional[List[Filter]] = None,
               limit: int = 10_000) -> List[Dict[str, Any]]:
    """Task records across the cluster: queued/running live rows plus
    the bounded terminal history (``retained=True`` rows carry
    state/duration/error_type/error_message after the live record is
    gone; ref: list_tasks over the task-event buffer)."""
    return _query("tasks", filters, limit)


def list_actors(filters: Optional[List[Filter]] = None,
                limit: int = 10_000) -> List[Dict[str, Any]]:
    return _query("actors", filters, limit)


def list_objects(filters: Optional[List[Filter]] = None,
                 limit: int = 10_000) -> List[Dict[str, Any]]:
    return _query("objects", filters, limit)


def list_workers(filters: Optional[List[Filter]] = None,
                 limit: int = 10_000) -> List[Dict[str, Any]]:
    return _query("workers", filters, limit)


def list_nodes(filters: Optional[List[Filter]] = None,
               limit: int = 10_000) -> List[Dict[str, Any]]:
    import ray_tpu

    rows = _apply_filters(ray_tpu.nodes(), filters)
    return rows[:limit]


def list_placement_groups(filters: Optional[List[Filter]] = None,
                          limit: int = 10_000) -> List[Dict[str, Any]]:
    import ray_tpu

    table = ray_tpu.util.placement_group_table()
    rows = _apply_filters(list(table.values()), filters)
    return rows[:limit]


def list_cluster_events(filters: Optional[List[Filter]] = None,
                        severity: Optional[str] = None,
                        source: Optional[str] = None,
                        limit: int = 1000) -> List[Dict[str, Any]]:
    """Aggregated cluster events from the head's severity-indexed store
    (ref: `ray list cluster-events`). ``severity``/``source`` filter
    server-side; ``filters`` apply the standard (key, pred, value)
    syntax on top."""
    rt = runtime_context.current_runtime()
    reply = rt.list_cluster_events(severity=severity, source=source,
                                   limit=limit)
    return _apply_filters(reply["events"], filters)


def summarize_tasks() -> Dict[str, Any]:
    """Task summary (ref: summarize_tasks): counts by state — including
    the retained failure history — plus per-function duration stats for
    terminal tasks."""
    by_state: Dict[str, int] = {}
    per_func: Dict[str, Dict[str, Any]] = {}
    tasks = list_tasks()
    for t in tasks:
        by_state[t["state"]] = by_state.get(t["state"], 0) + 1
        name = t.get("name") or "task"
        f = per_func.setdefault(name, {
            "count": 0, "failed": 0, "duration_count": 0,
            "duration_sum_s": 0.0, "max_duration_s": 0.0,
        })
        f["count"] += 1
        if t["state"] == "failed":
            f["failed"] += 1
        dur = t.get("duration_s")
        if dur is not None:
            f["duration_count"] += 1
            f["duration_sum_s"] += dur
            f["max_duration_s"] = max(f["max_duration_s"], dur)
    for f in per_func.values():
        n = f.pop("duration_count")
        total = f.pop("duration_sum_s")
        f["mean_duration_s"] = round(total / n, 6) if n else None
        f["max_duration_s"] = round(f["max_duration_s"], 6) if n else None
    return {
        "total": len(tasks),
        "by_state": by_state,
        "failed": by_state.get("failed", 0),
        "per_func": per_func,
    }


def summarize_actors() -> Dict[str, int]:
    out: Dict[str, int] = {}
    for a in list_actors():
        out[a["state"]] = out.get(a["state"], 0) + 1
    return out


def summarize_objects() -> Dict[str, Any]:
    objs = list_objects()
    by_state: Dict[str, int] = {}
    by_owner: Dict[str, int] = {}
    for o in objs:
        st = o.get("state") or o.get("where") or "?"
        by_state[st] = by_state.get(st, 0) + 1
        owner = o.get("owner") or "?"
        by_owner[owner] = by_owner.get(owner, 0) + 1
    return {
        "total_objects": len(objs),
        # In-flight/spilled rows may have no size yet: count them as 0
        # instead of blowing up the whole summary.
        "total_size_bytes": sum(o.get("size_bytes") or 0 for o in objs),
        "by_location": {
            where: sum(1 for o in objs if o["where"] == where)
            for where in {o["where"] for o in objs}
        },
        # Lifecycle + producer breakdowns from the census enrichment
        # (owner "?" = pre-census rows or the plane disabled).
        "by_state": by_state,
        "by_owner": by_owner,
    }
