"""Cluster state API.

Ref analogue: python/ray/util/state/api.py (list_tasks / list_actors /
list_objects / list_nodes / list_workers / list_placement_groups /
summarize_*). Backed by a fan-out state query: the local node manager
merges its own live tables with a ``state_snapshot`` peer RPC to every
alive node (api.py:1473's StateApiClient → raylet/GCS sources).

Every ``list_*`` takes ``filters``: a list of (key, predicate, value)
tuples with predicate "=" or "!=" (the reference's filter syntax).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..core import runtime_context

Filter = Tuple[str, str, Any]


def _query(kind: str, filters: Optional[List[Filter]],
           limit: int) -> List[Dict[str, Any]]:
    rt = runtime_context.current_runtime()
    state = rt.cluster_state()
    rows = state.get(kind, [])
    for key, pred, value in filters or []:
        if pred == "=":
            rows = [r for r in rows if r.get(key) == value]
        elif pred == "!=":
            rows = [r for r in rows if r.get(key) != value]
        else:
            raise ValueError(f"unsupported filter predicate {pred!r}")
    return rows[:limit]


def list_tasks(filters: Optional[List[Filter]] = None,
               limit: int = 10_000) -> List[Dict[str, Any]]:
    """Live task records across the cluster (queued/running/finished-
    retained; ref: list_tasks)."""
    return _query("tasks", filters, limit)


def list_actors(filters: Optional[List[Filter]] = None,
                limit: int = 10_000) -> List[Dict[str, Any]]:
    return _query("actors", filters, limit)


def list_objects(filters: Optional[List[Filter]] = None,
                 limit: int = 10_000) -> List[Dict[str, Any]]:
    return _query("objects", filters, limit)


def list_workers(filters: Optional[List[Filter]] = None,
                 limit: int = 10_000) -> List[Dict[str, Any]]:
    return _query("workers", filters, limit)


def list_nodes(filters: Optional[List[Filter]] = None,
               limit: int = 10_000) -> List[Dict[str, Any]]:
    import ray_tpu

    rows = ray_tpu.nodes()
    for key, pred, value in filters or []:
        if pred == "=":
            rows = [r for r in rows if r.get(key) == value]
        elif pred == "!=":
            rows = [r for r in rows if r.get(key) != value]
    return rows[:limit]


def list_placement_groups(limit: int = 10_000) -> List[Dict[str, Any]]:
    import ray_tpu

    table = ray_tpu.util.placement_group_table()
    return list(table.values())[:limit]


def summarize_tasks() -> Dict[str, int]:
    """Task counts by state (ref: summarize_tasks)."""
    out: Dict[str, int] = {}
    for t in list_tasks():
        out[t["state"]] = out.get(t["state"], 0) + 1
    return out


def summarize_actors() -> Dict[str, int]:
    out: Dict[str, int] = {}
    for a in list_actors():
        out[a["state"]] = out.get(a["state"], 0) + 1
    return out


def summarize_objects() -> Dict[str, Any]:
    objs = list_objects()
    return {
        "total_objects": len(objs),
        "total_size_bytes": sum(o["size_bytes"] for o in objs),
        "by_location": {
            where: sum(1 for o in objs if o["where"] == where)
            for where in {o["where"] for o in objs}
        },
    }
