"""ray_tpu.util: utility APIs mirroring ray.util.

Ref analogue: python/ray/util/__init__.py — placement groups,
scheduling strategies, ActorPool, queue, metrics.
"""

from ray_tpu.core.placement_group import (  # noqa: F401
    PlacementGroup,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.core.scheduling_strategies import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
from . import check_serialize  # noqa: F401
from . import events  # noqa: F401
from . import iter  # noqa: F401
from . import metrics  # noqa: F401
from . import multiprocessing  # noqa: F401
from . import pubsub  # noqa: F401
from . import state  # noqa: F401
from . import tqdm  # noqa: F401
from .actor_pool import ActorPool  # noqa: F401
from . import queue  # noqa: F401

__all__ = [
    "state",
    "pubsub",
    "events",
    "ActorPool",
    "queue",
    "PlacementGroup",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
    "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]
