"""User-defined metrics.

Ref analogue: python/ray/util/metrics.py (Counter/Gauge/Histogram) over
the metrics agent pipeline (src/ray/stats/) — here each process batches
its metric values and flushes them to the cluster KV under
``__metrics__/<process>``; ``get_metrics_report()`` aggregates across
every process for dashboards/tests (the Prometheus exposition layer can
read the same table).
"""

from __future__ import annotations

import atexit
import os
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

FLUSH_INTERVAL_S = 0.5
KV_PREFIX = "__metrics__/"


class _Registry:
    def __init__(self):
        self.lock = threading.Lock()
        # name -> ("counter"|"gauge"|"histogram", {tags_key: value})
        self.metrics: Dict[str, Tuple[str, Dict]] = {}
        # name -> (kind, description), recorded at metric construction —
        # feeds `# HELP` lines and tools/check_metric_names.py.
        self.meta: Dict[str, Tuple[str, str]] = {}
        # Names re-declared or re-recorded under a conflicting kind.
        self.kind_conflicts: Dict[str, Tuple[str, str]] = {}
        self._warned_kinds: set = set()
        self._flusher: Optional[threading.Thread] = None
        self._dirty = False

    def ensure_flusher(self):
        if self._flusher is None:
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True
            )
            self._flusher.start()
            atexit.register(self.flush)

    def declare(self, name: str, kind: str, description: str):
        with self.lock:
            old = self.meta.get(name)
            if old is not None and old[0] != kind:
                self.kind_conflicts[name] = (old[0], kind)
                self._warn_kind_conflict(name, old[0], kind)
                return
            if old is None or (description and not old[1]):
                self.meta[name] = (kind, description)

    def _warn_kind_conflict(self, name: str, old: str, new: str):
        # Caller holds self.lock.
        if name in self._warned_kinds:
            return
        self._warned_kinds.add(name)
        warnings.warn(
            f"metric {name!r} already registered as a {old}; ignoring "
            f"records under conflicting kind {new!r} (the series would "
            f"be corrupted)",
            UserWarning,
            stacklevel=3,
        )

    def record(self, name: str, kind: str, tags_key: tuple, update):
        with self.lock:
            kind_, series = self.metrics.setdefault(name, (kind, {}))
            if kind_ != kind:
                # A second metric object reused the name with a different
                # kind: recording its update would write, say, a float
                # into a histogram series dict. Warn once and drop.
                self.kind_conflicts[name] = (kind_, kind)
                self._warn_kind_conflict(name, kind_, kind)
                return
            series[tags_key] = update(series.get(tags_key))
            self._dirty = True
        self.ensure_flusher()

    def _flush_loop(self):
        while True:
            time.sleep(FLUSH_INTERVAL_S)
            try:
                self.flush()
            except Exception:
                pass

    def flush(self):
        from ..core import runtime_context

        rt = runtime_context.current_runtime_or_none()
        if rt is None:
            return
        with self.lock:
            if not self._dirty:
                return
            self._dirty = False
            snapshot = {
                name: (kind, dict(series),
                       self.meta.get(name, ("", ""))[1])
                for name, (kind, series) in self.metrics.items()
            }
        rt.kv_put(
            f"{KV_PREFIX}{os.getpid()}",
            cloudpickle.dumps(snapshot),
        )


_registry = _Registry()


class _Metric:
    KIND = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        _registry.declare(name, self.KIND, description)

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return tuple(sorted(merged.items()))


class _BoundCounter:
    """Pre-resolved (name, tags-key) counter handle — see
    ``_Metric.with_tags``."""

    __slots__ = ("_name", "_key")

    def __init__(self, name: str, key: tuple):
        self._name = name
        self._key = key

    def inc(self, value: float = 1.0):
        _registry.record(
            self._name, "counter", self._key,
            lambda cur: (cur or 0.0) + value,
        )


class _BoundGauge:
    __slots__ = ("_name", "_key")

    def __init__(self, name: str, key: tuple):
        self._name = name
        self._key = key

    def set(self, value: float):
        _registry.record(self._name, "gauge", self._key, lambda cur: value)


class _BoundHistogram:
    __slots__ = ("_name", "_key", "_bounds")

    def __init__(self, name: str, key: tuple, bounds: List[float]):
        self._name = name
        self._key = key
        self._bounds = bounds

    def observe(self, value: float, exemplar: Optional[str] = None):
        Histogram._observe(self._name, self._bounds, self._key, value,
                           exemplar)


class Counter(_Metric):
    KIND = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        _registry.record(
            self._name, self.KIND, self._key(tags),
            lambda cur: (cur or 0.0) + value,
        )

    def with_tags(self, **tags) -> _BoundCounter:
        """Resolve the tag set ONCE and return a slim recorder: hot
        paths (per-token decode taps, per-stripe transfer accounting)
        skip the dict merge + sort every ``inc`` otherwise pays."""
        return _BoundCounter(self._name, self._key(tags))


class Gauge(_Metric):
    KIND = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        _registry.record(
            self._name, self.KIND, self._key(tags), lambda cur: value
        )

    def with_tags(self, **tags) -> _BoundGauge:
        """Pre-resolved handle; see ``Counter.with_tags``."""
        return _BoundGauge(self._name, self._key(tags))


class Histogram(_Metric):
    KIND = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self._boundaries = sorted(boundaries or
                                  [0.01, 0.1, 1.0, 10.0, 100.0])

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None,
                exemplar: Optional[str] = None):
        """``exemplar`` is a trace id attached to the bucket this value
        lands in (OpenMetrics exemplar: latest observation wins) — the
        one-hop link from a latency bucket to a recorded waterfall."""
        self._observe(self._name, self._boundaries, self._key(tags),
                      value, exemplar)

    def with_tags(self, **tags) -> _BoundHistogram:
        """Pre-resolved handle; see ``Counter.with_tags``."""
        return _BoundHistogram(self._name, self._key(tags),
                               self._boundaries)

    @staticmethod
    def _observe(name: str, bounds: List[float], key: tuple, value: float,
                 exemplar: Optional[str] = None):
        ex_ts = time.time() if exemplar else 0.0

        def update(cur):
            cur = cur or {"count": 0, "sum": 0.0, "bounds": list(bounds),
                          "buckets": [0] * (len(bounds) + 1)}
            le: Any = "+Inf"
            for i, b in enumerate(bounds):
                if value <= b:
                    cur["buckets"][i] += 1
                    le = b
                    break
            else:
                cur["buckets"][-1] += 1
            cur["count"] += 1
            cur["sum"] += value
            if exemplar:
                cur.setdefault("exemplars", {})[le] = {
                    "trace_id": exemplar, "value": value, "ts": ex_ts,
                }
            return cur

        _registry.record(name, "histogram", key, update)


def declared_metrics() -> Dict[str, Tuple[str, str]]:
    """Every metric declared in this process: name -> (kind, description).
    Data source for tools/check_metric_names.py."""
    with _registry.lock:
        return dict(_registry.meta)


def declaration_conflicts() -> Dict[str, Tuple[str, str]]:
    """Names registered under two different kinds: name -> (old, new)."""
    with _registry.lock:
        return dict(_registry.kind_conflicts)


def _merge_histogram(cur: Dict, value: Dict) -> Dict:
    """Merge two histogram series points. Identical boundaries sum
    bucket-wise; DIFFERENT boundaries merge on the union of bounds —
    each source bucket (b_{i-1}, b_i] lands in the union bucket whose
    upper edge is exactly b_i, so cumulative counts stay exact at every
    original boundary. (The old zip() truncated the longer bucket list
    silently, dropping observations.) Exemplars are keyed by their `le`
    bound, so they merge independently of rebucketing — the newest
    observation per bound wins, matching OpenMetrics semantics."""
    if cur.get("bounds", []) == value.get("bounds", []):
        return {
            "count": cur["count"] + value["count"],
            "sum": cur["sum"] + value["sum"],
            "bounds": list(cur.get("bounds", [])),
            "buckets": [
                a + b for a, b in zip(cur["buckets"], value["buckets"])
            ],
            **_merged_exemplars(cur, value),
        }
    bounds = sorted(set(cur.get("bounds", [])) | set(value.get("bounds", [])))
    index = {b: i for i, b in enumerate(bounds)}

    def rebucket(src: Dict) -> List[float]:
        out = [0] * (len(bounds) + 1)
        src_bounds = src.get("bounds", [])
        for i, c in enumerate(src["buckets"]):
            if i < len(src_bounds):
                out[index[src_bounds[i]]] += c
            else:
                out[-1] += c  # overflow bucket maps to union overflow
        return out

    return {
        "count": cur["count"] + value["count"],
        "sum": cur["sum"] + value["sum"],
        "bounds": bounds,
        "buckets": [a + b for a, b in zip(rebucket(cur), rebucket(value))],
        **_merged_exemplars(cur, value),
    }


def _merged_exemplars(cur: Dict, value: Dict) -> Dict:
    """Union of two histogram points' exemplar maps (newest ts wins per
    `le` key); {} when neither side carries any — the merged point then
    has no "exemplars" key at all, like an unobserved series."""
    a = cur.get("exemplars") or {}
    b = value.get("exemplars") or {}
    if not a and not b:
        return {}
    merged = dict(a)
    for le, ex in b.items():
        old = merged.get(le)
        if old is None or ex.get("ts", 0.0) >= old.get("ts", 0.0):
            merged[le] = ex
    return {"exemplars": merged}


def get_metrics_report() -> Dict[str, Dict]:
    """Aggregate every process's flushed metrics (ref analogue: scraping
    the metrics agents). Counters/histograms sum across processes; gauges
    keep the latest non-None value per tag set."""
    from ..core import runtime_context

    rt = runtime_context.current_runtime()
    _registry.flush()
    out: Dict[str, Dict] = {}
    for key in rt.kv_keys(KV_PREFIX):
        blob = rt.kv_get(key)
        if blob is None:
            continue
        snapshot = cloudpickle.loads(blob)
        for name, item in snapshot.items():
            kind, series = item[0], item[1]
            help_ = item[2] if len(item) > 2 else ""
            entry = out.setdefault(
                name, {"type": kind, "series": {}, "help": ""}
            )
            if help_ and not entry.get("help"):
                entry["help"] = help_
            for tags_key, value in series.items():
                cur = entry["series"].get(tags_key)
                if kind == "counter":
                    entry["series"][tags_key] = (cur or 0.0) + value
                elif kind == "gauge":
                    entry["series"][tags_key] = value
                elif cur is None:  # histogram, first sighting
                    entry["series"][tags_key] = dict(value)
                else:
                    entry["series"][tags_key] = _merge_histogram(cur, value)
    return out
