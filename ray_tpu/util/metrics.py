"""User-defined metrics.

Ref analogue: python/ray/util/metrics.py (Counter/Gauge/Histogram) over
the metrics agent pipeline (src/ray/stats/) — here each process batches
its metric values and flushes them to the cluster KV under
``__metrics__/<process>``; ``get_metrics_report()`` aggregates across
every process for dashboards/tests (the Prometheus exposition layer can
read the same table).
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import cloudpickle

FLUSH_INTERVAL_S = 0.5
KV_PREFIX = "__metrics__/"


class _Registry:
    def __init__(self):
        self.lock = threading.Lock()
        # name -> ("counter"|"gauge"|"histogram", {tags_key: value})
        self.metrics: Dict[str, Tuple[str, Dict]] = {}
        self._flusher: Optional[threading.Thread] = None
        self._dirty = False

    def ensure_flusher(self):
        if self._flusher is None:
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True
            )
            self._flusher.start()
            atexit.register(self.flush)

    def record(self, name: str, kind: str, tags_key: tuple, update):
        with self.lock:
            kind_, series = self.metrics.setdefault(name, (kind, {}))
            series[tags_key] = update(series.get(tags_key))
            self._dirty = True
        self.ensure_flusher()

    def _flush_loop(self):
        while True:
            time.sleep(FLUSH_INTERVAL_S)
            try:
                self.flush()
            except Exception:
                pass

    def flush(self):
        from ..core import runtime_context

        rt = runtime_context.current_runtime_or_none()
        if rt is None:
            return
        with self.lock:
            if not self._dirty:
                return
            self._dirty = False
            snapshot = {
                name: (kind, dict(series))
                for name, (kind, series) in self.metrics.items()
            }
        rt.kv_put(
            f"{KV_PREFIX}{os.getpid()}",
            cloudpickle.dumps(snapshot),
        )


_registry = _Registry()


class _Metric:
    KIND = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return tuple(sorted(merged.items()))


class Counter(_Metric):
    KIND = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        _registry.record(
            self._name, self.KIND, self._key(tags),
            lambda cur: (cur or 0.0) + value,
        )


class Gauge(_Metric):
    KIND = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        _registry.record(
            self._name, self.KIND, self._key(tags), lambda cur: value
        )


class Histogram(_Metric):
    KIND = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self._boundaries = sorted(boundaries or
                                  [0.01, 0.1, 1.0, 10.0, 100.0])

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        bounds = self._boundaries

        def update(cur):
            cur = cur or {"count": 0, "sum": 0.0, "bounds": list(bounds),
                          "buckets": [0] * (len(bounds) + 1)}
            cur["count"] += 1
            cur["sum"] += value
            for i, b in enumerate(bounds):
                if value <= b:
                    cur["buckets"][i] += 1
                    break
            else:
                cur["buckets"][-1] += 1
            return cur

        _registry.record(self._name, self.KIND, self._key(tags), update)


def get_metrics_report() -> Dict[str, Dict]:
    """Aggregate every process's flushed metrics (ref analogue: scraping
    the metrics agents). Counters/histograms sum across processes; gauges
    keep the latest non-None value per tag set."""
    from ..core import runtime_context

    rt = runtime_context.current_runtime()
    _registry.flush()
    out: Dict[str, Dict] = {}
    for key in rt.kv_keys(KV_PREFIX):
        blob = rt.kv_get(key)
        if blob is None:
            continue
        snapshot = cloudpickle.loads(blob)
        for name, (kind, series) in snapshot.items():
            entry = out.setdefault(name, {"type": kind, "series": {}})
            for tags_key, value in series.items():
                cur = entry["series"].get(tags_key)
                if kind == "counter":
                    entry["series"][tags_key] = (cur or 0.0) + value
                elif kind == "gauge":
                    entry["series"][tags_key] = value
                else:  # histogram
                    if cur is None:
                        entry["series"][tags_key] = dict(value)
                    else:
                        cur["count"] += value["count"]
                        cur["sum"] += value["sum"]
                        cur["buckets"] = [
                            a + b for a, b in zip(cur["buckets"],
                                                  value["buckets"])
                        ]
    return out
