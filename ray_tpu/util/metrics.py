"""User-defined metrics.

Ref analogue: python/ray/util/metrics.py (Counter/Gauge/Histogram) over
the metrics agent pipeline (src/ray/stats/) — here each process batches
its metric values and flushes them to the cluster KV under
``__metrics__/<process>``; ``get_metrics_report()`` aggregates across
every process for dashboards/tests (the Prometheus exposition layer can
read the same table).
"""

from __future__ import annotations

import atexit
import os
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

FLUSH_INTERVAL_S = 0.5
KV_PREFIX = "__metrics__/"
# Every PROC_SAMPLE_INTERVAL_S the flusher re-records this process's
# cpu/rss gauges, which (a) feeds the per-node rows of `rtpu top` and
# (b) acts as a liveness refresh: the v2 snapshot's `ts` stays fresh
# while the process lives, so the head-side GC (core/gcs.py) can reap
# blobs whose writer died without aggregating ghosts forever.
PROC_SAMPLE_INTERVAL_S = 5.0


class _Registry:
    def __init__(self):
        self.lock = threading.Lock()
        # name -> ("counter"|"gauge"|"histogram", {tags_key: value})
        self.metrics: Dict[str, Tuple[str, Dict]] = {}
        # name -> (kind, description), recorded at metric construction —
        # feeds `# HELP` lines and tools/check_metric_names.py.
        self.meta: Dict[str, Tuple[str, str]] = {}
        # Names re-declared or re-recorded under a conflicting kind.
        self.kind_conflicts: Dict[str, Tuple[str, str]] = {}
        self._warned_kinds: set = set()
        self._flusher: Optional[threading.Thread] = None
        self._dirty = False

    def ensure_flusher(self):
        if self._flusher is None:
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True
            )
            self._flusher.start()
            atexit.register(self.flush)

    def declare(self, name: str, kind: str, description: str):
        with self.lock:
            old = self.meta.get(name)
            if old is not None and old[0] != kind:
                self.kind_conflicts[name] = (old[0], kind)
                self._warn_kind_conflict(name, old[0], kind)
                return
            if old is None or (description and not old[1]):
                self.meta[name] = (kind, description)

    def _warn_kind_conflict(self, name: str, old: str, new: str):
        # Caller holds self.lock.
        if name in self._warned_kinds:
            return
        self._warned_kinds.add(name)
        warnings.warn(
            f"metric {name!r} already registered as a {old}; ignoring "
            f"records under conflicting kind {new!r} (the series would "
            f"be corrupted)",
            UserWarning,
            stacklevel=3,
        )

    def record(self, name: str, kind: str, tags_key: tuple, update):
        with self.lock:
            kind_, series = self.metrics.setdefault(name, (kind, {}))
            if kind_ != kind:
                # A second metric object reused the name with a different
                # kind: recording its update would write, say, a float
                # into a histogram series dict. Warn once and drop.
                self.kind_conflicts[name] = (kind_, kind)
                self._warn_kind_conflict(name, kind_, kind)
                return
            series[tags_key] = update(series.get(tags_key))
            self._dirty = True
        self.ensure_flusher()

    def _flush_loop(self):
        last_proc = 0.0
        while True:
            time.sleep(FLUSH_INTERVAL_S)
            try:
                now = time.monotonic()
                if now - last_proc >= PROC_SAMPLE_INTERVAL_S:
                    last_proc = now
                    _sample_process_stats()
                self.flush()
            except Exception:
                pass

    def flush(self):
        from ..core import runtime_context

        rt = runtime_context.current_runtime_or_none()
        if rt is None:
            return
        with self.lock:
            if not self._dirty:
                return
            self._dirty = False
            snapshot = {
                name: (kind, dict(series),
                       self.meta.get(name, ("", ""))[1])
                for name, (kind, series) in self.metrics.items()
            }
        # v2 envelope: the writer's node scopes the key (one node's
        # blobs GC together when it dies) and `ts` dates the snapshot
        # (a stale ts marks a dead pid's blob for head-side GC).
        node = getattr(rt, "node_id", None)
        node_hex = node.hex() if hasattr(node, "hex") else ""
        suffix = f"{node_hex}/{os.getpid()}" if node_hex else str(os.getpid())
        rt.kv_put(
            f"{KV_PREFIX}{suffix}",
            cloudpickle.dumps({
                "v": 2, "ts": time.time(), "pid": os.getpid(),
                "node": node_hex, "metrics": snapshot,
            }),
        )


_registry = _Registry()


class _Metric:
    KIND = ""

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        _registry.declare(name, self.KIND, description)

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return tuple(sorted(merged.items()))


class _BoundCounter:
    """Pre-resolved (name, tags-key) counter handle — see
    ``_Metric.with_tags``."""

    __slots__ = ("_name", "_key")

    def __init__(self, name: str, key: tuple):
        self._name = name
        self._key = key

    def inc(self, value: float = 1.0):
        _registry.record(
            self._name, "counter", self._key,
            lambda cur: (cur or 0.0) + value,
        )


class _BoundGauge:
    __slots__ = ("_name", "_key")

    def __init__(self, name: str, key: tuple):
        self._name = name
        self._key = key

    def set(self, value: float):
        _registry.record(self._name, "gauge", self._key, lambda cur: value)


class _BoundHistogram:
    __slots__ = ("_name", "_key", "_bounds")

    def __init__(self, name: str, key: tuple, bounds: List[float]):
        self._name = name
        self._key = key
        self._bounds = bounds

    def observe(self, value: float, exemplar: Optional[str] = None):
        Histogram._observe(self._name, self._bounds, self._key, value,
                           exemplar)


class Counter(_Metric):
    KIND = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None):
        _registry.record(
            self._name, self.KIND, self._key(tags),
            lambda cur: (cur or 0.0) + value,
        )

    def with_tags(self, **tags) -> _BoundCounter:
        """Resolve the tag set ONCE and return a slim recorder: hot
        paths (per-token decode taps, per-stripe transfer accounting)
        skip the dict merge + sort every ``inc`` otherwise pays."""
        return _BoundCounter(self._name, self._key(tags))


class Gauge(_Metric):
    KIND = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        _registry.record(
            self._name, self.KIND, self._key(tags), lambda cur: value
        )

    def with_tags(self, **tags) -> _BoundGauge:
        """Pre-resolved handle; see ``Counter.with_tags``."""
        return _BoundGauge(self._name, self._key(tags))


class Histogram(_Metric):
    KIND = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self._boundaries = sorted(boundaries or
                                  [0.01, 0.1, 1.0, 10.0, 100.0])

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None,
                exemplar: Optional[str] = None):
        """``exemplar`` is a trace id attached to the bucket this value
        lands in (OpenMetrics exemplar: latest observation wins) — the
        one-hop link from a latency bucket to a recorded waterfall."""
        self._observe(self._name, self._boundaries, self._key(tags),
                      value, exemplar)

    def with_tags(self, **tags) -> _BoundHistogram:
        """Pre-resolved handle; see ``Counter.with_tags``."""
        return _BoundHistogram(self._name, self._key(tags),
                               self._boundaries)

    @staticmethod
    def _observe(name: str, bounds: List[float], key: tuple, value: float,
                 exemplar: Optional[str] = None):
        ex_ts = time.time() if exemplar else 0.0

        def update(cur):
            cur = cur or {"count": 0, "sum": 0.0, "bounds": list(bounds),
                          "buckets": [0] * (len(bounds) + 1)}
            le: Any = "+Inf"
            for i, b in enumerate(bounds):
                if value <= b:
                    cur["buckets"][i] += 1
                    le = b
                    break
            else:
                cur["buckets"][-1] += 1
            cur["count"] += 1
            cur["sum"] += value
            if exemplar:
                cur.setdefault("exemplars", {})[le] = {
                    "trace_id": exemplar, "value": value, "ts": ex_ts,
                }
            return cur

        _registry.record(name, "histogram", key, update)


# Per-process resource series, recorded by the flusher's periodic
# liveness sample (`_sample_process_stats`). Identity tags (node, pid)
# keep writers distinct; sum over pid for a node's total RSS, rate the
# cpu counter for CPU%.
PROCESS_CPU = Counter(
    "ray_tpu_process_cpu_seconds_total",
    "Cumulative CPU seconds (user+sys) of one ray_tpu process.",
    tag_keys=("node", "pid"),
)
PROCESS_RSS = Gauge(
    "ray_tpu_process_rss_bytes",
    "Resident set size of one ray_tpu process.",
    tag_keys=("node", "pid"),
)
_last_cpu_seconds = 0.0


def declared_metrics() -> Dict[str, Tuple[str, str]]:
    """Every metric declared in this process: name -> (kind, description).
    Data source for tools/check_metric_names.py."""
    with _registry.lock:
        return dict(_registry.meta)


def declaration_conflicts() -> Dict[str, Tuple[str, str]]:
    """Names registered under two different kinds: name -> (old, new)."""
    with _registry.lock:
        return dict(_registry.kind_conflicts)


def _merge_histogram(cur: Dict, value: Dict) -> Dict:
    """Merge two histogram series points. Identical boundaries sum
    bucket-wise; DIFFERENT boundaries merge on the union of bounds —
    each source bucket (b_{i-1}, b_i] lands in the union bucket whose
    upper edge is exactly b_i, so cumulative counts stay exact at every
    original boundary. (The old zip() truncated the longer bucket list
    silently, dropping observations.) Exemplars are keyed by their `le`
    bound, so they merge independently of rebucketing — the newest
    observation per bound wins, matching OpenMetrics semantics."""
    if cur.get("bounds", []) == value.get("bounds", []):
        return {
            "count": cur["count"] + value["count"],
            "sum": cur["sum"] + value["sum"],
            "bounds": list(cur.get("bounds", [])),
            "buckets": [
                a + b for a, b in zip(cur["buckets"], value["buckets"])
            ],
            **_merged_exemplars(cur, value),
        }
    bounds = sorted(set(cur.get("bounds", [])) | set(value.get("bounds", [])))
    index = {b: i for i, b in enumerate(bounds)}

    def rebucket(src: Dict) -> List[float]:
        out = [0] * (len(bounds) + 1)
        src_bounds = src.get("bounds", [])
        for i, c in enumerate(src["buckets"]):
            if i < len(src_bounds):
                out[index[src_bounds[i]]] += c
            else:
                out[-1] += c  # overflow bucket maps to union overflow
        return out

    return {
        "count": cur["count"] + value["count"],
        "sum": cur["sum"] + value["sum"],
        "bounds": bounds,
        "buckets": [a + b for a, b in zip(rebucket(cur), rebucket(value))],
        **_merged_exemplars(cur, value),
    }


def _merged_exemplars(cur: Dict, value: Dict) -> Dict:
    """Union of two histogram points' exemplar maps (newest ts wins per
    `le` key); {} when neither side carries any — the merged point then
    has no "exemplars" key at all, like an unobserved series."""
    a = cur.get("exemplars") or {}
    b = value.get("exemplars") or {}
    if not a and not b:
        return {}
    merged = dict(a)
    for le, ex in b.items():
        old = merged.get(le)
        if old is None or ex.get("ts", 0.0) >= old.get("ts", 0.0):
            merged[le] = ex
    return {"exemplars": merged}


def _sample_process_stats() -> None:
    """Record this process's cpu/rss (from /proc, psutil-free) into the
    standard pipeline — the per-node resource rows of `rtpu top` and
    the head TSDB derive CPU use via counter->rate (no-op off Linux)."""
    from ..core import runtime_context

    rt = runtime_context.current_runtime_or_none()
    node = getattr(rt, "node_id", None) if rt is not None else None
    tags = {"node": node.hex() if hasattr(node, "hex") else "",
            "pid": str(os.getpid())}
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        PROCESS_RSS.set(pages * os.sysconf("SC_PAGE_SIZE"), tags=tags)
        with open("/proc/self/stat") as f:
            parts = f.read().split()
        tick = os.sysconf("SC_CLK_TCK")
        cpu = (int(parts[13]) + int(parts[14])) / tick
    except Exception:
        return
    global _last_cpu_seconds
    if cpu > _last_cpu_seconds:
        PROCESS_CPU.inc(cpu - _last_cpu_seconds, tags=tags)
        _last_cpu_seconds = cpu


def decode_snapshot(blob: bytes) -> Tuple[Dict, float]:
    """One flushed KV blob -> (metrics dict, snapshot ts). Accepts both
    the v2 envelope and the pre-envelope bare dict (ts 0.0: age
    unknown, exempt from staleness GC)."""
    snapshot = cloudpickle.loads(blob)
    if isinstance(snapshot, dict) and snapshot.get("v") == 2:
        return snapshot.get("metrics") or {}, float(snapshot.get("ts", 0.0))
    return snapshot, 0.0


def merge_snapshot(out: Dict[str, Dict], snapshot: Dict) -> None:
    """Fold one process snapshot into a report accumulator: counters and
    histograms sum across processes; gauges keep the latest write per
    tag set (identity tags keep writers distinct — see _telemetry)."""
    for name, item in snapshot.items():
        kind, series = item[0], item[1]
        help_ = item[2] if len(item) > 2 else ""
        entry = out.setdefault(
            name, {"type": kind, "series": {}, "help": ""}
        )
        if help_ and not entry.get("help"):
            entry["help"] = help_
        for tags_key, value in series.items():
            cur = entry["series"].get(tags_key)
            if kind == "counter":
                entry["series"][tags_key] = (cur or 0.0) + value
            elif kind == "gauge":
                entry["series"][tags_key] = value
            elif cur is None:  # histogram, first sighting
                entry["series"][tags_key] = dict(value)
            else:
                entry["series"][tags_key] = _merge_histogram(cur, value)


def aggregate_blobs(blobs) -> Dict[str, Dict]:
    """Aggregate an iterable of flushed KV blobs into one report dict.
    Shared by the driver-side report below and the head GCS's TSDB
    sampler (core/gcs.py), which reads its KV table directly. Corrupt
    blobs are skipped — one wedged writer must not blind the report."""
    out: Dict[str, Dict] = {}
    for blob in blobs:
        if not blob:
            continue
        try:
            snapshot, _ts = decode_snapshot(blob)
        except Exception:
            continue
        merge_snapshot(out, snapshot)
    return out


def local_snapshot() -> Dict[str, Tuple]:
    """This process's registry in flushed-snapshot form, without going
    through (or requiring) a runtime. The head GCS uses it to publish
    its own ray_tpu_slo_* gauges when it runs standalone."""
    with _registry.lock:
        return {
            name: (kind, dict(series),
                   _registry.meta.get(name, ("", ""))[1])
            for name, (kind, series) in _registry.metrics.items()
        }


def get_metrics_report() -> Dict[str, Dict]:
    """Aggregate every process's flushed metrics (ref analogue: scraping
    the metrics agents). Counters/histograms sum across processes; gauges
    keep the latest non-None value per tag set."""
    from ..core import runtime_context

    rt = runtime_context.current_runtime()
    _registry.flush()
    return aggregate_blobs(
        rt.kv_get(key) for key in rt.kv_keys(KV_PREFIX)
    )
