"""Structured cluster events + failure-history plane.

Ref analogue: the reference's export-event / RAY_LOG channel (ref:
src/ray/gcs/gcs_server pubsub RAY_LOG + python/ray/util/state
list_cluster_events): every process records typed lifecycle events
(node register/death, worker crash, task failure, actor restart, OOM
kills, autoscaler and serve decisions) into a bounded per-process ring
buffer; a flusher thread publishes batches through the GCS pubsub
(channel ``cluster_events``) to the head-side aggregator
(:class:`EventStore`), which keeps a bounded severity-indexed store and
an optional JSONL export sink for external collectors.

Emit sites call :func:`emit` with a severity and source from the
declared enums below — ``tools/check_metric_names.py`` (the
observability lint, ``make check-obs``) statically validates both at
every call site.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..core.pubsub import CLUSTER_EVENTS  # noqa: F401 — re-exported

# --------------------------------------------------------------- enums

# Severities (ref: export_event.proto severity levels).
DEBUG = "DEBUG"
INFO = "INFO"
WARNING = "WARNING"
ERROR = "ERROR"
FATAL = "FATAL"
SEVERITIES = (DEBUG, INFO, WARNING, ERROR, FATAL)

# Event sources (ref analogue: SourceType in export_event.proto — which
# subsystem recorded the event).
GCS = "GCS"
RAYLET = "RAYLET"
WORKER = "WORKER"
TASK = "TASK"
ACTOR = "ACTOR"
OBJECT_STORE = "OBJECT_STORE"
AUTOSCALER = "AUTOSCALER"
SERVE = "SERVE"
JOB = "JOB"
# Fault-injection firings (util/faults.py — the chaos plane).
CHAOS = "CHAOS"
# Train gang lifecycle (train/trainer.py supervisor: rank death/hang,
# gang aborts, restart-from-checkpoint, cooperative preemption).
TRAIN = "TRAIN"
# Cluster membership lifecycle (core/fencing.py + the GCS epoch plane):
# FENCE decisions — node fenced at an epoch, zombie self-termination,
# fresh-incarnation rejoin — surfaced via `rtpu events --source NODE`.
NODE = "NODE"
# SLO plane (util/slo.py evaluated in the head GCS): error-budget
# burn-rate alert transitions — WARNING on crossing, INFO on clearing,
# deduped while the condition persists.
SLO = "SLO"
# Process self-health (util/loop_monitor.py watchdogs): event-loop
# stalls — WARNING when a loop's watchdog tick is overdue past
# loop_stall_warn_s, deduped per stall episode, payload carries the
# stalled thread's stack and the running asyncio task name.
SYSTEM = "SYSTEM"
SOURCES = (GCS, RAYLET, WORKER, TASK, ACTOR, OBJECT_STORE, AUTOSCALER,
           SERVE, JOB, CHAOS, TRAIN, NODE, SLO, SYSTEM)

FLUSH_INTERVAL_S = 0.25


def make_event(severity: str, source: str, message: str, *,
               node_id: Optional[str] = None,
               job_id: Optional[str] = None,
               task_id: Optional[str] = None,
               actor_id: Optional[str] = None,
               trace_id: Optional[str] = None,
               span_id: Optional[str] = None,
               custom_fields: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
    """Build one typed event record. Severity/source must come from the
    declared enums — unknown values raise so emit sites stay lintable.
    ``trace_id``/``span_id`` link the event into a request waterfall
    (emit() fills them from the thread's active span automatically)."""
    if severity not in SEVERITIES:
        raise ValueError(
            f"unknown event severity {severity!r} (one of {SEVERITIES})"
        )
    if source not in SOURCES:
        raise ValueError(
            f"unknown event source {source!r} (one of {SOURCES})"
        )
    return {
        "event_id": uuid.uuid4().hex[:16],
        "ts": time.time(),
        "severity": severity,
        "source": source,
        "message": message,
        "node_id": node_id,
        "job_id": job_id,
        "task_id": task_id,
        "actor_id": actor_id,
        "trace_id": trace_id,
        "span_id": span_id,
        "pid": os.getpid(),
        "custom_fields": dict(custom_fields or {}),
    }


# ---------------------------------------------------- per-process buffer


class EventBuffer:
    """Bounded ring of not-yet-published events. A producer that outruns
    the flusher loses OLDEST events first and the drop is counted, never
    silent (same contract as the pubsub subscriber queues)."""

    def __init__(self, maxlen: int = 1000):
        self._lock = threading.Lock()
        self._pending: deque = deque(maxlen=maxlen)
        self._dropped = 0

    def append(self, event: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._pending) == self._pending.maxlen:
                self._dropped += 1
            self._pending.append(event)

    def drain(self) -> Tuple[List[Dict[str, Any]], int]:
        """Pop everything buffered; returns (events, dropped-since-last)."""
        with self._lock:
            events = list(self._pending)
            self._pending.clear()
            dropped, self._dropped = self._dropped, 0
        return events, dropped

    def requeue(self, events: List[Dict[str, Any]]) -> None:
        """Put a drained-but-unpublished batch back at the FRONT (the
        publish failed): order is preserved against newer emits, and any
        overflow drops oldest-first with the drop counted."""
        with self._lock:
            merged = list(events) + list(self._pending)
            overflow = max(0, len(merged) - (self._pending.maxlen or 0))
            if overflow:
                self._dropped += overflow
                merged = merged[overflow:]
            self._pending = deque(merged, maxlen=self._pending.maxlen)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)


class _Emitter:
    """Module singleton: buffer + lazy flusher thread + transport."""

    def __init__(self):
        self.lock = threading.Lock()
        # Serializes flush(): the periodic flusher and explicit flush()
        # callers (worker failure paths) must not interleave drains, or
        # batches publish out of order.
        self._flush_lock = threading.Lock()
        self._buffer: Optional[EventBuffer] = None
        self._flusher: Optional[threading.Thread] = None
        # Installed by a node manager living in this process; publishes a
        # batch on its own loop (node-manager processes have no driver
        # runtime to route through).
        self._publish_hook = None

    def buffer(self) -> EventBuffer:
        with self.lock:
            if self._buffer is None:
                from ..core.config import get_config

                size = getattr(get_config(), "event_buffer_size", 1000)
                self._buffer = EventBuffer(maxlen=size)
            return self._buffer

    def ensure_flusher(self):
        with self.lock:
            if self._flusher is None:
                self._flusher = threading.Thread(
                    target=self._flush_loop, name="ray_tpu-event-flusher",
                    daemon=True,
                )
                self._flusher.start()

    def _flush_loop(self):
        while True:
            time.sleep(FLUSH_INTERVAL_S)
            try:
                self.flush()
            except Exception:
                pass

    def flush(self):
        with self._flush_lock:
            self._flush_locked()

    def _flush_locked(self):
        from ..core import runtime_context

        if self._buffer is None:
            return
        hook = self._publish_hook
        rt = runtime_context.current_runtime_or_none()
        if hook is None and rt is None:
            # No transport yet (runtime/hook not installed): keep events
            # in the ring — it bounds retention and counts drops — so
            # they publish once a connection exists.
            return
        batch, dropped = self._buffer.drain()
        if dropped:
            batch.append(make_event(
                WARNING, WORKER,
                f"event buffer overflow: {dropped} event(s) dropped in "
                f"pid {os.getpid()}",
                custom_fields={"dropped": dropped},
            ))
        if not batch:
            return
        if hook is not None:
            try:
                hook(batch)
                return
            except Exception:
                pass  # hook's node manager shut down; try the runtime
        if rt is None:
            self._buffer.requeue(batch)
            return
        try:
            rt.pubsub_op({
                "op": "publish", "channel": CLUSTER_EVENTS, "data": batch,
            })
        except Exception:
            self._buffer.requeue(batch)


_emitter = _Emitter()

# Final flush at interpreter exit (mirrors timeline.py's atexit flush):
# the flusher thread is a daemon, so without this the ring's last
# ``FLUSH_INTERVAL_S`` of events — exactly the crash-adjacent
# CHAOS/ERROR tail a postmortem needs — died with the process.
# Registered at import (not first emit) so it runs LAST in atexit's
# LIFO order, i.e. after user atexit hooks that may still emit; the
# shutdown paths (node manager, worker main) additionally flush
# explicitly while their transport is still up.
atexit.register(lambda: _emitter.flush())


def emit(severity: str, source: str, message: str, *,
         node_id: Optional[str] = None,
         job_id: Optional[str] = None,
         task_id: Optional[str] = None,
         actor_id: Optional[str] = None,
         custom_fields: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Record one cluster event; returns the event dict. Buffered
    locally and published to the head aggregator within
    ``FLUSH_INTERVAL_S`` (best effort: a process with no cluster
    connection keeps events in its ring only)."""
    if node_id is None:
        from ..core import runtime_context

        rt = runtime_context.current_runtime_or_none()
        if rt is not None and getattr(rt, "node_id", None) is not None:
            node_id = rt.node_id.hex()
    # Events emitted inside an active span carry its trace context, so
    # `rtpu events` rows correlate 1:1 with recorded request waterfalls
    # (CHAOS firings, TRAIN gang aborts, SERVE ejections...).
    trace_id = span_id = None
    try:
        from ..core.timeline import current_span

        ctx = current_span()
        if ctx is not None:
            trace_id, span_id = ctx[0], (ctx[1] or None)
    except Exception:
        pass
    event = make_event(
        severity, source, message, node_id=node_id, job_id=job_id,
        task_id=task_id, actor_id=actor_id, trace_id=trace_id,
        span_id=span_id, custom_fields=custom_fields,
    )
    _emitter.buffer().append(event)
    _emitter.ensure_flusher()
    return event


def flush() -> None:
    """Publish anything buffered now (tests / shutdown paths)."""
    _emitter.flush()


def set_publish_hook(hook) -> None:
    """Install the process's publish transport (called by the node
    manager: batches go out on its loop via the GCS handle)."""
    _emitter._publish_hook = hook


def clear_publish_hook(hook) -> None:
    """Remove ``hook`` if it is still the installed one (a second node
    manager in the same process may have replaced it)."""
    if _emitter._publish_hook == hook:  # == : bound methods compare by
        _emitter._publish_hook = None   # (instance, func), `is` would not


# ------------------------------------------------------ head aggregator


class EventStore:
    """Head-side bounded, severity-indexed event store (ref analogue:
    the GCS-side buffer behind `ray list cluster-events`). Optionally
    mirrors every event to a JSONL sink for external collectors."""

    def __init__(self, maxlen: int = 10_000, jsonl_path: str = ""):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=maxlen)
        self._by_severity: Dict[str, deque] = {
            sev: deque(maxlen=maxlen) for sev in SEVERITIES
        }
        self._seq = 0
        self._total = 0
        self._dropped = 0
        self._jsonl_path = jsonl_path
        self._jsonl_file = None

    def add(self, event: Dict[str, Any]) -> None:
        self.add_batch([event])

    def add_batch(self, events: List[Dict[str, Any]]) -> None:
        """Ingest a batch under one lock acquisition with a single JSONL
        flush at the end (per-event flushes would stall the GCS loop the
        aggregator runs on during event bursts)."""
        with self._lock:
            wrote = False
            for event in events:
                if not isinstance(event, dict):
                    continue
                self._seq += 1
                self._total += 1
                event = dict(event)
                event["seq"] = self._seq
                self._events.append(event)
                index = self._by_severity.get(event.get("severity"))
                if index is not None:
                    index.append(event)
                if self._jsonl_path:
                    wrote |= self._write_jsonl(event)
            if wrote and self._jsonl_file is not None:
                try:
                    self._jsonl_file.flush()
                except Exception:
                    self._jsonl_path = ""

    def note_dropped(self, n: int) -> None:
        with self._lock:
            self._dropped += n

    def _write_jsonl(self, event: Dict[str, Any]) -> bool:
        # Caller holds the lock; flushing is the caller's (batched) job.
        try:
            if self._jsonl_file is None:
                d = os.path.dirname(self._jsonl_path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._jsonl_file = open(self._jsonl_path, "a")
            self._jsonl_file.write(json.dumps(event, default=str) + "\n")
            return True
        except Exception:
            self._jsonl_path = ""  # sink broke: stop retrying per event
            return False

    def list(self, severity: Optional[str] = None,
             source: Optional[str] = None,
             limit: int = 1000) -> List[Dict[str, Any]]:
        """Events oldest-first, optionally filtered; ``limit`` keeps the
        NEWEST matches (you page backwards through history)."""
        with self._lock:
            if severity is not None:
                rows = list(self._by_severity.get(severity, ()))
            else:
                rows = list(self._events)
        if source is not None:
            rows = [e for e in rows if e.get("source") == source]
        if limit and limit > 0:
            rows = rows[-limit:]
        return rows

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "total": self._total,
                "stored": len(self._events),
                "dropped": self._dropped,
                "by_severity": {
                    sev: len(q) for sev, q in self._by_severity.items()
                },
            }

    def close(self) -> None:
        with self._lock:
            if self._jsonl_file is not None:
                try:
                    self._jsonl_file.close()
                except Exception:
                    pass
                self._jsonl_file = None
