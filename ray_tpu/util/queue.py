"""Distributed FIFO queue.

Ref analogue: python/ray/util/queue.py Queue — an actor-backed queue
usable from any worker or the driver. Blocking put/get poll the actor
(the actor itself never blocks, so one queue serves many producers and
consumers without stalling its event loop).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, List, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self._maxsize = maxsize
        self._items: deque = deque()

    def qsize(self) -> int:
        return len(self._items)

    def put(self, item) -> bool:
        if self._maxsize > 0 and len(self._items) >= self._maxsize:
            return False
        self._items.append(item)
        return True

    def put_batch(self, items: List[Any]) -> int:
        n = 0
        for item in items:
            if not self.put(item):
                break
            n += 1
        return n

    def get(self):
        if not self._items:
            return False, None
        return True, self._items.popleft()

    def get_batch(self, max_items: int):
        out = []
        while self._items and len(out) < max_items:
            out.append(self._items.popleft())
        return out


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict]
                 = None):
        import ray_tpu

        opts = actor_options or {}
        cls = ray_tpu.remote(**opts)(_QueueActor) if opts else \
            ray_tpu.remote(_QueueActor)
        self._actor = cls.remote(maxsize)
        self._maxsize = maxsize

    def qsize(self) -> int:
        import ray_tpu

        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self._maxsize > 0 and self.qsize() >= self._maxsize

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        import ray_tpu

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self._actor.put.remote(item)):
                return
            if not block:
                raise Full
            if deadline is not None and time.monotonic() > deadline:
                raise Full
            time.sleep(0.01)

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        import ray_tpu

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self._actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() > deadline:
                raise Empty
            time.sleep(0.01)

    def get_nowait(self):
        return self.get(block=False)

    def put_batch(self, items: List[Any]) -> None:
        import ray_tpu

        remaining = list(items)
        while remaining:
            n = ray_tpu.get(self._actor.put_batch.remote(remaining))
            remaining = remaining[n:]
            if remaining:
                time.sleep(0.01)

    def get_batch(self, max_items: int) -> List[Any]:
        import ray_tpu

        return ray_tpu.get(self._actor.get_batch.remote(max_items))

    def shutdown(self) -> None:
        import ray_tpu

        try:
            ray_tpu.kill(self._actor)
        except Exception:
            pass
