"""ActorPool.

Ref analogue: python/ray/util/actor_pool.py ActorPool — schedule work
over a fixed set of actors, yielding results in submission order
(``map``) or completion order (``map_unordered``).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional


class ActorPool:
    def __init__(self, actors: List[Any]):
        if not actors:
            raise ValueError("ActorPool needs at least one actor")
        self._idle: List[Any] = list(actors)
        # ref-id -> (actor, submission index)
        self._inflight = {}
        self._index_to_ref = {}
        self._next_submit = 0
        self._next_return = 0

    # ---- submission --------------------------------------------------------

    def has_free(self) -> bool:
        return bool(self._idle)

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) must return an ObjectRef, e.g.
        ``pool.submit(lambda a, v: a.double.remote(v), 1)``."""
        if not self._idle:
            raise RuntimeError("no idle actor; call get_next* first")
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._inflight[ref.id()] = (ref, actor, self._next_submit)
        self._index_to_ref[self._next_submit] = ref
        self._next_submit += 1

    def has_next(self) -> bool:
        return bool(self._inflight)

    # ---- retrieval ---------------------------------------------------------

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in SUBMISSION order."""
        import ray_tpu

        idx = self._next_return
        ref = self._index_to_ref.get(idx)
        if ref is None:
            raise RuntimeError("no pending results")
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next timed out")
        return self._finish(ref.id())

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next result in COMPLETION order."""
        import ray_tpu

        if not self._inflight:
            raise RuntimeError("no pending results")
        refs = [entry[0] for entry in self._inflight.values()]
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        return self._finish(ready[0].id())

    def _finish(self, ref_id) -> Any:
        import ray_tpu

        ref, actor, idx = self._inflight.pop(ref_id)
        self._index_to_ref.pop(idx, None)
        if idx == self._next_return:
            while self._next_return not in self._index_to_ref and \
                    self._next_return < self._next_submit:
                self._next_return += 1
        self._idle.append(actor)
        return ray_tpu.get(ref)

    # ---- bulk maps ---------------------------------------------------------

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        """Results in submission order, streaming as actors free up."""
        values = iter(values)
        exhausted = False
        while True:
            while not exhausted and self.has_free():
                try:
                    self.submit(fn, next(values))
                except StopIteration:
                    exhausted = True
            if not self.has_next():
                if exhausted:
                    return
                continue
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        values = iter(values)
        exhausted = False
        while True:
            while not exhausted and self.has_free():
                try:
                    self.submit(fn, next(values))
                except StopIteration:
                    exhausted = True
            if not self.has_next():
                if exhausted:
                    return
                continue
            yield self.get_next_unordered()
