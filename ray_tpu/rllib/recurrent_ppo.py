"""Recurrent PPO: LSTM policies for partially observable tasks.

Ref analogue: the reference PPO's ``use_lstm`` model option
(rllib/models/ — the LSTM wrapper every on-policy algorithm can turn
on). The rollout policy is an LSTM actor-critic run in numpy with
carried hidden state (reset at episode boundaries); replaying uses
R2D2's stored-state strategy — fragments are chopped into
fixed-length sequences carrying the recurrent state captured at
sequence start, never crossing an episode boundary (short tails are
padded and masked) — and the learner unrolls online with ``lax.scan``
under a masked PPO clipped-surrogate loss, with GAE computed over the
original flat fragment before chopping.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .policy import init_mlp_params
from .r2d2 import _lstm_step_np
from .sample_batch import SampleBatch, compute_gae


class RecurrentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.clip_param: float = 0.2
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.lstm_size: int = 32
        self.seq_len: int = 8
        self.num_epochs = 4

    def build(self) -> "RecurrentPPO":
        return RecurrentPPO(self.copy())


def _init_params(obs_dim, num_actions, hidden, seed):
    rng = np.random.RandomState(seed)
    scale = 1.0 / np.sqrt(obs_dim + hidden)
    return {
        "wx": (rng.randn(obs_dim, 4 * hidden) * scale
               ).astype(np.float32),
        "wh": (rng.randn(hidden, 4 * hidden) * scale
               ).astype(np.float32),
        "b": np.zeros(4 * hidden, np.float32),
        "pi": init_mlp_params(rng, [hidden, num_actions]),
        "vf": init_mlp_params(rng, [hidden, 1]),
    }


class _LSTMAcPolicy:
    """numpy LSTM actor-critic with carried hidden state."""

    def __init__(self, obs_dim, num_actions, hidden, seed):
        self.weights = _init_params(obs_dim, num_actions, hidden, seed)
        self.hidden = hidden
        self.num_actions = num_actions
        self.reset_state()

    def reset_state(self):
        self.h = np.zeros(self.hidden, np.float32)
        self.c = np.zeros(self.hidden, np.float32)

    def state(self):
        return self.h.copy(), self.c.copy()

    def set_weights(self, w):
        self.weights = w

    def get_weights(self):
        return self.weights

    def compute_action(self, obs, rng):
        self.h, self.c = _lstm_step_np(
            self.weights, np.asarray(obs, np.float32).reshape(-1),
            self.h, self.c,
        )
        (Wp, bp), = self.weights["pi"]
        (Wv, bv), = self.weights["vf"]
        logits = self.h @ Wp + bp
        logits = logits - logits.max()
        probs = np.exp(logits)
        probs /= probs.sum()
        a = int(rng.choice(self.num_actions, p=probs))
        return a, float(np.log(probs[a] + 1e-12)), \
            float((self.h @ Wv + bv)[0])


class _RecurrentEnvRunner:
    """On-policy sequence collection: flat fragment stepping (GAE over
    the flat arrays), then chopped into stored-state sequences."""

    def __init__(self, env_creator, policy_factory, seed=0,
                 rollout_fragment_length=128, gamma=0.99, lam=0.95,
                 seq_len=8, **_):
        self.env = env_creator()
        self.policy = policy_factory()
        self.rng = np.random.RandomState(seed)
        self.fragment = rollout_fragment_length
        self.gamma, self.lam = gamma, lam
        self.L = seq_len
        self._obs, _ = self.env.reset(seed=seed)
        self.policy.reset_state()
        self._episode_reward = 0.0
        self._episode_rewards: List[float] = []

    def set_weights(self, w):
        self.policy.set_weights(w)

    def sample(self) -> SampleBatch:
        L = self.L
        obs_l, act_l, rew_l, done_l, logp_l, val_l = \
            [], [], [], [], [], []
        # (start_index, h0, c0) per sequence.
        seq_marks = [(0, *self.policy.state())]
        for t in range(self.fragment):
            obs = np.asarray(self._obs, np.float32).reshape(-1)
            a, logp, v = self.policy.compute_action(obs, self.rng)
            nxt, r, term, trunc, _ = self.env.step(a)
            done = bool(term or trunc)
            obs_l.append(obs)
            act_l.append(a)
            rew_l.append(float(r))
            done_l.append(bool(term))
            logp_l.append(logp)
            val_l.append(v)
            self._episode_reward += float(r)
            boundary = False
            if done:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self.env.reset()
                self.policy.reset_state()
                boundary = True
            else:
                self._obs = nxt
            steps_in_seq = t + 1 - seq_marks[-1][0]
            if (boundary or steps_in_seq == L) and \
                    t + 1 < self.fragment:
                seq_marks.append((t + 1, *self.policy.state()))
        # Bootstrap value for the fragment tail.
        last_value = 0.0
        if not done_l[-1]:
            h, c = self.policy.state()
            h2, _ = _lstm_step_np(
                self.policy.weights,
                np.asarray(self._obs, np.float32).reshape(-1), h, c,
            )
            (Wv, bv), = self.policy.weights["vf"]
            last_value = float((h2 @ Wv + bv)[0])
        gae = compute_gae(
            np.asarray(rew_l, np.float32),
            np.asarray(val_l, np.float32),
            np.asarray(done_l), last_value,
            gamma=self.gamma, lam=self.lam,
        )
        # Chop the flat columns into padded stored-state sequences.
        obs_dim = obs_l[0].shape[0]
        starts = [m[0] for m in seq_marks] + [self.fragment]
        seqs = []
        for i, (start, h0, c0) in enumerate(seq_marks):
            end = min(starts[i + 1], start + L)
            n = end - start
            if n <= 0:
                continue
            s = {
                "obs": np.zeros((L, obs_dim), np.float32),
                "actions": np.zeros(L, np.int32),
                "old_logp": np.zeros(L, np.float32),
                "adv": np.zeros(L, np.float32),
                "returns": np.zeros(L, np.float32),
                "mask": np.zeros(L, np.float32),
                "h0": h0, "c0": c0,
            }
            s["obs"][:n] = np.stack(obs_l[start:end])
            s["actions"][:n] = act_l[start:end]
            s["old_logp"][:n] = logp_l[start:end]
            s["adv"][:n] = gae["advantages"][start:end]
            s["returns"][:n] = gae["returns"][start:end]
            s["mask"][:n] = 1.0
            seqs.append(s)
        return SampleBatch({
            k: np.stack([s[k] for s in seqs]) for k in seqs[0]
        })

    def episode_stats(self) -> Dict[str, float]:
        recent = self._episode_rewards[-20:]
        return {
            "episodes_total": len(self._episode_rewards),
            "episode_reward_mean": float(np.mean(recent))
            if recent else 0.0,
        }


class RecurrentPPOLearner:
    """Masked clipped-surrogate loss over scan-unrolled sequences."""

    def __init__(self, obs_dim, num_actions, cfg):
        import jax
        import jax.numpy as jnp
        import optax

        self._tx = optax.adam(cfg.lr)
        self._params = jax.tree.map(
            jnp.asarray,
            _init_params(obs_dim, num_actions, cfg.lstm_size,
                         cfg.seed),
        )
        self._opt_state = self._tx.init(self._params)
        H = cfg.lstm_size
        clip = cfg.clip_param
        vf_c, ent_c = cfg.vf_loss_coeff, cfg.entropy_coeff

        def unroll(w, obs, h0, c0):
            def cell(carry, x):
                h, c = carry
                z = x @ w["wx"] + h @ w["wh"] + w["b"]
                i = jax.nn.sigmoid(z[..., :H])
                f = jax.nn.sigmoid(z[..., H:2 * H])
                g = jnp.tanh(z[..., 2 * H:3 * H])
                o = jax.nn.sigmoid(z[..., 3 * H:])
                c2 = f * c + i * g
                h2 = o * jnp.tanh(c2)
                return (h2, c2), h2

            _, hs = jax.lax.scan(cell, (h0, c0),
                                 jnp.swapaxes(obs, 0, 1))
            return jnp.swapaxes(hs, 0, 1)     # [B, T, H]

        def loss_fn(p, batch):
            hs = unroll(p, batch["obs"], batch["h0"], batch["c0"])
            (Wp, bp), = p["pi"]
            (Wv, bv), = p["vf"]
            logits = hs @ Wp + bp
            values = (hs @ Wv + bv)[..., 0]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None], -1
            )[..., 0]
            mask = batch["mask"]
            msum = jnp.maximum(mask.sum(), 1.0)
            adv = batch["adv"]
            amean = (adv * mask).sum() / msum
            astd = jnp.sqrt(
                (((adv - amean) * mask) ** 2).sum() / msum
            ) + 1e-8
            adv_n = (adv - amean) / astd
            ratio = jnp.exp(logp - batch["old_logp"])
            surr = jnp.minimum(
                ratio * adv_n,
                jnp.clip(ratio, 1 - clip, 1 + clip) * adv_n,
            )
            pi_loss = -(surr * mask).sum() / msum
            vf_loss = (((values - batch["returns"]) ** 2) * mask
                       ).sum() / msum
            ent = (-(jnp.exp(logp_all) * logp_all).sum(-1) * mask
                   ).sum() / msum
            return pi_loss + vf_c * vf_loss - ent_c * ent

        def update(p, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(p, batch)
            updates, opt_state = self._tx.update(grads, opt_state)
            return optax.apply_updates(p, updates), opt_state, loss

        self._update = jax.jit(update)

    def learn_on_batch(self, mb) -> float:
        import jax.numpy as jnp

        batch = {k: jnp.asarray(v) for k, v in mb.items()}
        batch["actions"] = jnp.asarray(mb["actions"], jnp.int32)
        self._params, self._opt_state, loss = self._update(
            self._params, self._opt_state, batch
        )
        return float(loss)

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self._params)


class RecurrentPPO(Algorithm):
    def _make_policy_factory(self, obs_dim: int, num_actions: int):
        self._require_discrete()
        c = self.config

        def policy_factory(obs_dim=obs_dim, n=num_actions,
                           hidden=c.lstm_size, seed=c.seed):
            return _LSTMAcPolicy(obs_dim, n, hidden, seed)

        return policy_factory

    def _runner_class(self):
        return _RecurrentEnvRunner

    def __init__(self, config):
        import ray_tpu

        # Custom runner construction (needs seq_len), so build the
        # gang here instead of the base constructor's loop.
        self.config = config
        self.iteration = 0
        c = config
        creator = c.env_creator()
        probe = creator()
        obs_dim = int(np.prod(probe.observation_space.shape))
        if not hasattr(probe.action_space, "n"):
            raise ValueError(
                "RecurrentPPO supports discrete action spaces"
            )
        num_actions = int(probe.action_space.n)
        if hasattr(probe, "close"):
            probe.close()
        self._obs_dim, self._num_actions = obs_dim, num_actions
        self._continuous = False

        policy_factory = self._make_policy_factory(obs_dim,
                                                   num_actions)
        runner_cls = ray_tpu.remote(_RecurrentEnvRunner)
        self.runners = [
            runner_cls.remote(
                creator, policy_factory, seed=c.seed + i,
                rollout_fragment_length=c.rollout_fragment_length,
                gamma=c.gamma, lam=c.lambda_, seq_len=c.seq_len,
            )
            for i in range(c.num_env_runners)
        ]
        self.learner = RecurrentPPOLearner(obs_dim, num_actions, c)
        self._rng = np.random.RandomState(c.seed)

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        batches = ray_tpu.get([r.sample.remote() for r in self.runners])
        batch = SampleBatch.concat(batches)
        loss = float("nan")
        for _ in range(c.num_epochs):
            sh = batch.shuffle(self._rng)
            for mb in sh.minibatches(
                max(1, min(c.minibatch_size // c.seq_len, sh.count))
            ):
                loss = self.learner.learn_on_batch(dict(mb))
        w = self.learner.get_weights()
        ray_tpu.get([r.set_weights.remote(w) for r in self.runners])

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled":
                self.iteration * c.num_env_runners
                * c.rollout_fragment_length,
            "loss": loss,
        }
