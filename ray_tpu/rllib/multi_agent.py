"""Multi-agent PPO: several agents per env, policy-mapped learners.

Ref analogue: rllib's MultiAgentEnv + policy mapping
(rllib/env/multi_agent_env.py, the ``policies`` / ``policy_mapping_fn``
config): each env step consumes/produces per-agent dicts; a mapping
function assigns every agent to a policy id; rollouts aggregate
per-POLICY sample batches and one PPOLearner per policy trains on the
accelerator. Agents sharing a policy id share weights (the "shared
policy" pattern); distinct ids train independently.

Env protocol (dict-space, gymnasium-free):
  reset(seed=None) -> ({agent: obs}, info)
  step({agent: action}) -> ({agent: obs}, {agent: reward},
                            {agent: terminated, "__all__": bool},
                            {agent: truncated, "__all__": bool}, info)
Agents absent from an obs dict are inactive that step.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .algorithm import AlgorithmConfig
from .ppo import PPOLearner
from .sample_batch import (
    ACTIONS,
    ADVANTAGES,
    DONES,
    LOGPS,
    OBS,
    RETURNS,
    REWARDS,
    SampleBatch,
    VALUES,
    compute_gae,
)


class MultiAgentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param: float = 0.2
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        # policy_id -> {"obs_dim": int, "num_actions": int}
        self.policies: Dict[str, Dict[str, int]] = {}
        self.policy_mapping_fn: Callable[[str], str] = lambda aid: "default"

    def multi_agent(self, *, policies: Dict[str, Dict[str, int]],
                    policy_mapping_fn: Callable[[str], str]
                    ) -> "MultiAgentPPOConfig":
        self.policies = dict(policies)
        self.policy_mapping_fn = policy_mapping_fn
        return self

    def build(self) -> "MultiAgentPPO":
        if not self.policies:
            raise ValueError("multi_agent(policies=...) required")
        return MultiAgentPPO(self.copy())


class MultiAgentEnvRunner:
    """CPU actor: steps a dict-protocol env with one numpy policy per
    policy id; returns {policy_id: GAE-postprocessed SampleBatch}."""

    def __init__(self, env_creator, policy_factories: Dict[str, Any],
                 policy_mapping_fn, seed: int = 0,
                 rollout_fragment_length: int = 200,
                 gamma: float = 0.99, lam: float = 0.95):
        self.env = env_creator()
        self.policies = {pid: f() for pid, f in policy_factories.items()}
        self.mapping = policy_mapping_fn
        self.rng = np.random.RandomState(seed)
        self.fragment = rollout_fragment_length
        self.gamma = gamma
        self.lam = lam
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_reward = 0.0
        self._episode_rewards: List[float] = []

    def set_weights(self, weights: Dict[str, Any]):
        for pid, w in weights.items():
            self.policies[pid].set_weights(w)

    def _collect(self):
        """Per-AGENT transition columns for one fragment. GAE must run
        over one agent's temporally-adjacent trajectory — interleaving
        agents in a single column would bootstrap one agent's reward
        from the OTHER agent's value estimate (the reference
        postprocesses per (agent, episode) for the same reason)."""
        cols: Dict[str, Dict[str, list]] = {}
        for _ in range(self.fragment):
            actions = {}
            staged = {}  # agent -> (pid, obs, act, logp, val)
            for agent, obs in self._obs.items():
                pid = self.mapping(agent)
                a, logp, val = self.policies[pid].compute_action(
                    np.asarray(obs, dtype=np.float32), self.rng
                )
                actions[agent] = a
                staged[agent] = (pid, obs, a, logp, val)
            nxt, rewards, terms, truncs, _ = self.env.step(actions)
            done_all = bool(terms.get("__all__") or truncs.get("__all__"))
            for agent, (pid, obs, a, logp, val) in staged.items():
                done = bool(
                    terms.get(agent, False) or truncs.get(agent, False)
                    or done_all
                )
                c = cols.setdefault(agent, {
                    "pid": pid, "obs": [], "act": [], "rew": [],
                    "done": [], "logp": [], "val": [],
                })
                c["obs"].append(np.asarray(obs, dtype=np.float32))
                c["act"].append(a)
                c["rew"].append(float(rewards.get(agent, 0.0)))
                c["done"].append(done)
                c["logp"].append(float(logp))
                c["val"].append(float(val))
                self._episode_reward += float(rewards.get(agent, 0.0))
            if done_all:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = nxt
        return cols

    def sample(self) -> Dict[str, SampleBatch]:
        cols = self._collect()
        per_policy: Dict[str, List[SampleBatch]] = {}
        for agent, c in cols.items():
            if not c["obs"]:
                continue
            # Fragment-boundary bootstrap: if this agent's trajectory
            # ends mid-episode, V(s_T+1) comes from its policy's value
            # head (dropping it would bias every truncated tail to 0).
            last_value = 0.0
            if not c["done"][-1] and agent in self._obs:
                _, _, last_value = self.policies[c["pid"]].compute_action(
                    np.asarray(self._obs[agent], dtype=np.float32),
                    self.rng,
                )
            batch = SampleBatch({
                OBS: np.stack(c["obs"]),
                ACTIONS: np.asarray(c["act"]),
                REWARDS: np.asarray(c["rew"], dtype=np.float32),
                DONES: np.asarray(c["done"]),
                LOGPS: np.asarray(c["logp"], dtype=np.float32),
                VALUES: np.asarray(c["val"], dtype=np.float32),
            })
            batch.update(compute_gae(
                batch[REWARDS], batch[VALUES], batch[DONES],
                float(last_value), gamma=self.gamma, lam=self.lam,
            ))
            per_policy.setdefault(c["pid"], []).append(batch)
        return {pid: SampleBatch.concat(parts)
                for pid, parts in per_policy.items()}

    def episode_stats(self) -> Dict[str, float]:
        recent = self._episode_rewards[-20:]
        return {
            "episodes_total": len(self._episode_rewards),
            "episode_reward_mean": (float(np.mean(recent))
                                    if recent else 0.0),
        }


class MultiAgentPPO:
    """One PPOLearner per policy id; rollouts on CPU actors."""

    def __init__(self, config: MultiAgentPPOConfig):
        import ray_tpu
        from .policy import MLPPolicy

        self.config = config
        self.iteration = 0
        c = config

        def factory_for(spec):
            def make(obs_dim=spec["obs_dim"],
                     num_actions=spec["num_actions"],
                     hidden=c.hidden_size, seed=c.seed):
                return MLPPolicy(obs_dim, num_actions, hidden, seed)

            return make

        factories = {pid: factory_for(spec)
                     for pid, spec in c.policies.items()}
        runner_cls = ray_tpu.remote(MultiAgentEnvRunner)
        self.runners = [
            runner_cls.remote(
                c.env_creator(), factories, c.policy_mapping_fn,
                seed=c.seed + i,
                rollout_fragment_length=c.rollout_fragment_length,
                gamma=c.gamma, lam=c.lambda_,
            )
            for i in range(c.num_env_runners)
        ]
        self.learners = {
            pid: PPOLearner(factories[pid](), c.lr, c.clip_param,
                            c.vf_loss_coeff, c.entropy_coeff)
            for pid in c.policies
        }

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        self.iteration += 1
        per_runner = ray_tpu.get([r.sample.remote() for r in self.runners])
        merged: Dict[str, List[SampleBatch]] = {}
        for batches in per_runner:
            for pid, b in batches.items():
                merged.setdefault(pid, []).append(b)
        stats: Dict[str, Any] = {}
        weights: Dict[str, Any] = {}
        for pid, parts in merged.items():
            batch = SampleBatch.concat(parts)
            out = self.learners[pid].update_epochs(
                batch, epochs=c.num_epochs,
                minibatch_size=c.minibatch_size, rng=np.random.RandomState(
                    c.seed + self.iteration),
            )
            stats[f"{pid}/loss"] = out["total_loss"]
            weights[pid] = self.learners[pid].get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self.runners])
        ep = ray_tpu.get([r.episode_stats.remote() for r in self.runners])
        means = [s["episode_reward_mean"] for s in ep
                 if s["episodes_total"] > 0]
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep),
            **stats,
        }

    def get_weights(self) -> Dict[str, Any]:
        return {pid: lr.get_weights() for pid, lr in self.learners.items()}

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
