"""Replay buffers.

Ref analogue: rllib/utils/replay_buffers/replay_buffer.py ReplayBuffer +
prioritized_episode_buffer / PrioritizedReplayBuffer (proportional
prioritization, Schaul et al. 2015). Column-oriented numpy ring storage —
sampling produces contiguous arrays ready for the jax learner without a
per-row gather of python objects.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform FIFO ring buffer over SampleBatch columns."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.RandomState(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = batch.count
        if not self._cols:
            for k, v in batch.items():
                v = np.asarray(v)
                self._cols[k] = np.zeros(
                    (self.capacity,) + v.shape[1:], dtype=v.dtype
                )
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = np.asarray(v)
        self._on_add(idx)
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self._size + n, self.capacity)

    def _on_add(self, idx: np.ndarray) -> None:
        pass

    def sample(self, num_items: int) -> SampleBatch:
        idx = self._rng.randint(0, self._size, size=num_items)
        return self._take(idx)

    def _take(self, idx: np.ndarray) -> SampleBatch:
        out = SampleBatch({k: v[idx] for k, v in self._cols.items()})
        out["batch_indexes"] = idx
        return out


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (ref:
    utils/replay_buffers/prioritized_replay_buffer.py): P(i) ∝ p_i^alpha,
    importance weights w_i = (N · P(i))^-beta / max w."""

    def __init__(self, capacity: int = 100_000, *, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._priorities = np.zeros(capacity, dtype=np.float64)
        self._max_priority = 1.0

    def _on_add(self, idx: np.ndarray) -> None:
        # New transitions get max priority so each is sampled at least once.
        self._priorities[idx] = self._max_priority

    def sample(self, num_items: int) -> SampleBatch:
        p = self._priorities[:self._size] ** self.alpha
        probs = p / p.sum()
        idx = self._rng.choice(self._size, size=num_items, p=probs)
        batch = self._take(idx)
        weights = (self._size * probs[idx]) ** (-self.beta)
        batch["weights"] = (weights / weights.max()).astype(np.float32)
        return batch

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        priorities = np.abs(np.asarray(priorities, dtype=np.float64)) + 1e-6
        self._priorities[np.asarray(idx)] = priorities
        self._max_priority = max(self._max_priority, priorities.max())
