"""Policies.

Ref analogue: rllib/policy/ + new-stack rl_module. The rollout-side policy
is pure numpy (CPU actors step envs without importing jax — SURVEY.md §3.6
keeps env stepping light); the Learner trains the same parameter pytree
with jax on the accelerator and broadcasts weights back.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


def init_mlp_params(
    rng: np.random.RandomState, sizes: List[int]
) -> List[Tuple[np.ndarray, np.ndarray]]:
    params = []
    for fan_in, fan_out in zip(sizes[:-1], sizes[1:]):
        W = rng.randn(fan_in, fan_out).astype(np.float32) * np.sqrt(
            2.0 / fan_in
        )
        b = np.zeros(fan_out, dtype=np.float32)
        params.append((W, b))
    return params


class MLPPolicy:
    """Discrete-action actor-critic MLP; numpy inference."""

    def __init__(self, obs_dim: int, num_actions: int, hidden: int = 64,
                 seed: int = 0):
        rng = np.random.RandomState(seed)
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.weights: Dict[str, List] = {
            "trunk": init_mlp_params(rng, [obs_dim, hidden, hidden]),
            "pi": init_mlp_params(rng, [hidden, num_actions]),
            "vf": init_mlp_params(rng, [hidden, 1]),
        }

    def set_weights(self, weights):
        self.weights = weights

    def get_weights(self):
        return self.weights

    def _trunk(self, x: np.ndarray) -> np.ndarray:
        for W, b in self.weights["trunk"]:
            x = np.tanh(x @ W + b)
        return x

    def logits_and_value(self, obs: np.ndarray):
        h = self._trunk(obs)
        (Wp, bp), = self.weights["pi"]
        (Wv, bv), = self.weights["vf"]
        return h @ Wp + bp, (h @ Wv + bv)[..., 0]

    def compute_action(self, obs: np.ndarray, rng: np.random.RandomState):
        # The net is sized with np.prod(observation_space.shape); flatten so
        # multi-dimensional observation spaces work.
        logits, value = self.logits_and_value(np.asarray(obs).reshape(-1)[None])
        logits = logits[0] - logits[0].max()
        probs = np.exp(logits)
        probs /= probs.sum()
        action = int(rng.choice(self.num_actions, p=probs))
        logp = float(np.log(probs[action] + 1e-12))
        return action, logp, float(value[0])


class QPolicy:
    """Discrete-action Q-network MLP; numpy inference with epsilon-greedy
    exploration (ref analogue: the DQN RLModule's inference path +
    EpsilonGreedy exploration, rllib/utils/exploration/epsilon_greedy.py)."""

    def __init__(self, obs_dim: int, num_actions: int, hidden: int = 64,
                 seed: int = 0):
        rng = np.random.RandomState(seed)
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.epsilon = 1.0
        self.weights: Dict[str, List] = {
            "trunk": init_mlp_params(rng, [obs_dim, hidden, hidden]),
            "q": init_mlp_params(rng, [hidden, num_actions]),
        }

    def set_weights(self, weights):
        self.weights = weights

    def get_weights(self):
        return self.weights

    def set_epsilon(self, epsilon: float):
        self.epsilon = float(epsilon)

    def q_values(self, obs: np.ndarray) -> np.ndarray:
        h = obs
        for W, b in self.weights["trunk"]:
            h = np.tanh(h @ W + b)
        (Wq, bq), = self.weights["q"]
        return h @ Wq + bq

    def compute_action(self, obs: np.ndarray, rng: np.random.RandomState):
        if rng.rand() < self.epsilon:
            action = int(rng.randint(self.num_actions))
        else:
            q = self.q_values(np.asarray(obs).reshape(-1)[None])[0]
            action = int(np.argmax(q))
        # (action, logp, value) signature shared with MLPPolicy so runners
        # are interchangeable; Q-learning has no logp/value at sample time.
        return action, 0.0, 0.0


class DuelingQPolicy(QPolicy):
    """Dueling-architecture Q network (Wang 2016, ref analogue: the
    reference DQN stack's dueling head): Q(s,a) = V(s) + A(s,a) -
    mean_a A(s,a); numpy inference, epsilon-greedy shared with
    QPolicy."""

    def __init__(self, obs_dim: int, num_actions: int, hidden: int = 64,
                 seed: int = 0):
        rng = np.random.RandomState(seed)
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.epsilon = 1.0
        self.weights: Dict[str, List] = {
            "trunk": init_mlp_params(rng, [obs_dim, hidden, hidden]),
            "v": init_mlp_params(rng, [hidden, 1]),
            "a": init_mlp_params(rng, [hidden, num_actions]),
        }

    def q_values(self, obs: np.ndarray) -> np.ndarray:
        h = obs
        for W, b in self.weights["trunk"]:
            h = np.tanh(h @ W + b)
        (Wv, bv), = self.weights["v"]
        (Wa, ba), = self.weights["a"]
        v = h @ Wv + bv
        a = h @ Wa + ba
        return v + a - a.mean(axis=-1, keepdims=True)


class DeterministicPolicy:
    """Continuous-control deterministic actor (TD3-style): tanh(mu)
    scaled to the Box bounds, plus Gaussian EXPLORATION noise applied at
    sample time only (ref analogue: the TD3 policy's deterministic
    action + GaussianNoise exploration)."""

    def __init__(self, obs_dim: int, act_dim: int, low, high,
                 hidden: int = 64, seed: int = 0,
                 exploration_noise: float = 0.1):
        rng = np.random.RandomState(seed)
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.low = np.asarray(low, dtype=np.float32)
        self.high = np.asarray(high, dtype=np.float32)
        self.exploration_noise = exploration_noise
        self.weights: Dict[str, List] = {
            "trunk": init_mlp_params(rng, [obs_dim, hidden, hidden]),
            "mu": init_mlp_params(rng, [hidden, act_dim]),
        }

    def set_weights(self, weights):
        self.weights = weights

    def get_weights(self):
        return self.weights

    def compute_action(self, obs: np.ndarray, rng: np.random.RandomState):
        h = obs.reshape(-1)
        for W, b in self.weights["trunk"]:
            h = np.tanh(h @ W + b)
        (Wm, bm), = self.weights["mu"]
        u = np.tanh(h @ Wm + bm)
        u = np.clip(
            u + self.exploration_noise * rng.randn(self.act_dim),
            -1.0, 1.0,
        )
        action = self.low + (u + 1.0) * 0.5 * (self.high - self.low)
        return action.astype(np.float32), 0.0, 0.0


class SquashedGaussianPolicy:
    """Continuous-control actor: tanh-squashed Gaussian over a Box action
    space, numpy inference for rollouts (ref analogue: the SAC policy's
    SquashedGaussian action distribution)."""

    def __init__(self, obs_dim: int, act_dim: int, low, high,
                 hidden: int = 64, seed: int = 0):
        rng = np.random.RandomState(seed)
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.low = np.asarray(low, dtype=np.float32)
        self.high = np.asarray(high, dtype=np.float32)
        self.weights: Dict[str, List] = {
            "trunk": init_mlp_params(rng, [obs_dim, hidden, hidden]),
            "mu": init_mlp_params(rng, [hidden, act_dim]),
            "log_std": init_mlp_params(rng, [hidden, act_dim]),
        }

    def set_weights(self, weights):
        self.weights = weights

    def get_weights(self):
        return self.weights

    def compute_action(self, obs: np.ndarray, rng: np.random.RandomState):
        h = obs.reshape(-1)  # flatten multi-dim Box observations
        for W, b in self.weights["trunk"]:
            h = np.tanh(h @ W + b)
        (Wm, bm), = self.weights["mu"]
        (Ws, bs), = self.weights["log_std"]
        mu = h @ Wm + bm
        log_std = np.clip(h @ Ws + bs, -5.0, 2.0)
        u = np.tanh(mu + np.exp(log_std) * rng.randn(self.act_dim))
        action = self.low + (u + 1.0) * 0.5 * (self.high - self.low)
        return action.astype(np.float32), 0.0, 0.0
