"""CRR: critic-regularized regression — offline continuous control.

Ref analogue: rllib/algorithms/crr (Wang 2020 "Critic Regularized
Regression"). Twin critics learn a standard TD backup from the logged
transitions (no conservative penalty — that is CQL's device); the
actor is trained by ADVANTAGE-FILTERED behavior cloning: regress
pi(s) toward the DATASET action, weighted by
    f(A) = 1[A > 0]          ("binary" mode)
    f(A) = exp(A / beta)      ("exp" mode, clipped)
with A(s, a) = Q1(s, a) - Q1(s, pi(s)) — actions the critic scores
above the current policy pull the policy toward them; worse actions
are ignored (binary) or exponentially down-weighted. The reference
trains a stochastic policy; this adaptation regresses the shared
deterministic actor (core.py DeterministicActorModule), which keeps
weights drop-in compatible with the TD3/CQL rollout policies.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .algorithm import AlgorithmConfig
from .core import (
    DeterministicActorModule,
    QModule,
    TwinCriticLearner,
)


class CRRConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.dataset = None
        self.obs_column = "obs"
        self.action_column = "action"
        self.reward_column = "reward"
        self.next_obs_column = "next_obs"
        self.done_column = "done"
        self.tau: float = 0.005
        self.weight_type: str = "exp"   # "exp" | "binary"
        self.beta: float = 1.0          # exp temperature
        self.epochs_per_iteration: int = 1

    _COLUMN_KEYS = ("obs_column", "action_column", "reward_column",
                    "next_obs_column", "done_column")

    def offline_data(self, dataset, **columns) -> "CRRConfig":
        self.dataset = dataset
        for k, v in columns.items():
            if k not in self._COLUMN_KEYS:
                raise ValueError(
                    f"unknown offline_data column {k!r} "
                    f"(allowed: {self._COLUMN_KEYS})"
                )
            setattr(self, k, v)
        return self

    def build(self) -> "CRR":
        if self.dataset is None:
            raise ValueError("CRRConfig.offline_data(dataset=...) "
                             "required")
        return CRR(self.copy())


class CRRLearner(TwinCriticLearner):
    """Critic: twin TD toward the target actor's next action (TD3
    without smoothing). Actor: advantage-weighted regression toward
    the logged action — overrides the base actor_update (which would
    maximize Q; CRR explicitly regularizes toward the data instead)."""

    def __init__(self, cfg, obs_dim: int, act_dim: int):
        super().__init__(
            DeterministicActorModule(
                obs_dim, act_dim, cfg.hidden_size, cfg.seed
            ).init_params(),
            obs_dim=obs_dim, act_dim=act_dim, hidden=cfg.hidden_size,
            lr=cfg.lr, tau=cfg.tau, seed=cfg.seed,
        )
        self._gamma = cfg.gamma
        self._beta = cfg.beta
        self._binary = cfg.weight_type == "binary"
        self._jit_crr_actor = None

    def compute_loss(self, params, target, batch):
        import jax
        import jax.numpy as jnp

        obs, act = batch["obs"], batch["act"]
        nxt, rew, done = batch["next_obs"], batch["rew"], batch["done"]
        a2 = DeterministicActorModule.forward(target["actor"], nxt)
        tq = jnp.minimum(
            QModule.forward(target["q1"], nxt, a2),
            QModule.forward(target["q2"], nxt, a2),
        )
        backup = jax.lax.stop_gradient(
            rew + self._gamma * (1.0 - done) * tq
        )
        q1 = QModule.forward(params["q1"], obs, act)
        q2 = QModule.forward(params["q2"], obs, act)
        td_loss = ((q1 - backup) ** 2 + (q2 - backup) ** 2).mean()
        return td_loss, {"td_loss": td_loss, "q1_mean": q1.mean()}

    def actor_update(self, batch) -> Dict[str, Any]:
        """Advantage-weighted regression toward the dataset action."""
        import jax
        import jax.numpy as jnp
        import optax

        if self._jit_crr_actor is None:
            tau = self._tau
            binary = self._binary
            beta = self._beta

            def aloss(actor, q1, obs, act):
                pi = DeterministicActorModule.forward(actor, obs)
                adv = (QModule.forward(q1, obs, act)
                       - QModule.forward(q1, obs, pi))
                adv = jax.lax.stop_gradient(adv)
                if binary:
                    w = (adv > 0).astype(jnp.float32)
                else:
                    w = jnp.exp(jnp.clip(adv / beta, -5.0, 5.0))
                mse = ((pi - act) ** 2).sum(-1)
                return (w * mse).mean(), w.mean()

            def upd(actor, aopt_state, q1, atarget, obs, act):
                (loss, wmean), grads = jax.value_and_grad(
                    aloss, has_aux=True
                )(actor, jax.lax.stop_gradient(q1), obs, act)
                updates, aopt_state = self._atx.update(
                    grads, aopt_state, actor
                )
                actor = optax.apply_updates(actor, updates)
                atarget = jax.tree.map(
                    lambda t, p: (1.0 - tau) * t + tau * p,
                    atarget, actor,
                )
                return actor, aopt_state, atarget, loss, wmean

            self._jit_crr_actor = jax.jit(upd)
        actor, self._aopt_state, atarget, loss, wmean = (
            self._jit_crr_actor(
                self._params["actor"], self._aopt_state,
                self._params["q1"], self._target["actor"],
                jnp.asarray(batch["obs"]), jnp.asarray(batch["act"]),
            )
        )
        self._params = {**self._params, "actor": actor}
        self._target = {**self._target, "actor": atarget}
        return {"actor_loss": loss, "mean_weight": wmean}

    def learn_on_batch(self, np_batch) -> Dict[str, Any]:
        stats = self.update_device(np_batch)
        return {**stats, **self.actor_update(np_batch)}


class CRR:
    """Offline trainer: epochs of minibatch updates streamed from the
    Dataset (same driver shape as CQL)."""

    def __init__(self, config: CRRConfig):
        c = config
        self.config = c
        self.iteration = 0
        probe = next(iter(
            c.dataset.iter_batches(batch_size=1, batch_format="numpy")
        ))
        obs = np.asarray(probe[c.obs_column])
        act = np.asarray(probe[c.action_column])
        self._obs_dim = int(np.prod(obs.shape[1:])) or 1
        self._act_dim = int(np.prod(act.shape[1:])) or 1
        self.learner = CRRLearner(c, self._obs_dim, self._act_dim)

    def train(self) -> Dict[str, Any]:
        c = self.config
        self.iteration += 1
        stats: Dict[str, Any] = {}
        updates = 0
        for _ in range(c.epochs_per_iteration):
            for batch in c.dataset.iter_batches(
                batch_size=c.minibatch_size, batch_format="numpy",
                drop_last=True,
            ):
                n = len(batch[c.obs_column])
                stats = self.learner.learn_on_batch({
                    "obs": np.asarray(batch[c.obs_column],
                                      np.float32).reshape(n, -1),
                    "act": np.asarray(batch[c.action_column],
                                      np.float32).reshape(n, -1),
                    "rew": np.asarray(batch[c.reward_column],
                                      np.float32),
                    "next_obs": np.asarray(
                        batch[c.next_obs_column], np.float32
                    ).reshape(n, -1),
                    "done": np.asarray(batch[c.done_column],
                                       np.float32),
                })
                updates += 1
        stats = {k: float(v) for k, v in stats.items()}
        return {
            "training_iteration": self.iteration,
            "num_learner_updates": updates,
            **stats,
        }

    def get_weights(self):
        return self.learner.get_weights()

    def stop(self):
        pass
