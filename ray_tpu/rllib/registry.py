"""Algorithm registry: name -> Config class.

Ref analogue: rllib/algorithms/registry.py (get_algorithm_class — the
lookup behind `rllib train --run PPO`). Names are case-insensitive;
``get_algorithm_config("ppo")`` returns a fresh config builder.
"""

from __future__ import annotations

from typing import Dict, List, Type


def _registry() -> Dict[str, Type]:
    from . import (
        A2CConfig,
        A3CConfig,
        AlphaZeroConfig,
        ApexDDPGConfig,
        ApexDQNConfig,
        APPOConfig,
        ARSConfig,
        BanditLinTSConfig,
        BanditLinUCBConfig,
        BCConfig,
        CQLConfig,
        CRRConfig,
        DDPGConfig,
        DDPPOConfig,
        DQNConfig,
        DTConfig,
        ESConfig,
        IMPALAConfig,
        MADDPGConfig,
        MARWILConfig,
        MultiAgentPPOConfig,
        PGConfig,
        PPOConfig,
        QMIXConfig,
        R2D2Config,
        RecurrentPPOConfig,
        SACConfig,
        SlateQConfig,
        TD3Config,
    )

    return {
        "a2c": A2CConfig,
        "a3c": A3CConfig,
        "alphazero": AlphaZeroConfig,
        "alpha_zero": AlphaZeroConfig,
        "apex": ApexDQNConfig,
        "apex_ddpg": ApexDDPGConfig,
        "apex_dqn": ApexDQNConfig,
        "appo": APPOConfig,
        "ars": ARSConfig,
        "bandit_lints": BanditLinTSConfig,
        "bandit_linucb": BanditLinUCBConfig,
        "bc": BCConfig,
        "cql": CQLConfig,
        "crr": CRRConfig,
        "ddpg": DDPGConfig,
        "ddppo": DDPPOConfig,
        "dqn": DQNConfig,
        "dt": DTConfig,
        "es": ESConfig,
        "impala": IMPALAConfig,
        "maddpg": MADDPGConfig,
        "marwil": MARWILConfig,
        "multi_agent_ppo": MultiAgentPPOConfig,
        "pg": PGConfig,
        "ppo": PPOConfig,
        "qmix": QMIXConfig,
        "r2d2": R2D2Config,
        "recurrent_ppo": RecurrentPPOConfig,
        "ppo_lstm": RecurrentPPOConfig,
        "sac": SACConfig,
        "slateq": SlateQConfig,
        "td3": TD3Config,
    }


def get_algorithm_config(name: str):
    """Fresh Config instance for an algorithm name (ref:
    get_algorithm_class)."""
    reg = _registry()
    key = name.lower().replace("-", "_")
    if key not in reg:
        raise ValueError(
            f"unknown algorithm {name!r}; available: "
            f"{sorted(set(reg))}"
        )
    return reg[key]()


def list_algorithms() -> List[str]:
    """Canonical registered names (one resolvable key per algorithm;
    aliases collapsed to the shortest)."""
    by_cls: Dict[Type, str] = {}
    for key, cls in sorted(_registry().items(),
                           key=lambda kv: (len(kv[0]), kv[0])):
        by_cls.setdefault(cls, key)
    return sorted(by_cls.values())
