"""Ape-X DDPG: distributed prioritized replay for continuous control.

Ref analogue: rllib/algorithms/apex_ddpg (Horgan 2018 applied to
DDPG). The Ape-X architecture of apex_dqn.py — replay buffer as an
actor, per-worker exploration ladder, async rollout re-arming — with
the DDPG learner underneath: here the ladder scales the Gaussian
EXPLORATION NOISE of each deterministic-policy worker instead of an
epsilon.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .apex_dqn import _ReplayActor
from .ddpg import DDPG, DDPGConfig, DDPGLearner
from .sample_batch import SampleBatch


class ApexDDPGConfig(DDPGConfig):
    def __init__(self):
        super().__init__()
        self.num_env_runners = 4
        self.noise_base: float = 0.4      # most exploratory worker
        self.noise_exponent: float = 3.0  # ladder decay
        self.prioritized_replay_alpha: float = 0.6
        self.prioritized_replay_beta: float = 0.4

    def build(self) -> "ApexDDPG":
        return ApexDDPG(self.copy())


class ApexDDPG(DDPG):
    def _make_policy_factory(self, obs_dim: int, act_dim: int):
        # Per-worker noise set at runner construction via the ladder;
        # the factory itself uses the base noise (replaced below).
        return super()._make_policy_factory(obs_dim, act_dim)

    def _build_learner(self, policy):
        import ray_tpu

        c = self.config
        self._env_steps = 0
        self.replay = ray_tpu.remote(_ReplayActor).remote(
            c.buffer_size, c.prioritized_replay_alpha,
            c.prioritized_replay_beta, c.seed,
        )
        n = max(1, len(getattr(self, "runners", []))
                or c.num_env_runners)
        # Noise ladder: worker i explores with
        # noise_base^(1 + k·i/(n-1)) — same shape as Ape-X's epsilon
        # ladder, applied to the Gaussian action noise.
        self._ladder = [
            c.noise_base ** (
                1.0 + c.noise_exponent * i / max(1, n - 1)
            )
            for i in range(n)
        ]
        self._sample_futs: Dict[Any, int] = {}
        return DDPGLearner(policy, c, self._obs_dim,
                           self._num_actions, self._action_low,
                           self._action_high)

    def _arm(self, i: int):
        self._sample_futs[self.runners[i].sample.remote()] = i

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        if not self._sample_futs:
            # One-time: apply the noise ladder (policy attribute on the
            # runner side) and arm every runner.
            for i, r in enumerate(self.runners):
                r.set_exploration_noise.remote(self._ladder[i])
            for i in range(len(self.runners)):
                self._arm(i)

        ready, rest = ray_tpu.wait(
            list(self._sample_futs), num_returns=1, timeout=10.0
        )
        if rest:
            more, _ = ray_tpu.wait(rest, num_returns=len(rest),
                                   timeout=0)
            ready = list(ready) + list(more)
        add_futs = []
        for ref in ready:
            i = self._sample_futs.pop(ref)
            batch = ray_tpu.get(ref)
            self._env_steps += batch.count
            add_futs.append(self.replay.add.remote(batch))
            self._arm(i)
        if add_futs:
            ray_tpu.get(add_futs)

        stats: Dict[str, Any] = {}
        num_updates = 0
        buffer_size = ray_tpu.get(self.replay.size.remote())
        if buffer_size >= c.num_steps_sampled_before_learning_starts:
            pending = self.replay.sample.remote(c.minibatch_size)
            for _ in range(c.num_updates_per_iteration):
                mb = ray_tpu.get(pending)
                pending = self.replay.sample.remote(c.minibatch_size)
                if mb is None:
                    break
                stats = self.learner.learn_on_batch(mb)
                # New transitions enter at max priority (each sampled at
                # least once — the Ape-X insertion property); td-error
                # priority REFRESH is not wired through the jitted DDPG
                # critic step, so replay decays toward uniform.
                num_updates += 1
            stats = {k: float(v) for k, v in stats.items()}
            weights = self.learner.get_weights()
            for r in self.runners:
                r.set_weights.remote(weights)  # async broadcast

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "num_learner_updates": num_updates,
            "buffer_size": buffer_size,
            **stats,
        }

    def stop(self):
        import ray_tpu

        super().stop()
        try:
            ray_tpu.kill(self.replay)
        except Exception:
            pass
