"""A3C: asynchronous advantage actor-critic.

Ref analogue: rllib/algorithms/a3c (Mnih 2016). The asynchrony is the
point: each rollout worker computes actor-critic gradients on its own
fresh fragment and the central learner applies them AS THEY ARRIVE —
no barrier, no averaging — then sends that worker the refreshed
weights. Slow workers therefore compute gradients against slightly
stale parameters (the HOGWILD-style tolerance the paper relies on).
Reuses DD-PPO's embedded worker-learner plane with the A2C loss.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .a2c import A2CLearner
from .algorithm import AlgorithmConfig
from .ddppo import _WorkerLearner
from .sample_batch import ACTIONS, ADVANTAGES, OBS, RETURNS


class A3CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.grads_per_iteration: int = 8

    def build(self) -> "A3C":
        return A3C(self.copy())


class _A3CWorker(_WorkerLearner):
    """Worker computing A2C gradients on its own rollouts."""

    def __init__(self, env_creator, policy_factory, *, lr, vf_coeff,
                 ent_coeff, seed=0, rollout_fragment_length=200,
                 gamma=0.99, lam=0.95):
        # Reuse the DD-PPO worker shell with the A2C loss.
        super().__init__(
            env_creator, policy_factory, lr=lr, clip=0.2,
            vf_coeff=vf_coeff, ent_coeff=ent_coeff, seed=seed,
            rollout_fragment_length=rollout_fragment_length,
            gamma=gamma, lam=lam,
        )
        self._learner = A2CLearner(self.policy, lr, vf_coeff,
                                   ent_coeff)
        self._grad_fn = None

    def set_weights(self, weights):
        import jax
        import jax.numpy as jnp

        self._learner._params = jax.tree.map(jnp.asarray, weights)
        self.policy.set_weights(weights)


class A3C:
    def __init__(self, config: A3CConfig):
        import jax
        import jax.numpy as jnp
        import optax

        import ray_tpu

        self.config = config
        self.iteration = 0
        c = config
        creator = c.env_creator()
        probe = creator()
        obs_dim = int(np.prod(probe.observation_space.shape))
        if not hasattr(probe.action_space, "n"):
            raise ValueError("A3C supports discrete action spaces")
        num_actions = int(probe.action_space.n)
        if hasattr(probe, "close"):
            probe.close()

        def policy_factory(obs_dim=obs_dim, num_actions=num_actions,
                           hidden=c.hidden_size, seed=c.seed):
            from .policy import MLPPolicy

            return MLPPolicy(obs_dim, num_actions, hidden, seed)

        worker_cls = ray_tpu.remote(_A3CWorker)
        self.workers = [
            worker_cls.remote(
                creator, policy_factory,
                lr=c.lr, vf_coeff=c.vf_loss_coeff,
                ent_coeff=c.entropy_coeff, seed=c.seed + i,
                rollout_fragment_length=c.rollout_fragment_length,
                gamma=c.gamma, lam=c.lambda_,
            )
            for i in range(c.num_env_runners)
        ]
        # Central parameter server: the driver holds the canonical
        # params + optimizer and applies gradients as they land.
        policy = policy_factory()
        self._params = jax.tree.map(jnp.asarray, policy.get_weights())
        self._tx = optax.adam(c.lr)
        self._opt_state = self._tx.init(self._params)

        def apply(params, opt_state, grads):
            updates, opt_state = self._tx.update(grads, opt_state,
                                                 params)
            import optax as _optax

            return _optax.apply_updates(params, updates), opt_state

        self._apply = jax.jit(apply)
        self._env_steps = 0
        self._inflight: Dict[Any, int] = {}

    def _weights(self):
        import jax

        return jax.tree.map(np.asarray, self._params)

    def train(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        import ray_tpu

        self.iteration += 1
        c = self.config
        if not self._inflight:
            w = self._weights()
            ray_tpu.get([wk.set_weights.remote(w)
                         for wk in self.workers])
            for i, wk in enumerate(self.workers):
                self._inflight[wk.sample_and_grad.remote()] = i

        losses: List[float] = []
        applied = 0
        while applied < c.grads_per_iteration:
            ready, _ = ray_tpu.wait(
                list(self._inflight), num_returns=1, timeout=30.0
            )
            if not ready:
                break
            ref = ready[0]
            i = self._inflight.pop(ref)
            out = ray_tpu.get(ref)
            grads = jax.tree.map(jnp.asarray, out["grads"])
            # Apply THIS worker's gradient immediately (async,
            # possibly stale — the A3C contract).
            self._params, self._opt_state = self._apply(
                self._params, self._opt_state, grads
            )
            self._env_steps += out["count"]
            losses.append(out["loss"])
            applied += 1
            # Refresh only this worker and re-arm it.
            self.workers[i].set_weights.remote(self._weights())
            self._inflight[
                self.workers[i].sample_and_grad.remote()
            ] = i

        ep_stats = ray_tpu.get(
            [wk.episode_stats.remote() for wk in self.workers]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "num_grads_applied": applied,
            "loss": float(np.mean(losses)) if losses else float("nan"),
        }

    def get_weights(self):
        return self._weights()

    def stop(self):
        import ray_tpu

        for wk in self.workers:
            try:
                ray_tpu.kill(wk)
            except Exception:
                pass
