"""MADDPG: multi-agent DDPG with centralized critics.

Ref analogue: rllib/algorithms/maddpg (Lowe 2017 "Multi-Agent
Actor-Critic for Mixed Cooperative-Competitive Environments").
Execution is decentralized — each agent's deterministic actor sees
only its own observation — but training is centralized: every agent's
critic Q_i(o_all, a_all) conditions on ALL agents' observations and
actions, with other agents' next actions supplied by their target
actors. That converts the non-stationary multi-agent problem into a
stationary one per critic.

Env protocol: the dict convention of multi_agent.py with Box action
spaces and every agent present each step (fixed team).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import AlgorithmConfig
from .core import DeterministicActorModule, Learner, QModule
from .policy import init_mlp_params
from .replay_buffers import ReplayBuffer
from .sample_batch import SampleBatch


class MADDPGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size: int = 50_000
        self.num_steps_sampled_before_learning_starts: int = 500
        self.num_updates_per_iteration: int = 48
        self.tau: float = 0.01
        self.exploration_noise: float = 0.2
        # probed/declared dims
        self.act_dim: int = 0   # per-agent Box action dim (required)

    def build(self) -> "MADDPG":
        if not self.act_dim:
            raise ValueError("MADDPGConfig.training(act_dim=...) "
                             "required")
        return MADDPG(self.copy())


class MADDPGLearner(Learner):
    """params: {actor_<i>, q_<i>} per agent. The base polyak machinery
    tracks every subtree; one jitted update per agent pair (critic on
    the joint transition, actor maximizing its own centralized Q with
    the OTHER agents' current actions held fixed)."""

    def __init__(self, n_agents: int, obs_dim: int, act_dim: int,
                 hidden: int, lr: float, tau: float, gamma: float,
                 seed: int):
        joint_obs = n_agents * obs_dim
        joint_act = n_agents * act_dim
        params: Dict[str, Any] = {}
        for i in range(n_agents):
            params[f"actor_{i}"] = DeterministicActorModule(
                obs_dim, act_dim, hidden, seed + i
            ).init_params()
            params[f"q_{i}"] = QModule(
                joint_obs, joint_act, hidden, seed + 100 + i
            ).init_params()
        super().__init__(params, lr=lr, target_keys=tuple(params),
                         tau=tau)
        self._n = n_agents
        self._gamma = gamma
        self._obs_dim = obs_dim
        self._act_dim = act_dim
        self._jit_step = None

    def _build_step(self):
        import jax
        import jax.numpy as jnp
        import optax

        n, gamma = self._n, self._gamma

        def joint(x):  # [B, A, D] -> [B, A*D]
            return x.reshape(x.shape[0], -1)

        def critic_loss(params, target, batch):
            # Next joint action from ALL target actors.
            a2 = jnp.stack([
                DeterministicActorModule.forward(
                    target[f"actor_{j}"], batch["next_obs"][:, j]
                )
                for j in range(n)
            ], axis=1)
            total = 0.0
            stats = {}
            for i in range(n):
                tq = QModule.forward(
                    target[f"q_{i}"], joint(batch["next_obs"]),
                    joint(a2),
                )
                backup = jax.lax.stop_gradient(
                    batch["rew"][:, i]
                    + gamma * (1.0 - batch["done"]) * tq
                )
                q = QModule.forward(
                    params[f"q_{i}"], joint(batch["obs"]),
                    joint(batch["actions"]),
                )
                li = ((q - backup) ** 2).mean()
                total = total + li
                stats[f"critic_loss_{i}"] = li
            return total, stats

        def actor_loss(params, batch):
            total = 0.0
            for i in range(n):
                acts = [
                    DeterministicActorModule.forward(
                        params[f"actor_{j}"], batch["obs"][:, j]
                    ) if j == i else jax.lax.stop_gradient(
                        batch["actions"][:, j]
                    )
                    for j in range(n)
                ]
                a = jnp.stack(acts, axis=1)
                q = QModule.forward(
                    jax.lax.stop_gradient(params[f"q_{i}"]),
                    joint(batch["obs"]), joint(a),
                )
                total = total - q.mean()
            return total

        def step(params, opt_state, target, batch):
            (closs, stats), cgrads = jax.value_and_grad(
                critic_loss, has_aux=True
            )(params, target, batch)
            aloss, agrads = jax.value_and_grad(actor_loss)(
                params, batch
            )
            grads = jax.tree.map(lambda a, b: a + b, cgrads, agrads)
            updates, opt_state = self._tx.update(grads, opt_state,
                                                 params)
            params = optax.apply_updates(params, updates)
            tau = self._tau
            target = jax.tree.map(
                lambda t, p: (1.0 - tau) * t + tau * p, target, params
            )
            stats["actor_loss"] = aloss
            stats["critic_loss"] = closs
            return params, opt_state, target, stats

        self._jit_step = jax.jit(step)

    def learn_on_batch(self, np_batch) -> Dict[str, Any]:
        import jax.numpy as jnp

        if self._jit_step is None:
            self._build_step()
        jb = {k: jnp.asarray(v) for k, v in np_batch.items()}
        self._params, self._opt_state, self._target, stats = (
            self._jit_step(self._params, self._opt_state, self._target,
                           jb)
        )
        self.num_updates += 1
        return stats

    def actor_weights(self) -> List[Any]:
        import jax

        return [
            jax.tree.map(np.asarray, self._params[f"actor_{i}"])
            for i in range(self._n)
        ]


class _MADDPGEnvRunner:
    """CPU actor: steps the dict env with per-agent deterministic
    actors + exploration noise; emits joint transitions."""

    def __init__(self, env_creator, agent_ids, obs_dim, act_dim,
                 low, high, hidden, noise, seed: int = 0,
                 rollout_fragment_length: int = 200):
        self.env = env_creator()
        self.agent_ids = list(agent_ids)
        rng = np.random.RandomState(seed)
        self.weights = [
            {
                "trunk": init_mlp_params(rng, [obs_dim, hidden, hidden]),
                "mu": init_mlp_params(rng, [hidden, act_dim]),
            }
            for _ in self.agent_ids
        ]
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)
        self.noise = noise
        self.act_dim = act_dim
        self.rng = np.random.RandomState(seed)
        self.fragment = rollout_fragment_length
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_reward = 0.0
        self._episode_rewards: List[float] = []

    def set_weights(self, weights: List[Any]):
        self.weights = weights

    def _act(self, i: int, obs: np.ndarray) -> np.ndarray:
        h = obs.reshape(-1)
        for W, b in self.weights[i]["trunk"]:
            h = np.tanh(h @ W + b)
        (Wm, bm), = self.weights[i]["mu"]
        u = np.tanh(h @ Wm + bm)
        u = np.clip(u + self.noise * self.rng.randn(self.act_dim),
                    -1.0, 1.0)
        return (self.low + (u + 1.0) * 0.5
                * (self.high - self.low)).astype(np.float32)

    def _stack(self, obs_dict):
        return np.stack([
            np.asarray(obs_dict[a], np.float32).reshape(-1)
            for a in self.agent_ids
        ])

    def sample(self) -> SampleBatch:
        obs_l, act_l, rew_l, done_l, next_l = [], [], [], [], []
        for _ in range(self.fragment):
            joint = self._stack(self._obs)
            # Critics train on [-1,1] actions; env gets scaled ones.
            unit_actions = []
            env_actions = {}
            for i, a in enumerate(self.agent_ids):
                env_a = self._act(i, joint[i])
                u = (env_a - self.low) / (self.high - self.low) \
                    * 2.0 - 1.0
                unit_actions.append(u.astype(np.float32))
                env_actions[a] = env_a
            nxt, rew, term, trunc, _ = self.env.step(env_actions)
            done = bool(term.get("__all__")) or bool(
                trunc.get("__all__")
            )
            obs_l.append(joint)
            act_l.append(np.stack(unit_actions))
            rew_l.append([float(rew[a]) for a in self.agent_ids])
            done_l.append(bool(term.get("__all__")))
            next_l.append(self._stack(nxt))
            self._episode_reward += float(sum(rew.values()))
            if done:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = nxt
        return SampleBatch({
            "obs": np.stack(obs_l),            # [T, A, obs]
            "actions": np.stack(act_l),        # [T, A, act] in [-1,1]
            "rew": np.asarray(rew_l, np.float32),   # [T, A]
            "done": np.asarray(done_l, np.float32),
            "next_obs": np.stack(next_l),
        })

    def episode_stats(self) -> Dict[str, float]:
        recent = self._episode_rewards[-20:]
        return {
            "episodes_total": len(self._episode_rewards),
            "episode_reward_mean": float(np.mean(recent))
            if recent else 0.0,
        }


class MADDPG:
    def __init__(self, config: MADDPGConfig):
        import ray_tpu

        self.config = config
        self.iteration = 0
        c = config
        creator = c.env_creator()
        probe = creator()
        obs0, _ = probe.reset(seed=0)
        self.agent_ids = sorted(obs0.keys())
        n = len(self.agent_ids)
        obs_dim = int(np.prod(np.asarray(
            obs0[self.agent_ids[0]]).shape))
        if hasattr(probe, "close"):
            probe.close()
        low = -np.ones(c.act_dim, np.float32)
        high = np.ones(c.act_dim, np.float32)
        if hasattr(probe, "action_low"):
            low = np.asarray(probe.action_low, np.float32)
            high = np.asarray(probe.action_high, np.float32)
        self._n, self._obs_dim = n, obs_dim

        runner_cls = ray_tpu.remote(_MADDPGEnvRunner)
        self.runners = [
            runner_cls.remote(
                creator, self.agent_ids, obs_dim, c.act_dim, low, high,
                c.hidden_size, c.exploration_noise, seed=c.seed + i,
                rollout_fragment_length=c.rollout_fragment_length,
            )
            for i in range(c.num_env_runners)
        ]
        self.learner = MADDPGLearner(
            n, obs_dim, c.act_dim, c.hidden_size, c.lr, c.tau,
            c.gamma, c.seed,
        )
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._env_steps = 0

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        self.iteration += 1
        c = self.config
        batches = ray_tpu.get([r.sample.remote() for r in self.runners])
        for b in batches:
            self.buffer.add(b)
            self._env_steps += b.count

        stats: Dict[str, Any] = {}
        num_updates = 0
        if self._env_steps >= c.num_steps_sampled_before_learning_starts:
            for _ in range(c.num_updates_per_iteration):
                mb = self.buffer.sample(c.minibatch_size)
                stats = self.learner.learn_on_batch({
                    "obs": mb["obs"], "actions": mb["actions"],
                    "rew": mb["rew"], "done": mb["done"],
                    "next_obs": mb["next_obs"],
                })
                num_updates += 1
            stats = {k: float(v) for k, v in stats.items()}
            weights = self.learner.actor_weights()
            ray_tpu.get(
                [r.set_weights.remote(weights) for r in self.runners]
            )

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "num_learner_updates": num_updates,
            **stats,
        }

    def get_weights(self):
        return self.learner.actor_weights()

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
