"""Ape-X DQN: distributed prioritized experience replay.

Ref analogue: rllib/algorithms/apex_dqn (Horgan 2018): the reference's
architectural changes over DQN, mapped onto this runtime —
  * the replay buffer becomes a dedicated ACTOR (the reference's
    ReplayActor shards) so sampling, insertion and priority updates are
    off the learner's critical path;
  * EnvRunners explore with a fixed per-worker epsilon LADDER
    eps_i = base^(1 + 7 i/(N-1)) instead of a global decay schedule;
  * rollout collection is ASYNC: runner sample futures are re-armed as
    they land (ray_tpu.wait), while the learner trains on replay
    minibatches concurrently and pushes td-error priorities back.
Reuses DQNLearner (double-Q, dueling, n-step via DQNConfig flags).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .dqn import DQN, DQNConfig, DQNLearner, nstep_returns
from .replay_buffers import PrioritizedReplayBuffer
from .sample_batch import SampleBatch


class _ReplayActor:
    """Owns the prioritized buffer; all access is actor calls."""

    def __init__(self, capacity: int, alpha: float, beta: float,
                 seed: int):
        self._buf = PrioritizedReplayBuffer(
            capacity, alpha=alpha, beta=beta, seed=seed
        )

    def add(self, batch: SampleBatch) -> int:
        self._buf.add(batch)
        return len(self._buf)

    def sample(self, n: int):
        if len(self._buf) < n:
            return None
        return self._buf.sample(n)

    def update_priorities(self, idx, td):
        self._buf.update_priorities(idx, td)

    def size(self) -> int:
        return len(self._buf)


class ApexDQNConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.num_env_runners = 4
        self.prioritized_replay = True
        self.epsilon_base: float = 0.4
        self.epsilon_exponent: float = 7.0
        self.num_updates_per_iteration = 64

    def build(self) -> "ApexDQN":
        return ApexDQN(self.copy())


class ApexDQN(DQN):
    def _build_learner(self, policy):
        import ray_tpu

        c = self.config
        self._env_steps = 0
        self._last_target_sync = 0
        self.replay = ray_tpu.remote(_ReplayActor).remote(
            c.buffer_size, c.prioritized_replay_alpha,
            c.prioritized_replay_beta, c.seed,
        )
        # Fixed exploration ladder, set once (no decay schedule).
        n = max(1, len(getattr(self, "runners", [])) or
                c.num_env_runners)
        self._ladder = [
            c.epsilon_base ** (
                1.0 + c.epsilon_exponent * i / max(1, n - 1)
            )
            for i in range(n)
        ]
        self._sample_futs: Dict[Any, int] = {}
        return DQNLearner(policy, c.lr, c.double_q)

    def _arm(self, i: int):
        self._sample_futs[self.runners[i].sample.remote()] = i

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        if not self._sample_futs:
            ray_tpu.get([
                r.set_epsilon.remote(self._ladder[i])
                for i, r in enumerate(self.runners)
            ])
            for i in range(len(self.runners)):
                self._arm(i)

        # Drain ALL landed rollouts (ASYNC: re-arm immediately), pushing
        # the n-step-folded transitions into the replay actor. wait()
        # caps the ready list at num_returns, so block for one and then
        # sweep the rest non-blockingly.
        ready, rest = ray_tpu.wait(
            list(self._sample_futs), num_returns=1, timeout=10.0
        )
        if rest:
            more, _ = ray_tpu.wait(
                rest, num_returns=len(rest), timeout=0
            )
            ready = list(ready) + list(more)
        add_futs = []
        for ref in ready:
            i = self._sample_futs.pop(ref)
            batch = ray_tpu.get(ref)
            self._env_steps += batch.count
            add_futs.append(self.replay.add.remote(
                nstep_returns(batch, c.n_step, c.gamma)
            ))
            self._arm(i)
        if add_futs:
            ray_tpu.get(add_futs)

        stats: Dict[str, Any] = {}
        num_updates = 0
        buffer_size = ray_tpu.get(self.replay.size.remote())
        if buffer_size >= c.num_steps_sampled_before_learning_starts:
            # Pipeline: keep one sample request in flight while the
            # learner steps on the previous minibatch.
            pending = self.replay.sample.remote(c.minibatch_size)
            for _ in range(c.num_updates_per_iteration):
                mb = ray_tpu.get(pending)
                pending = self.replay.sample.remote(c.minibatch_size)
                if mb is None:
                    break
                out = self.learner.update(mb)
                stats["loss"] = out["loss"]
                self.replay.update_priorities.remote(
                    mb["batch_indexes"], out["td_error"]
                )
                num_updates += 1
            if (self._env_steps - self._last_target_sync
                    >= c.target_network_update_freq):
                self.learner.sync_target()
                self._last_target_sync = self._env_steps
            weights = self.learner.get_weights()
            for r in self.runners:
                r.set_weights.remote(weights)  # async broadcast

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "num_learner_updates": num_updates,
            "buffer_size": buffer_size,
            **stats,
        }

    def stop(self):
        import ray_tpu

        super().stop()
        try:
            ray_tpu.kill(self.replay)
        except Exception:
            pass
