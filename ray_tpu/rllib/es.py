"""ES: evolution strategies (gradient-free, massively parallel).

Ref analogue: rllib/algorithms/es (Salimans 2017 "Evolution Strategies
as a Scalable Alternative to RL"). The driver holds a flat parameter
vector theta; each iteration samples antithetic Gaussian perturbation
pairs, fans their EPISODE evaluations out to CPU actors, and applies
the score-function estimate
    g = 1/(n*sigma) * sum_i rank(F_i) * eps_i
with centered-rank normalization. The classic shared-noise-table trick
becomes seed shipping: actors receive (seed, sigma) and regenerate
eps = randn(seed) locally, so the wire carries ints, not parameter
vectors — the same bandwidth shape the reference's SharedNoiseTable
achieves (rllib/algorithms/es/es.py noise table + rollout workers).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from .algorithm import AlgorithmConfig
from .policy import init_mlp_params


def flatten_params(tree) -> Tuple[np.ndarray, list]:
    """Nested {name: [(W, b), ...]} -> (flat float64 vector, spec)."""
    flat, spec = [], []
    for name in sorted(tree):
        for i, (W, b) in enumerate(tree[name]):
            spec.append((name, i, W.shape, b.shape))
            flat.append(W.ravel())
            flat.append(b.ravel())
    return np.concatenate(flat).astype(np.float64), spec


def unflatten_params(vec: np.ndarray, spec: list):
    tree: Dict[str, list] = {}
    off = 0
    for name, i, wshape, bshape in spec:
        wn = int(np.prod(wshape))
        bn = int(np.prod(bshape))
        W = vec[off:off + wn].reshape(wshape).astype(np.float32)
        b = vec[off + wn:off + wn + bn].reshape(bshape).astype(
            np.float32)
        off += wn + bn
        tree.setdefault(name, []).append((W, b))
    return tree


class DeterministicDiscretePolicy:
    """argmax-logits MLP policy — ES/ARS evaluate deterministic
    behavior; exploration comes from parameter-space noise."""

    def __init__(self, obs_dim: int, num_actions: int, hidden: int = 32,
                 seed: int = 0):
        rng = np.random.RandomState(seed)
        self.weights = {
            "trunk": init_mlp_params(rng, [obs_dim, hidden]),
            "pi": init_mlp_params(rng, [hidden, num_actions]),
        }

    def set_weights(self, weights):
        self.weights = weights

    def get_weights(self):
        return self.weights

    def compute_action(self, obs, rng):
        h = np.asarray(obs, np.float32).reshape(-1)
        for W, b in self.weights["trunk"]:
            h = np.tanh(h @ W + b)
        (W, b), = self.weights["pi"]
        return int(np.argmax(h @ W + b)), 0.0, 0.0


class EpisodeEvaluator:
    """CPU actor: evaluates parameter perturbations by full episode.
    Receives the base theta once per iteration; perturbations arrive as
    noise SEEDS and are regenerated locally (antithetic +/- pairs)."""

    def __init__(self, env_creator: Callable[[], Any], policy_factory,
                 spec_blob: bytes, seed: int = 0,
                 episode_horizon: int = 1000):
        import pickle

        self.env = env_creator()
        self.policy = policy_factory()
        self.spec = pickle.loads(spec_blob)
        self.horizon = episode_horizon
        self.rng = np.random.RandomState(seed)
        self._theta = None

    def set_theta(self, theta: np.ndarray):
        self._theta = np.asarray(theta, np.float64)

    def _rollout(self, vec: np.ndarray) -> float:
        self.policy.set_weights(unflatten_params(vec, self.spec))
        obs, _ = self.env.reset(
            seed=int(self.rng.randint(2 ** 31 - 1))
        )
        total = 0.0
        for _ in range(self.horizon):
            action, _, _ = self.policy.compute_action(obs, self.rng)
            obs, reward, terminated, truncated, _ = self.env.step(action)
            total += float(reward)
            if terminated or truncated:
                break
        return total

    def evaluate_pairs(self, seeds: List[int], sigma: float
                       ) -> List[Tuple[int, float, float]]:
        """[(seed, F(theta + sigma*eps), F(theta - sigma*eps))]."""
        out = []
        for s in seeds:
            eps = np.random.RandomState(s).randn(len(self._theta))
            out.append((
                s,
                self._rollout(self._theta + sigma * eps),
                self._rollout(self._theta - sigma * eps),
            ))
        return out

    def evaluate_theta(self) -> float:
        return self._rollout(self._theta)


def centered_ranks(x: np.ndarray) -> np.ndarray:
    """Map scores to [-0.5, 0.5] by rank (Salimans 2017 fitness
    shaping)."""
    ranks = np.empty(len(x), dtype=np.float64)
    ranks[np.argsort(x)] = np.arange(len(x))
    return ranks / (len(x) - 1) - 0.5 if len(x) > 1 else np.zeros(1)


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.num_env_runners = 2
        self.episodes_per_batch: int = 16   # antithetic PAIRS / iter
        self.sigma: float = 0.1             # perturbation stddev
        self.step_size: float = 0.05        # SGD step on the estimate
        self.l2_coeff: float = 0.005
        self.episode_horizon: int = 1000
        self.hidden_size = 32

    def build(self) -> "ES":
        return ES(self.copy())


class _EvolutionBase:
    """Shared driver shape for ES and ARS: flat theta + evaluator
    actors + seed fan-out; subclasses implement _apply_update."""

    def __init__(self, config):
        import pickle

        import ray_tpu

        self.config = config
        self.iteration = 0
        c = config
        creator = c.env_creator()
        probe = creator()
        obs_dim = int(np.prod(probe.observation_space.shape))
        if not hasattr(probe.action_space, "n"):
            raise ValueError(
                f"{type(self).__name__} here supports discrete action "
                f"spaces (parameter-space search over argmax policies)"
            )
        num_actions = int(probe.action_space.n)
        if hasattr(probe, "close"):
            probe.close()

        def policy_factory(obs_dim=obs_dim, num_actions=num_actions,
                           hidden=c.hidden_size, seed=c.seed):
            return DeterministicDiscretePolicy(
                obs_dim, num_actions, hidden, seed
            )

        self.theta, self.spec = flatten_params(
            policy_factory().get_weights()
        )
        spec_blob = pickle.dumps(self.spec)
        evaluator_cls = ray_tpu.remote(EpisodeEvaluator)
        self.evaluators = [
            evaluator_cls.remote(
                creator, policy_factory, spec_blob,
                seed=c.seed + 1000 * (i + 1),
                episode_horizon=c.episode_horizon,
            )
            for i in range(c.num_env_runners)
        ]
        self._seed_rng = np.random.RandomState(c.seed)
        self._episodes = 0

    def _evaluate_batch(self, num_pairs: int, sigma: float):
        """Fan seed chunks over evaluators; returns (seeds, F+, F-)."""
        import ray_tpu

        seeds = self._seed_rng.randint(
            2 ** 31 - 1, size=num_pairs
        ).tolist()
        chunks = np.array_split(seeds, len(self.evaluators))
        ray_tpu.get([e.set_theta.remote(self.theta)
                     for e in self.evaluators])
        results = ray_tpu.get([
            e.evaluate_pairs.remote([int(s) for s in chunk], sigma)
            for e, chunk in zip(self.evaluators, chunks)
            if len(chunk)
        ])
        triples = [t for chunk in results for t in chunk]
        self._episodes += 2 * len(triples)
        s = [t[0] for t in triples]
        fp = np.asarray([t[1] for t in triples])
        fn = np.asarray([t[2] for t in triples])
        return s, fp, fn

    def _noise(self, seed: int) -> np.ndarray:
        return np.random.RandomState(seed).randn(len(self.theta))

    def _apply_update(self, seeds, f_pos, f_neg):
        raise NotImplementedError

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        self.iteration += 1
        c = self.config
        seeds, f_pos, f_neg = self._evaluate_batch(
            c.episodes_per_batch, c.sigma
        )
        self._apply_update(seeds, f_pos, f_neg)
        # Evaluate the CURRENT (unperturbed) theta on one evaluator.
        ray_tpu.get(
            [self.evaluators[0].set_theta.remote(self.theta)]
        )
        cur = float(ray_tpu.get(
            self.evaluators[0].evaluate_theta.remote()
        ))
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": cur,
            "perturbed_reward_mean": float(
                np.mean(np.concatenate([f_pos, f_neg]))
            ),
            "episodes_total": self._episodes,
            "theta_norm": float(np.linalg.norm(self.theta)),
        }

    def get_weights(self):
        return unflatten_params(self.theta, self.spec)

    def get_policy(self):
        c = self.config
        policy = DeterministicDiscretePolicy(1, 1)  # shapes from spec
        policy.set_weights(self.get_weights())
        return policy

    def stop(self):
        import ray_tpu

        for e in self.evaluators:
            try:
                ray_tpu.kill(e)
            except Exception:
                pass


class ES(_EvolutionBase):
    def _apply_update(self, seeds, f_pos, f_neg):
        c = self.config
        # Centered-rank shaping over the 2n returns, folded back to the
        # antithetic difference per pair.
        shaped = centered_ranks(np.concatenate([f_pos, f_neg]))
        n = len(seeds)
        diff = shaped[:n] - shaped[n:]
        g = np.zeros_like(self.theta)
        for s, d in zip(seeds, diff):
            g += d * self._noise(s)
        g /= 2 * n * c.sigma
        self.theta = (
            (1.0 - c.l2_coeff * c.step_size) * self.theta
            + c.step_size * g
        )
