"""EnvRunner: environment stepping on CPU actors.

Ref analogue: rllib/env/single_agent_env_runner.py (new stack) /
evaluation/rollout_worker.py RolloutWorker (:159, sample:653). Runs as a
CPU actor; receives policy weights, steps a gymnasium env, returns
SampleBatches. The TPU-side Learner never touches the env (SURVEY.md §3.6:
env stepping is the CPU hot loop; learning is the TPU hot loop).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from .sample_batch import (
    ACTIONS,
    BOOTSTRAP_OBS,
    DONES,
    LOGPS,
    OBS,
    REWARDS,
    SampleBatch,
    VALUES,
    compute_gae,
)


class EnvRunner:
    def __init__(self, env_creator: Callable[[], Any], policy_factory,
                 seed: int = 0, rollout_fragment_length: int = 200,
                 gamma: float = 0.99, lam: float = 0.95):
        self.env = env_creator()
        self.policy = policy_factory()
        self.rng = np.random.RandomState(seed)
        self.fragment = rollout_fragment_length
        self.gamma = gamma
        self.lam = lam
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_reward = 0.0
        self._episode_len = 0
        self._episode_rewards = []

    def set_weights(self, weights):
        self.policy.set_weights(weights)

    def sample(self) -> SampleBatch:
        obs_l, act_l, rew_l, done_l, logp_l, val_l = [], [], [], [], [], []
        for _ in range(self.fragment):
            action, logp, value = self.policy.compute_action(
                np.asarray(self._obs, dtype=np.float32), self.rng
            )
            nxt, reward, terminated, truncated, _ = self.env.step(action)
            done = bool(terminated or truncated)
            obs_l.append(np.asarray(self._obs, dtype=np.float32))
            act_l.append(action)
            rew_l.append(float(reward))
            done_l.append(done)
            logp_l.append(float(logp))
            val_l.append(float(value))
            self._episode_reward += float(reward)
            self._episode_len += 1
            if done:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._episode_len = 0
                self._obs, _ = self.env.reset()
            else:
                self._obs = nxt
        last_value = 0.0
        if not done_l[-1]:
            _, _, last_value = self.policy.compute_action(
                np.asarray(self._obs, dtype=np.float32), self.rng
            )
        batch = SampleBatch({
            OBS: np.stack(obs_l),
            ACTIONS: np.asarray(act_l),
            REWARDS: np.asarray(rew_l, dtype=np.float32),
            DONES: np.asarray(done_l),
            LOGPS: np.asarray(logp_l, dtype=np.float32),
            VALUES: np.asarray(val_l, dtype=np.float32),
            # Post-fragment observation for the learner's value bootstrap
            # (if the fragment ended on done, V(s_{T+1}) is masked by
            # (1-done) anyway, so the reset obs here is harmless).
            BOOTSTRAP_OBS: np.asarray(self._obs, dtype=np.float32),
        })
        batch.update(compute_gae(
            batch[REWARDS], batch[VALUES], batch[DONES], float(last_value),
            gamma=self.gamma, lam=self.lam,
        ))
        return batch

    def episode_stats(self) -> Dict[str, float]:
        recent = self._episode_rewards[-20:]
        out = {
            "episodes_total": len(self._episode_rewards),
            "episode_reward_mean": float(np.mean(recent)) if recent else 0.0,
        }
        return out


NEXT_OBS = "next_obs"
BOUNDARY = "boundary"  # episode ended here (terminated OR truncated)


class TransitionEnvRunner(EnvRunner):
    """Off-policy variant: collects raw (s, a, r, s', done) transitions for
    a replay buffer instead of GAE-postprocessed fragments (ref analogue:
    the rollout path feeding EpisodeReplayBuffer in the DQN stack)."""

    def set_epsilon(self, epsilon: float):
        self.policy.set_epsilon(epsilon)

    def set_exploration_noise(self, noise: float):
        """Gaussian-noise scale for deterministic policies (the Ape-X
        DDPG ladder)."""
        self.policy.exploration_noise = float(noise)

    def sample(self) -> SampleBatch:
        obs_l, act_l, rew_l, done_l, next_l, bound_l = \
            [], [], [], [], [], []
        for _ in range(self.fragment):
            action, _, _ = self.policy.compute_action(
                np.asarray(self._obs, dtype=np.float32), self.rng
            )
            nxt, reward, terminated, truncated, _ = self.env.step(action)
            done = bool(terminated or truncated)
            obs_l.append(np.asarray(self._obs, dtype=np.float32).reshape(-1))
            act_l.append(action)
            rew_l.append(float(reward))
            # Bootstrapping must stop at TERMINATION but not truncation
            # (time limits are not environment death); multi-step
            # lookaheads must stop at BOTH (the env resets either way).
            done_l.append(bool(terminated))
            bound_l.append(done)
            next_l.append(np.asarray(nxt, dtype=np.float32).reshape(-1))
            self._episode_reward += float(reward)
            self._episode_len += 1
            if done:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._episode_len = 0
                self._obs, _ = self.env.reset()
            else:
                self._obs = nxt
        return SampleBatch({
            OBS: np.stack(obs_l),
            ACTIONS: np.asarray(act_l),
            REWARDS: np.asarray(rew_l, dtype=np.float32),
            DONES: np.asarray(done_l),
            BOUNDARY: np.asarray(bound_l),
            NEXT_OBS: np.stack(next_l),
        })
