"""QMIX: cooperative multi-agent Q-learning with monotonic value mixing.

Ref analogue: rllib/algorithms/qmix (Rashid 2018). Agents share one
utility network Q_a(o_a, .) (parameter sharing, the reference default);
a MIXING network combines the chosen per-agent utilities into Q_tot
conditioned on the global state, with monotonicity enforced by
abs()-constrained hypernetwork weights — so per-agent argmax equals
team argmax (the IGM condition) and execution stays decentralized.
TD target: y = r_team + gamma (1-d) Q_tot'(s', argmax_a Q_a'(o'_a, .)).

Env protocol: the dict multi-agent convention of multi_agent.py, with
every agent present each step (QMIX assumes a fixed team); the global
state is the concatenation of agent observations in sorted-agent
order.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import AlgorithmConfig
from .core import Learner
from .policy import QPolicy, init_mlp_params
from .replay_buffers import ReplayBuffer
from .sample_batch import SampleBatch


class QMIXConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size: int = 50_000
        self.num_steps_sampled_before_learning_starts: int = 500
        self.target_network_update_freq: int = 500
        self.num_updates_per_iteration: int = 32
        self.mixing_embed_dim: int = 16
        self.epsilon_initial: float = 1.0
        self.epsilon_final: float = 0.05
        self.epsilon_timesteps: int = 8_000
        # agent ids (sorted) + per-agent spaces; probed from the env
        self.obs_dim: int = 0
        self.num_actions: int = 0

    def build(self) -> "QMIX":
        return QMIX(self.copy())


class QMIXLearner(Learner):
    """params: {agent: {trunk, q}, mix: hypernet linears}. The whole
    tree polyaks hard-sync style via sync_target() (QMIX uses periodic
    target copies like DQN, not soft polyak)."""

    def __init__(self, agent_params, *, n_agents: int, obs_dim: int,
                 num_actions: int, state_dim: int, embed: int,
                 lr: float, gamma: float, seed: int):
        rng = np.random.RandomState(seed + 7)
        params = {
            "agent": agent_params,
            "mix": {
                "hw1": init_mlp_params(rng,
                                       [state_dim, n_agents * embed]),
                "hb1": init_mlp_params(rng, [state_dim, embed]),
                "hw2": init_mlp_params(rng, [state_dim, embed]),
                "hb2": init_mlp_params(rng, [state_dim, embed, 1]),
            },
        }
        super().__init__(params, lr=lr)
        import jax

        self._gamma = gamma
        self._shape = (n_agents, embed, num_actions)
        self._target_full = jax.tree.map(lambda x: x, self._params)

    @staticmethod
    def agent_q(agent, obs):
        """Q_a for stacked per-agent obs [B, A, obs_dim] -> [B, A, n]."""
        import jax.numpy as jnp

        h = obs
        for W, b in agent["trunk"]:
            h = jnp.tanh(h @ W + b)
        (Wq, bq), = agent["q"]
        return h @ Wq + bq

    def _mix(self, mix, state, qa):
        """Monotonic mixing: qa [B, A] + state [B, S] -> Q_tot [B]."""
        import jax
        import jax.numpy as jnp

        A, H, _ = self._shape
        (W1, c1), = mix["hw1"]
        (Wb1, cb1), = mix["hb1"]
        (W2, c2), = mix["hw2"]
        w1 = jnp.abs(state @ W1 + c1).reshape(-1, A, H)
        b1 = (state @ Wb1 + cb1)[:, None, :]
        hidden = jax.nn.elu(qa[:, None, :] @ w1 + b1)   # [B, 1, H]
        w2 = jnp.abs(state @ W2 + c2)[:, :, None]       # [B, H, 1]
        # b2: 2-layer state-conditioned scalar (Rashid 2018 eq. 6).
        (Wv1, cv1), (Wv2, cv2) = mix["hb2"]
        b2 = jnp.tanh(state @ Wv1 + cv1) @ Wv2 + cv2
        return (hidden @ w2)[:, 0, 0] + b2[:, 0]

    def compute_loss(self, params, target, batch):
        import jax
        import jax.numpy as jnp

        tgt = batch["_target"]
        qa_all = self.agent_q(params["agent"], batch["obs"])
        q_chosen = jnp.take_along_axis(
            qa_all, batch["actions"][..., None], axis=-1
        )[..., 0]                                        # [B, A]
        q_tot = self._mix(params["mix"], batch["state"], q_chosen)

        # Target: per-agent greedy utilities mixed by the target net.
        qa_next = self.agent_q(tgt["agent"], batch["next_obs"])
        q_next = qa_next.max(axis=-1)                    # [B, A]
        tq_tot = self._mix(tgt["mix"], batch["next_state"], q_next)
        y = jax.lax.stop_gradient(
            batch["rew"] + self._gamma * (1.0 - batch["done"]) * tq_tot
        )
        td = q_tot - y
        loss = (td * td).mean()
        return loss, {"td_loss": loss, "q_tot_mean": q_tot.mean()}

    def update_qmix(self, np_batch) -> Dict[str, Any]:
        """Passes the hard-synced target TREE through the batch pytree
        (the base update_device asarray's every value, which a nested
        tree would break; jit treats it as more traced leaves — no
        retrace when the copy refreshes)."""
        import jax.numpy as jnp

        if self._jit_update is None:
            self._build()
        jb = {k: jnp.asarray(v) for k, v in np_batch.items()}
        jb["_target"] = self._target_full
        self._params, self._opt_state, self._target, stats = (
            self._jit_update(
                self._params, self._opt_state, self._target, jb
            )
        )
        self.num_updates += 1
        return stats

    def sync_target(self):
        import jax

        self._target_full = jax.tree.map(lambda x: x, self._params)

    def agent_weights(self):
        """Per-agent utility net weights for the rollout QPolicies."""
        import jax

        return jax.tree.map(np.asarray, self._params["agent"])


class _QMIXEnvRunner:
    """CPU actor: steps the dict env with shared epsilon-greedy agent
    policies; emits joint transitions (obs/actions stacked over the
    sorted agent axis, team reward summed)."""

    def __init__(self, env_creator, policy_factory, agent_ids,
                 seed: int = 0, rollout_fragment_length: int = 200,
                 **_):
        self.env = env_creator()
        self.policy = policy_factory()   # ONE shared utility net
        self.agent_ids = list(agent_ids)
        self.rng = np.random.RandomState(seed)
        self.fragment = rollout_fragment_length
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_reward = 0.0
        self._episode_rewards: List[float] = []

    def set_weights(self, weights):
        self.policy.set_weights(weights)

    def set_epsilon(self, eps: float):
        self.policy.set_epsilon(eps)

    def _stack(self, obs_dict):
        return np.stack([
            np.asarray(obs_dict[a], np.float32).reshape(-1)
            for a in self.agent_ids
        ])

    def sample(self) -> SampleBatch:
        obs_l, act_l, rew_l, done_l, next_l = [], [], [], [], []
        for _ in range(self.fragment):
            joint = self._stack(self._obs)
            actions = {
                a: self.policy.compute_action(joint[i], self.rng)[0]
                for i, a in enumerate(self.agent_ids)
            }
            nxt, rew, term, trunc, _ = self.env.step(actions)
            done = bool(term.get("__all__")) or bool(
                trunc.get("__all__")
            )
            team_r = float(sum(rew.values()))
            obs_l.append(joint)
            act_l.append([actions[a] for a in self.agent_ids])
            rew_l.append(team_r)
            done_l.append(bool(term.get("__all__")))
            next_l.append(self._stack(nxt))
            self._episode_reward += team_r
            if done:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = nxt
        return SampleBatch({
            "obs": np.stack(obs_l),          # [T, A, obs_dim]
            "actions": np.asarray(act_l, np.int32),
            "rew": np.asarray(rew_l, np.float32),
            "done": np.asarray(done_l, np.float32),
            "next_obs": np.stack(next_l),
        })

    def episode_stats(self) -> Dict[str, float]:
        recent = self._episode_rewards[-20:]
        return {
            "episodes_total": len(self._episode_rewards),
            "episode_reward_mean": float(np.mean(recent))
            if recent else 0.0,
        }


class QMIX:
    def __init__(self, config: QMIXConfig):
        import ray_tpu

        self.config = config
        self.iteration = 0
        c = config
        creator = c.env_creator()
        probe = creator()
        obs0, _ = probe.reset(seed=0)
        self.agent_ids = sorted(obs0.keys())
        n_agents = len(self.agent_ids)
        obs_dim = c.obs_dim or int(
            np.prod(np.asarray(obs0[self.agent_ids[0]]).shape)
        )
        if not c.num_actions:
            raise ValueError("QMIXConfig.training(num_actions=...) "
                             "required")
        if hasattr(probe, "close"):
            probe.close()
        self._n_agents, self._obs_dim = n_agents, obs_dim
        state_dim = n_agents * obs_dim

        def policy_factory(obs_dim=obs_dim, n=c.num_actions,
                           hidden=c.hidden_size, seed=c.seed):
            return QPolicy(obs_dim, n, hidden, seed)

        runner_cls = ray_tpu.remote(_QMIXEnvRunner)
        self.runners = [
            runner_cls.remote(
                creator, policy_factory, self.agent_ids,
                seed=c.seed + i,
                rollout_fragment_length=c.rollout_fragment_length,
            )
            for i in range(c.num_env_runners)
        ]
        self.learner = QMIXLearner(
            policy_factory().get_weights(),
            n_agents=n_agents, obs_dim=obs_dim,
            num_actions=c.num_actions, state_dim=state_dim,
            embed=c.mixing_embed_dim, lr=c.lr, gamma=c.gamma,
            seed=c.seed,
        )
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._env_steps = 0
        self._last_target_sync = 0

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._env_steps / max(1, c.epsilon_timesteps))
        return c.epsilon_initial + frac * (
            c.epsilon_final - c.epsilon_initial
        )

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        self.iteration += 1
        c = self.config
        eps = self._epsilon()
        ray_tpu.get([r.set_epsilon.remote(eps) for r in self.runners])
        batches = ray_tpu.get([r.sample.remote() for r in self.runners])
        for b in batches:
            self.buffer.add(b)
            self._env_steps += b.count

        stats: Dict[str, Any] = {}
        num_updates = 0
        if self._env_steps >= c.num_steps_sampled_before_learning_starts:
            for _ in range(c.num_updates_per_iteration):
                mb = self.buffer.sample(c.minibatch_size)
                obs = np.asarray(mb["obs"], np.float32)
                nxt = np.asarray(mb["next_obs"], np.float32)
                stats = self.learner.update_qmix({
                    "obs": obs,
                    "state": obs.reshape(len(obs), -1),
                    "actions": np.asarray(mb["actions"], np.int32),
                    "rew": np.asarray(mb["rew"], np.float32),
                    "done": np.asarray(mb["done"], np.float32),
                    "next_obs": nxt,
                    "next_state": nxt.reshape(len(nxt), -1),
                })
                num_updates += 1
            stats = {k: float(v) for k, v in stats.items()}
            if (self._env_steps - self._last_target_sync
                    >= c.target_network_update_freq):
                self.learner.sync_target()
                self._last_target_sync = self._env_steps
            weights = self.learner.agent_weights()
            ray_tpu.get(
                [r.set_weights.remote(weights) for r in self.runners]
            )

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "num_learner_updates": num_updates,
            "epsilon": eps,
            **stats,
        }

    def get_weights(self):
        return self.learner.agent_weights()

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
