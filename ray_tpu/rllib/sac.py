"""SAC: off-policy maximum-entropy actor-critic for continuous control.

Ref analogue: rllib/algorithms/sac/ (sac.py + sac_torch_policy.py) —
twin Q networks with polyak-averaged targets, a tanh-squashed Gaussian
actor, and automatic temperature tuning against a target entropy
(Haarnoja 2018). Sampling stays on CPU EnvRunner actors; the learner is
one fused jax update (both critics, the actor, and alpha in a single
jitted step on the accelerator).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .env_runner import NEXT_OBS, TransitionEnvRunner
from .replay_buffers import ReplayBuffer
from .sample_batch import ACTIONS, DONES, OBS, REWARDS, SampleBatch

_LOG_STD_MIN, _LOG_STD_MAX = -5.0, 2.0


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.buffer_size: int = 100_000
        self.num_steps_sampled_before_learning_starts: int = 500
        self.num_updates_per_iteration: int = 64
        self.tau: float = 0.005          # polyak coefficient
        self.initial_alpha: float = 0.2
        self.target_entropy: float | None = None  # default: -act_dim

    def build(self) -> "SAC":
        return SAC(self.copy())


def _mlp_init(rng, sizes):
    import jax
    import jax.numpy as jnp

    from .policy import init_mlp_params

    return jax.tree.map(jnp.asarray, init_mlp_params(rng, sizes))


class SACLearner:
    """One jitted step: critic TD update against the entropy-regularized
    target, actor update through the reparameterized sample, temperature
    update toward the target entropy, polyak target sync."""

    def __init__(self, policy, cfg, obs_dim: int, act_dim: int,
                 low: np.ndarray, high: np.ndarray):
        import jax
        import jax.numpy as jnp
        import optax

        hidden = cfg.hidden_size
        rng = np.random.RandomState(cfg.seed + 1)
        self._low = jnp.asarray(low)
        self._high = jnp.asarray(high)
        target_entropy = (cfg.target_entropy
                          if cfg.target_entropy is not None
                          else -float(act_dim))

        def make_q():
            return {"trunk": _mlp_init(rng, [obs_dim + act_dim,
                                             hidden, hidden]),
                    "q": _mlp_init(rng, [hidden, 1])}

        actor = jax.tree.map(jnp.asarray, policy.get_weights())
        self._params = {
            "actor": actor,
            "q1": make_q(),
            "q2": make_q(),
            "log_alpha": jnp.asarray(
                np.log(cfg.initial_alpha), dtype=jnp.float32
            ),
        }
        # jnp leaves are immutable; sharing them is a correct "copy".
        self._target = {"q1": self._params["q1"],
                        "q2": self._params["q2"]}
        self._tx = optax.adam(cfg.lr)
        self._opt_state = self._tx.init(self._params)
        tau = cfg.tau
        gamma = cfg.gamma

        def mlp(params, x):
            for W, b in params:
                x = jnp.tanh(x @ W + b)
            return x

        def q_val(qp, obs, act):
            h = mlp(qp["trunk"], jnp.concatenate([obs, act], axis=-1))
            (W, b), = qp["q"]
            return (h @ W + b)[..., 0]

        def actor_sample(ap, obs, eps):
            h = mlp(ap["trunk"], obs)
            (Wm, bm), = ap["mu"]
            (Ws, bs), = ap["log_std"]
            mu = h @ Wm + bm
            log_std = jnp.clip(h @ Ws + bs, _LOG_STD_MIN, _LOG_STD_MAX)
            std = jnp.exp(log_std)
            pre = mu + std * eps
            u = jnp.tanh(pre)
            # Gaussian logp + tanh change-of-variables correction.
            logp = (
                -0.5 * (((pre - mu) / std) ** 2
                        + 2 * log_std + np.log(2 * np.pi))
            ).sum(-1)
            logp -= (2 * (np.log(2.0) - pre
                          - jax.nn.softplus(-2 * pre))).sum(-1)
            return u, logp

        def from_env(a):
            u = (a - self._low) / (self._high - self._low) * 2.0 - 1.0
            return jnp.clip(u, -0.999, 0.999)

        def losses(params, target, obs, act_env, rew, done, nxt,
                   eps1, eps2):
            alpha = jnp.exp(params["log_alpha"])
            act = from_env(act_env)
            # Critic target: r + gamma (min target Q - alpha logp).
            u2, logp2 = actor_sample(params["actor"], nxt, eps2)
            tq = jnp.minimum(
                q_val(target["q1"], nxt, u2),
                q_val(target["q2"], nxt, u2),
            ) - jax.lax.stop_gradient(alpha) * logp2
            backup = jax.lax.stop_gradient(
                rew + gamma * (1.0 - done) * tq
            )
            q1 = q_val(params["q1"], obs, act)
            q2 = q_val(params["q2"], obs, act)
            critic_loss = ((q1 - backup) ** 2 + (q2 - backup) ** 2).mean()
            # Actor: maximize min Q of the reparameterized action.
            u, logp = actor_sample(params["actor"], obs, eps1)
            q_pi = jnp.minimum(
                q_val(jax.lax.stop_gradient(params["q1"]), obs, u),
                q_val(jax.lax.stop_gradient(params["q2"]), obs, u),
            )
            actor_loss = (jax.lax.stop_gradient(alpha) * logp
                          - q_pi).mean()
            # Temperature toward the target entropy.
            alpha_loss = -(params["log_alpha"] * jax.lax.stop_gradient(
                logp + target_entropy
            )).mean()
            total = critic_loss + actor_loss + alpha_loss
            return total, (critic_loss, actor_loss, alpha)

        def update(params, opt_state, target, obs, act, rew, done, nxt,
                   eps1, eps2):
            (loss, aux), grads = jax.value_and_grad(
                losses, has_aux=True
            )(params, target, obs, act, rew, done, nxt, eps1, eps2)
            updates, opt_state = self._tx.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            target = jax.tree.map(
                lambda t, p: (1 - tau) * t + tau * p,
                target, {"q1": params["q1"], "q2": params["q2"]},
            )
            return params, opt_state, target, loss, aux

        self._update = jax.jit(update)
        self._rng = np.random.RandomState(cfg.seed + 2)
        self._act_dim = act_dim

    def update(self, batch: SampleBatch) -> Dict[str, Any]:
        import jax.numpy as jnp

        n = batch.count
        eps1 = jnp.asarray(
            self._rng.randn(n, self._act_dim).astype(np.float32))
        eps2 = jnp.asarray(
            self._rng.randn(n, self._act_dim).astype(np.float32))
        (self._params, self._opt_state, self._target, loss,
         (critic_loss, actor_loss, alpha)) = self._update(
            self._params, self._opt_state, self._target,
            jnp.asarray(batch[OBS]),
            jnp.asarray(batch[ACTIONS], dtype=jnp.float32),
            jnp.asarray(batch[REWARDS]),
            jnp.asarray(batch[DONES], dtype=jnp.float32),
            jnp.asarray(batch[NEXT_OBS]),
            eps1, eps2,
        )
        return {
            "loss": float(loss),
            "critic_loss": float(critic_loss),
            "actor_loss": float(actor_loss),
            "alpha": float(alpha),
        }

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self._params["actor"])


class _SACEnvRunner(TransitionEnvRunner):
    """Transition collection with a stochastic policy (no epsilon)."""


class SAC(Algorithm):
    def _make_policy_factory(self, obs_dim: int, act_dim: int):
        from .policy import SquashedGaussianPolicy

        if not getattr(self, "_continuous", False):
            raise ValueError(
                "SAC supports Box (continuous) action spaces only; use "
                "PPO/DQN/IMPALA for discrete envs"
            )
        config = self.config
        low, high = self._action_low, self._action_high

        def policy_factory(obs_dim=obs_dim, act_dim=act_dim,
                           hidden=config.hidden_size, seed=config.seed):
            return SquashedGaussianPolicy(
                obs_dim, act_dim, low, high, hidden, seed
            )

        return policy_factory

    def _runner_class(self):
        return _SACEnvRunner

    def _build_learner(self, policy):
        c = self.config
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._env_steps = 0
        return SACLearner(policy, c, self._obs_dim, self._num_actions,
                          self._action_low, self._action_high)

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        batches: List[SampleBatch] = ray_tpu.get(
            [r.sample.remote() for r in self.runners]
        )
        for b in batches:
            self.buffer.add(b)
            self._env_steps += b.count

        stats: Dict[str, Any] = {}
        num_updates = 0
        if self._env_steps >= c.num_steps_sampled_before_learning_starts:
            for _ in range(c.num_updates_per_iteration):
                mb = self.buffer.sample(c.minibatch_size)
                stats = self.learner.update(mb)
                num_updates += 1
            weights = self.learner.get_weights()
            ray_tpu.get(
                [r.set_weights.remote(weights) for r in self.runners]
            )

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "num_learner_updates": num_updates,
            "buffer_size": len(self.buffer),
            **stats,
        }
