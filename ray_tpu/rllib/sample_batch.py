"""SampleBatch: columnar rollout storage.

Ref analogue: rllib/policy/sample_batch.py SampleBatch — a dict of aligned
arrays with standard column names.
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np

OBS = "obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
NEXT_OBS = "next_obs"
# Observation AFTER the fragment's last transition (for value
# bootstrapping at fragment boundaries). Scalar row, not per-timestep.
BOOTSTRAP_OBS = "bootstrap_obs"
LOGPS = "action_logp"
VALUES = "values"
ADVANTAGES = "advantages"
RETURNS = "returns"

# Columns carrying ONE row per fragment rather than one per timestep.
_PER_FRAGMENT_KEYS = frozenset({BOOTSTRAP_OBS})


class SampleBatch(dict):
    @property
    def count(self) -> int:
        if OBS in self:
            return len(self[OBS])
        for v in self.values():
            return len(v)
        return 0

    def _aligned_keys(self) -> List[str]:
        # Per-fragment metadata (one row per fragment, not time-aligned)
        # only makes sense on an un-merged fragment and is dropped by
        # concat/shuffle/minibatches. Named explicitly — a length
        # heuristic would misfire whenever obs_dim == fragment length.
        return [k for k in self if k not in _PER_FRAGMENT_KEYS]

    @staticmethod
    def concat(batches: List["SampleBatch"]) -> "SampleBatch":
        keys = batches[0]._aligned_keys()
        return SampleBatch(
            {k: np.concatenate([np.asarray(b[k]) for b in batches]) for k in keys}
        )

    def shuffle(self, rng: np.random.RandomState) -> "SampleBatch":
        idx = rng.permutation(self.count)
        return SampleBatch(
            {k: np.asarray(self[k])[idx] for k in self._aligned_keys()}
        )

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = self.count
        keys = self._aligned_keys()
        for start in range(0, n - size + 1, size):
            yield SampleBatch(
                {k: np.asarray(self[k])[start:start + size] for k in keys}
            )


def compute_gae(
    rewards: np.ndarray,
    values: np.ndarray,
    dones: np.ndarray,
    last_value: float,
    *,
    gamma: float,
    lam: float,
) -> Dict[str, np.ndarray]:
    """Generalized advantage estimation (ref analogue:
    rllib/evaluation/postprocessing.py compute_advantages)."""
    T = len(rewards)
    adv = np.zeros(T, dtype=np.float32)
    last_gae = 0.0
    for t in reversed(range(T)):
        next_v = last_value if t == T - 1 else values[t + 1]
        nonterminal = 1.0 - float(dones[t])
        delta = rewards[t] + gamma * next_v * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
    returns = adv + values
    return {ADVANTAGES: adv, RETURNS: returns.astype(np.float32)}
