"""RLModule / Learner / LearnerGroup — the pluggable learner layer.

Ref analogue: rllib/core/rl_module/rl_module.py (network container) and
rllib/core/learner/learner.py:227 (compute_gradients:553,
apply_gradients:675, update:1227) + learner_group.py:66. Algorithms stop
hand-rolling jax nets and optimizer plumbing: an RLModule declares the
parameter pytree + pure forward functions, a Learner subclass implements
``compute_loss`` and inherits the jitted
grad/clip/apply/target-polyak update, and a LearnerGroup runs the
learner locally or inside a remote actor (the learner/actor split APPO
exercises).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


# ------------------------------------------------------------------ modules

class RLModule:
    """Owns network construction + pure forward functions (jax). The
    parameter pytree is plain nested lists/dicts of arrays so the CPU
    rollout policies (policy.py) can consume the same weights."""

    def init_params(self) -> Any:
        raise NotImplementedError

    @staticmethod
    def mlp(params, x):
        import jax.numpy as jnp

        for W, b in params:
            x = jnp.tanh(x @ W + b)
        return x


class ActorCriticModule(RLModule):
    """Discrete actor-critic MLP matching policy.MLPPolicy's pytree
    (trunk/pi/vf) so learner weights broadcast straight into the numpy
    rollout policy."""

    def __init__(self, obs_dim: int, num_actions: int, hidden: int = 64,
                 seed: int = 0):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = hidden
        self.seed = seed

    def init_params(self):
        from .policy import MLPPolicy

        return MLPPolicy(
            self.obs_dim, self.num_actions, self.hidden, self.seed
        ).get_weights()

    @classmethod
    def forward(cls, params, obs):
        """(logits, value) — pure jax."""
        h = cls.mlp(params["trunk"], obs)
        (Wp, bp), = params["pi"]
        (Wv, bv), = params["vf"]
        return h @ Wp + bp, (h @ Wv + bv)[..., 0]


class DeterministicActorModule(RLModule):
    """Deterministic continuous actor (TD3-style): tanh(mu) scaled to
    the Box bounds; matches policy.DeterministicPolicy's pytree."""

    def __init__(self, obs_dim: int, act_dim: int, hidden: int = 64,
                 seed: int = 0):
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.hidden = hidden
        self.seed = seed

    def init_params(self):
        from .policy import init_mlp_params

        rng = np.random.RandomState(self.seed)
        return {
            "trunk": init_mlp_params(
                rng, [self.obs_dim, self.hidden, self.hidden]
            ),
            "mu": init_mlp_params(rng, [self.hidden, self.act_dim]),
        }

    @classmethod
    def forward(cls, params, obs):
        """Action in [-1, 1]^act_dim — pure jax."""
        import jax.numpy as jnp

        h = cls.mlp(params["trunk"], obs)
        (Wm, bm), = params["mu"]
        return jnp.tanh(h @ Wm + bm)


class QModule(RLModule):
    """State-action value MLP: Q(s, a) -> scalar."""

    def __init__(self, obs_dim: int, act_dim: int, hidden: int = 64,
                 seed: int = 0):
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.hidden = hidden
        self.seed = seed

    def init_params(self):
        from .policy import init_mlp_params

        rng = np.random.RandomState(self.seed)
        return {
            "trunk": init_mlp_params(
                rng, [self.obs_dim + self.act_dim, self.hidden,
                      self.hidden]
            ),
            "q": init_mlp_params(rng, [self.hidden, 1]),
        }

    @classmethod
    def forward(cls, params, obs, act):
        import jax.numpy as jnp

        h = cls.mlp(params["trunk"], jnp.concatenate([obs, act], -1))
        (W, b), = params["q"]
        return (h @ W + b)[..., 0]


# ------------------------------------------------------------------ learner

class Learner:
    """Owns the parameter pytree, the optax optimizer, optional polyak
    target copies, and ONE jitted update. Subclasses implement
    ``compute_loss(params, target, batch) -> (loss, stats)`` (pure jax)
    and inherit everything else (ref: Learner.compute_gradients /
    apply_gradients / update)."""

    def __init__(self, params, *, lr: float = 3e-4,
                 grad_clip: Optional[float] = None,
                 target_keys: Tuple[str, ...] = (),
                 tau: float = 0.005):
        import jax
        import jax.numpy as jnp
        import optax

        self._params = jax.tree.map(jnp.asarray, params)
        chain = []
        if grad_clip:
            chain.append(optax.clip_by_global_norm(grad_clip))
        chain.append(optax.adam(lr))
        self._tx = optax.chain(*chain)
        self._opt_state = self._tx.init(self._params)
        self._target_keys = tuple(target_keys)
        self._tau = tau
        # jnp leaves are immutable; sharing is a correct deep "copy".
        self._target = {k: self._params[k] for k in self._target_keys}
        self._jit_update = None  # built lazily (subclass is ready then)
        self.num_updates = 0

    # -- subclass surface ----------------------------------------------

    def compute_loss(self, params, target, batch):
        """Pure jax: (scalar loss, {stat: scalar}). ``target`` is the
        polyak-averaged target subtree dict ({} when target_keys=())."""
        raise NotImplementedError

    # -- update --------------------------------------------------------

    def _build(self):
        import jax
        import optax

        tau = self._tau
        tkeys = self._target_keys

        def upd(params, opt_state, target, batch):
            (loss, stats), grads = jax.value_and_grad(
                self.compute_loss, has_aux=True
            )(params, target, batch)
            updates, opt_state = self._tx.update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            if tkeys:
                # Entries outside target_keys pass through untouched
                # (a subclass may maintain them on its own schedule,
                # e.g. TD3's delayed actor target).
                target = {
                    **target,
                    **{
                        k: jax.tree.map(
                            lambda t, p: (1.0 - tau) * t + tau * p,
                            target[k], params[k],
                        )
                        for k in tkeys
                    },
                }
            stats["total_loss"] = loss
            return params, opt_state, target, stats

        self._jit_update = jax.jit(upd)

    def update_device(self, batch: Dict[str, np.ndarray]
                      ) -> Dict[str, Any]:
        """One gradient step; stats stay ON DEVICE (no host sync), so a
        tight minibatch loop keeps jax's async dispatch pipelined."""
        import jax.numpy as jnp

        if self._jit_update is None:
            self._build()
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
        self._params, self._opt_state, self._target, stats = (
            self._jit_update(
                self._params, self._opt_state, self._target, jbatch
            )
        )
        self.num_updates += 1
        return stats

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        return {k: float(v)
                for k, v in self.update_device(batch).items()}

    # -- weights -------------------------------------------------------

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self._params)

    def set_weights(self, weights):
        import jax
        import jax.numpy as jnp

        self._params = jax.tree.map(jnp.asarray, weights)

    def get_state(self):
        import jax

        return {
            "params": self.get_weights(),
            "target": jax.tree.map(np.asarray, self._target),
            "num_updates": self.num_updates,
        }


class TwinCriticLearner(Learner):
    """Shared machinery for deterministic-actor critic algorithms
    (TD3, CQL twin; DDPG single): params {actor, q1[, q2, ...]}; the
    critic step runs through ``compute_loss`` with the actor subtree
    MASKED out of the optimizer (Adam momentum on zero grads would still
    move frozen params), the actor step maximizes Q1(s, pi(s)) with its
    OWN optimizer state and polyak-syncs the actor target (its only sync
    point — critic targets sync in the base update), and weight/state
    round-trips keep the critics (get_weights returns the actor for
    rollout policies; set_weights accepts actor-only or full trees)."""

    def __init__(self, actor_params, *, obs_dim: int, act_dim: int,
                 hidden: int, lr: float, tau: float, seed: int,
                 critics: int = 2):
        import jax
        import optax

        qkeys = tuple(f"q{i + 1}" for i in range(critics))
        params = {
            "actor": actor_params,
            **{
                k: QModule(obs_dim, act_dim, hidden,
                           seed + 1 + i).init_params()
                for i, k in enumerate(qkeys)
            },
        }
        # Critic targets polyak in the base update; the ACTOR target is
        # seeded below and synced ONLY by actor_update (the base passes
        # non-listed target entries through untouched).
        super().__init__(params, lr=lr, target_keys=qkeys, tau=tau)
        self._target["actor"] = self._params["actor"]
        labels = {
            k: jax.tree.map(
                lambda _: "frozen" if k == "actor" else "train", v
            )
            for k, v in self._params.items()
        }
        self._tx = optax.multi_transform(
            {"train": optax.adam(lr), "frozen": optax.set_to_zero()},
            labels,
        )
        self._opt_state = self._tx.init(self._params)
        self._atx = optax.adam(lr)
        self._aopt_state = self._atx.init(self._params["actor"])
        self._act_dim = act_dim
        self._jit_actor = None

    def actor_update(self, batch) -> Dict[str, Any]:
        """Policy step: maximize Q1(s, pi(s)); returns device-valued
        stats (callers sync once per iteration)."""
        import jax
        import jax.numpy as jnp
        import optax

        if self._jit_actor is None:
            tau = self._tau

            def aloss(actor, q1, obs):
                a = DeterministicActorModule.forward(actor, obs)
                return -QModule.forward(q1, obs, a).mean()

            def upd(actor, aopt_state, q1, atarget, obs):
                loss, grads = jax.value_and_grad(aloss)(
                    actor, jax.lax.stop_gradient(q1), obs,
                )
                updates, aopt_state = self._atx.update(
                    grads, aopt_state, actor
                )
                actor = optax.apply_updates(actor, updates)
                atarget = jax.tree.map(
                    lambda t, p: (1.0 - tau) * t + tau * p,
                    atarget, actor,
                )
                return actor, aopt_state, atarget, loss

            self._jit_actor = jax.jit(upd)
        actor, self._aopt_state, atarget, loss = self._jit_actor(
            self._params["actor"], self._aopt_state,
            self._params["q1"], self._target["actor"],
            jnp.asarray(batch["obs"]),
        )
        self._params = {**self._params, "actor": actor}
        self._target = {**self._target, "actor": atarget}
        return {"actor_loss": loss}  # device value; caller syncs

    def get_weights(self):
        """ACTOR weights only — what rollout policies consume."""
        import jax

        return jax.tree.map(np.asarray, self._params["actor"])

    def set_weights(self, weights):
        """Accepts either a full {actor, q1, q2} tree or (matching
        get_weights) an actor-only tree, merged into the full params —
        the inherited round-trip must not drop the critics."""
        import jax
        import jax.numpy as jnp

        if isinstance(weights, dict) and "q1" in weights:
            super().set_weights(weights)
        else:
            self._params = {
                **self._params,
                "actor": jax.tree.map(jnp.asarray, weights),
            }

    def get_state(self):
        import jax

        return {
            "params": jax.tree.map(np.asarray, self._params),
            "target": jax.tree.map(np.asarray, self._target),
            "num_updates": self.num_updates,
        }


class _LearnerActor:
    """Actor wrapper hosting a Learner replica (LearnerGroup remote
    mode)."""

    def __init__(self, blob: bytes):
        import cloudpickle

        factory = cloudpickle.loads(blob)
        self._learner = factory()

    def update(self, batch):
        return self._learner.update(batch)

    def get_weights(self):
        return self._learner.get_weights()

    def num_updates(self):
        return self._learner.num_updates


class LearnerGroup:
    """Run a Learner locally or inside a remote actor (ref:
    learner_group.py:66 — local vs remote learners; the remote mode is
    the learner/actor split async algorithms build on). ``update_async``
    returns a future-like ref in remote mode so sampling continues
    while the learner steps."""

    def __init__(self, learner_factory: Callable[[], Learner],
                 *, remote: bool = False,
                 ray_remote_args: Optional[dict] = None):
        self._remote = remote
        if not remote:
            self._learner = learner_factory()
            self._actor = None
        else:
            import cloudpickle

            import ray_tpu

            blob = cloudpickle.dumps(learner_factory)
            opts = dict(ray_remote_args or {})
            cls = (ray_tpu.remote(**opts)(_LearnerActor) if opts
                   else ray_tpu.remote(_LearnerActor))
            self._actor = cls.remote(blob)
            self._learner = None

    @property
    def is_remote(self) -> bool:
        return self._remote

    def update(self, batch) -> Dict[str, float]:
        if self._learner is not None:
            return self._learner.update(batch)
        import ray_tpu

        return ray_tpu.get(self._actor.update.remote(batch), timeout=300)

    def update_async(self, batch):
        """Remote mode: returns the update's result ref immediately.
        Local mode: runs inline and returns the stats."""
        if self._learner is not None:
            return self._learner.update(batch)
        return self._actor.update.remote(batch)

    def get_weights(self):
        if self._learner is not None:
            return self._learner.get_weights()
        import ray_tpu

        return ray_tpu.get(self._actor.get_weights.remote(), timeout=300)

    def shutdown(self):
        if self._actor is not None:
            import ray_tpu

            try:
                ray_tpu.kill(self._actor)
            except Exception:
                pass
