"""AlphaZero: MCTS self-play with a learned policy/value network.

Ref analogue: rllib/algorithms/alpha_zero (Silver 2017). The loop:
parallel SELF-PLAY actors run PUCT tree search at every move (priors
and leaf values from the current network, Dirichlet noise at the
root), emitting (state, visit-count policy, final outcome) triples;
the learner fits the network to the search policies (cross-entropy)
and outcomes (value MSE); fresh weights broadcast back. The game
interface is two-player zero-sum with a canonical
current-player-to-move encoding; a TicTacToe implementation ships for
tests and as the interface model (the reference bundles example
games the same way).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .algorithm import AlgorithmConfig
from .policy import init_mlp_params


class TicTacToe:
    """Canonical two-player game: board from the CURRENT player's view
    (+1 own, -1 opponent); terminal value from the current player's
    view."""

    NUM_ACTIONS = 9
    OBS_DIM = 9

    def initial_state(self) -> np.ndarray:
        return np.zeros(9, np.float32)

    def legal_actions(self, s: np.ndarray) -> np.ndarray:
        return np.flatnonzero(s == 0)

    def next_state(self, s: np.ndarray, a: int) -> np.ndarray:
        out = -s.copy()          # flip perspective to the next player
        out[a] = -1.0            # the move just made is the opponent's
        return out

    _LINES = [(0, 1, 2), (3, 4, 5), (6, 7, 8), (0, 3, 6), (1, 4, 7),
              (2, 5, 8), (0, 4, 8), (2, 4, 6)]

    def terminal_value(self, s: np.ndarray) -> Optional[float]:
        """None if non-terminal; else value for the player TO MOVE."""
        for i, j, k in self._LINES:
            tot = s[i] + s[j] + s[k]
            if tot == 3.0:
                return 1.0       # current player already won (cannot
            if tot == -3.0:      # happen by alternation) / lost
                return -1.0
        if not (s == 0).any():
            return 0.0
        return None


def _forward(weights, s: np.ndarray) -> Tuple[np.ndarray, float]:
    h = s
    for W, b in weights["trunk"]:
        h = np.tanh(h @ W + b)
    (Wp, bp), = weights["pi"]
    (Wv, bv), = weights["vf"]
    logits = h @ Wp + bp
    return logits, float(np.tanh(h @ Wv + bv)[0])


class MCTS:
    """PUCT search (ref: rllib/algorithms/alpha_zero/mcts.py)."""

    def __init__(self, game, weights, *, num_simulations: int = 48,
                 c_puct: float = 1.5, dirichlet_alpha: float = 0.6,
                 noise_eps: float = 0.25,
                 rng: Optional[np.random.RandomState] = None):
        self.game = game
        self.weights = weights
        self.n_sim = num_simulations
        self.c = c_puct
        self.alpha = dirichlet_alpha
        self.eps = noise_eps
        self.rng = rng or np.random.RandomState(0)

    def search(self, root: np.ndarray,
               add_noise: bool = True) -> np.ndarray:
        """Visit-count policy over actions after n_sim simulations."""
        g = self.game
        # Tree keyed by state bytes: stats per node.
        P: Dict[bytes, np.ndarray] = {}
        N: Dict[bytes, np.ndarray] = {}
        W: Dict[bytes, np.ndarray] = {}

        def expand(s) -> float:
            """Add leaf; returns value for the player to move at s."""
            key = s.tobytes()
            term = g.terminal_value(s)
            if term is not None:
                return term
            logits, v = _forward(self.weights, s)
            legal = g.legal_actions(s)
            p = np.zeros(g.NUM_ACTIONS)
            ex = np.exp(logits[legal] - logits[legal].max())
            p[legal] = ex / ex.sum()
            P[key] = p
            N[key] = np.zeros(g.NUM_ACTIONS)
            W[key] = np.zeros(g.NUM_ACTIONS)
            return v

        def simulate(s) -> float:
            key = s.tobytes()
            term = g.terminal_value(s)
            if term is not None:
                return term
            if key not in P:
                return expand(s)
            legal = g.legal_actions(s)
            n, w, p = N[key], W[key], P[key]
            q = np.where(n > 0, w / np.maximum(n, 1), 0.0)
            u = self.c * p * math.sqrt(n.sum() + 1) / (1 + n)
            scores = np.full(g.NUM_ACTIONS, -np.inf)
            scores[legal] = q[legal] + u[legal]
            a = int(np.argmax(scores))
            # Child value is from the OPPONENT's view -> negate.
            v = -simulate(g.next_state(s, a))
            n[a] += 1
            w[a] += v
            return v

        expand(root)
        key = root.tobytes()
        if add_noise and key in P:
            legal = g.legal_actions(root)
            noise = self.rng.dirichlet(
                [self.alpha] * len(legal)
            )
            P[key][legal] = (1 - self.eps) * P[key][legal] \
                + self.eps * noise
        for _ in range(self.n_sim):
            simulate(root)
        visits = N[key]
        total = visits.sum()
        if total == 0:
            legal = self.game.legal_actions(root)
            pi = np.zeros(self.game.NUM_ACTIONS)
            pi[legal] = 1.0 / len(legal)
            return pi
        return visits / total


class _SelfPlayActor:
    def __init__(self, game_blob: bytes, num_simulations: int,
                 seed: int):
        import cloudpickle

        self.game = cloudpickle.loads(game_blob)()
        self.n_sim = num_simulations
        self.rng = np.random.RandomState(seed)
        self.weights = None

    def set_weights(self, w):
        self.weights = w

    def play_games(self, n: int, temperature_moves: int = 4):
        """n self-play games -> (states, pis, zs) arrays."""
        states, pis = [], []
        zs: List[float] = []
        for _ in range(n):
            s = self.game.initial_state()
            mcts = MCTS(self.game, self.weights,
                        num_simulations=self.n_sim, rng=self.rng)
            traj_start = len(states)
            move = 0
            while True:
                term = self.game.terminal_value(s)
                if term is not None:
                    # negamax back-fill: v(s) = -v(next_state), so a
                    # state k moves before terminal scores
                    # term * (-1)^k from ITS mover's view.
                    d = len(states) - traj_start
                    for j in range(d):
                        zs.append(term * ((-1.0) ** (d - j)))
                    break
                pi = mcts.search(s)
                states.append(s.copy())
                pis.append(pi)
                if move < temperature_moves:
                    a = int(self.rng.choice(len(pi), p=pi))
                else:
                    a = int(np.argmax(pi))
                s = self.game.next_state(s, a)
                move += 1
        return (np.stack(states), np.stack(pis),
                np.asarray(zs, np.float32))


class AlphaZeroConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-3
        self.game = TicTacToe
        self.num_simulations: int = 48
        self.games_per_iteration: int = 12
        self.replay_window: int = 4_000     # positions
        self.train_batches_per_iteration: int = 16
        self.hidden_size = 64

    def build(self) -> "AlphaZero":
        return AlphaZero(self.copy())


class AlphaZero:
    def __init__(self, config: AlphaZeroConfig):
        import cloudpickle

        import ray_tpu

        self.config = config
        self.iteration = 0
        c = config
        game = c.game()
        self._obs_dim = game.OBS_DIM
        self._num_actions = game.NUM_ACTIONS
        rng = np.random.RandomState(c.seed)
        self.weights = {
            "trunk": init_mlp_params(
                rng, [game.OBS_DIM, c.hidden_size, c.hidden_size]
            ),
            "pi": init_mlp_params(rng, [c.hidden_size,
                                        game.NUM_ACTIONS]),
            "vf": init_mlp_params(rng, [c.hidden_size, 1]),
        }
        blob = cloudpickle.dumps(c.game)
        actor_cls = ray_tpu.remote(_SelfPlayActor)
        self.actors = [
            actor_cls.remote(blob, c.num_simulations, c.seed + i)
            for i in range(c.num_env_runners)
        ]
        self._replay: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] \
            = []
        self._build_learner()

    def _build_learner(self):
        import jax
        import jax.numpy as jnp
        import optax

        c = self.config
        self._tx = optax.adam(c.lr)
        self._params = jax.tree.map(jnp.asarray, self.weights)
        self._opt_state = self._tx.init(self._params)

        def loss_fn(p, s, pi, z):
            h = s
            for Wt, bt in p["trunk"]:
                h = jnp.tanh(h @ Wt + bt)
            (Wp, bp), = p["pi"]
            (Wv, bv), = p["vf"]
            logits = h @ Wp + bp
            v = jnp.tanh(h @ Wv + bv)[:, 0]
            logp = jax.nn.log_softmax(logits)
            pi_loss = -(pi * logp).sum(-1).mean()
            v_loss = ((v - z) ** 2).mean()
            return pi_loss + v_loss, (pi_loss, v_loss)

        def update(p, opt_state, s, pi, z):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(p, s, pi, z)
            updates, opt_state = self._tx.update(grads, opt_state)
            return optax.apply_updates(p, updates), opt_state, loss, aux

        self._update = jax.jit(update)
        self._rng = np.random.RandomState(c.seed + 17)

    def train(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        import ray_tpu

        self.iteration += 1
        c = self.config
        ray_tpu.get([
            a.set_weights.remote(self.weights) for a in self.actors
        ])
        per = max(1, c.games_per_iteration // len(self.actors))
        results = ray_tpu.get([
            a.play_games.remote(per) for a in self.actors
        ])
        new_positions = 0
        for s, pi, z in results:
            self._replay.append((s, pi, z))
            new_positions += len(s)
        # Bound the replay window by positions.
        while sum(len(r[0]) for r in self._replay) > c.replay_window \
                and len(self._replay) > 1:
            self._replay.pop(0)

        S = np.concatenate([r[0] for r in self._replay])
        PI = np.concatenate([r[1] for r in self._replay])
        Z = np.concatenate([r[2] for r in self._replay])
        loss = pi_loss = v_loss = float("nan")
        for _ in range(c.train_batches_per_iteration):
            idx = self._rng.randint(0, len(S),
                                    min(c.minibatch_size, len(S)))
            self._params, self._opt_state, lo, (pl, vl) = self._update(
                self._params, self._opt_state,
                jnp.asarray(S[idx]), jnp.asarray(PI[idx]),
                jnp.asarray(Z[idx]),
            )
            loss, pi_loss, v_loss = float(lo), float(pl), float(vl)
        self.weights = jax.tree.map(np.asarray, self._params)
        return {
            "training_iteration": self.iteration,
            "num_positions": len(S),
            "new_positions": new_positions,
            "total_loss": loss,
            "policy_loss": pi_loss,
            "value_loss": v_loss,
        }

    def get_weights(self):
        return self.weights

    def compute_action(self, state: np.ndarray, *,
                       use_mcts: bool = True,
                       num_simulations: Optional[int] = None) -> int:
        """Greedy play with the current net (optionally MCTS-backed)."""
        game = self.config.game()
        if use_mcts:
            mcts = MCTS(
                game, self.weights,
                num_simulations=(num_simulations
                                 or self.config.num_simulations),
                rng=self._rng,
            )
            return int(np.argmax(mcts.search(state, add_noise=False)))
        logits, _ = _forward(self.weights, state)
        legal = game.legal_actions(state)
        scores = np.full(game.NUM_ACTIONS, -np.inf)
        scores[legal] = logits[legal]
        return int(np.argmax(scores))

    def stop(self):
        import ray_tpu

        for a in self.actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
