"""DT: Decision Transformer — offline RL as sequence modeling.

Ref analogue: rllib/algorithms/dt (Chen 2021). Trajectories become
token sequences (R_t, s_t, a_t) with returns-to-go; a small causal
transformer (jax — runs on the accelerator) is trained to predict the
action at each state token given the preceding context; at inference
the desired return is supplied as the conditioning R_0 and actions
are decoded autoregressively, decrementing the return-to-go by
observed rewards.

Offline input: a ray_tpu.data Dataset of per-step rows carrying
``episode_id``/``t``/``obs``/``action``/``reward`` columns; the
driver groups rows into episodes, computes returns-to-go, and samples
length-K context windows as training batches. Discrete actions.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .algorithm import AlgorithmConfig
from .policy import init_mlp_params


class DTConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.dataset = None
        self.obs_column = "obs"
        self.action_column = "action"
        self.reward_column = "reward"
        self.episode_column = "episode_id"
        self.time_column = "t"
        self.num_actions: Optional[int] = None
        self.context_length: int = 8      # K
        self.embed_dim: int = 64
        self.num_layers: int = 2
        self.num_heads: int = 2
        self.max_ep_len: int = 512
        self.batches_per_iteration: int = 32

    def offline_data(self, dataset, **columns) -> "DTConfig":
        self.dataset = dataset
        allowed = ("obs_column", "action_column", "reward_column",
                   "episode_column", "time_column")
        for k, v in columns.items():
            if k not in allowed:
                raise ValueError(f"unknown offline_data column {k!r} "
                                 f"(allowed: {allowed})")
            setattr(self, k, v)
        return self

    def build(self) -> "DT":
        if self.dataset is None:
            raise ValueError("DTConfig.offline_data(dataset=...) "
                             "required")
        if self.num_actions is None:
            raise ValueError("DTConfig.training(num_actions=...) "
                             "required (discrete)")
        return DT(self.copy())


def _init_dt_params(cfg: DTConfig, obs_dim: int) -> Dict[str, Any]:
    rng = np.random.RandomState(cfg.seed)
    D = cfg.embed_dim

    def lin(n_in, n_out):
        return init_mlp_params(rng, [n_in, n_out])

    params: Dict[str, Any] = {
        "state_emb": lin(obs_dim, D),
        "rtg_emb": lin(1, D),
        "act_emb": (rng.randn(cfg.num_actions + 1, D)
                    * 0.02).astype(np.float32),  # +1 = BOS/pad id
        "time_emb": (rng.randn(cfg.max_ep_len, D)
                     * 0.02).astype(np.float32),
        "head": lin(D, cfg.num_actions),
    }
    for layer in range(cfg.num_layers):
        params[f"attn_{layer}"] = {
            "qkv": lin(D, 3 * D),
            "proj": lin(D, D),
        }
        params[f"mlp_{layer}"] = {
            "up": lin(D, 4 * D),
            "down": lin(4 * D, D),
        }
    return params


class DTLearner:
    """Jitted causal-transformer action prediction loss."""

    def __init__(self, cfg: DTConfig, obs_dim: int):
        import jax
        import jax.numpy as jnp
        import optax

        self._tx = optax.adam(cfg.lr)
        self._params = jax.tree.map(
            jnp.asarray, _init_dt_params(cfg, obs_dim)
        )
        self._opt_state = self._tx.init(self._params)
        D, H = cfg.embed_dim, cfg.num_heads
        L = cfg.num_layers
        K = cfg.context_length

        def dense(p, x):
            (W, b), = p
            return x @ W + b

        def norm(x):
            mu = x.mean(-1, keepdims=True)
            var = x.var(-1, keepdims=True)
            return (x - mu) * jax.lax.rsqrt(var + 1e-5)

        def block(p_attn, p_mlp, x, mask):
            B, T, _ = x.shape
            qkv = dense(p_attn["qkv"], norm(x))
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def heads(t):
                return t.reshape(B, T, H, D // H).transpose(0, 2, 1, 3)

            q, k, v = heads(q), heads(k), heads(v)
            att = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(D // H)
            att = jnp.where(mask, att, -1e9)
            att = jax.nn.softmax(att, axis=-1)
            y = (att @ v).transpose(0, 2, 1, 3).reshape(B, T, D)
            x = x + dense(p_attn["proj"], y)
            h = dense(p_mlp["up"], norm(x))
            x = x + dense(p_mlp["down"], jax.nn.gelu(h))
            return x

        def forward(p, rtg, obs, act_in, timesteps):
            """rtg [B,K,1], obs [B,K,Do], act_in [B,K] (previous
            actions, BOS-shifted) -> logits [B,K,A] at state tokens."""
            B = obs.shape[0]
            te = p["time_emb"][timesteps]          # [B,K,D]
            tok_r = dense(p["rtg_emb"], rtg) + te
            tok_s = dense(p["state_emb"], obs) + te
            tok_a = p["act_emb"][act_in] + te
            # interleave (r, s, a) -> [B, 3K, D]
            x = jnp.stack([tok_r, tok_s, tok_a], axis=2)
            x = x.reshape(B, 3 * K, D)
            T = 3 * K
            causal = jnp.tril(jnp.ones((T, T), bool))[None, None]
            for layer in range(L):
                x = block(p[f"attn_{layer}"], p[f"mlp_{layer}"], x,
                          causal)
            x = norm(x)
            # state tokens sit at positions 3t+1
            s_out = x[:, 1::3]
            return dense(p["head"], s_out)

        def loss_fn(p, batch):
            logits = forward(p, batch["rtg"], batch["obs"],
                             batch["act_in"], batch["timesteps"])
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, batch["actions"][..., None], axis=-1
            )[..., 0]
            return (nll * batch["mask"]).sum() / jnp.maximum(
                batch["mask"].sum(), 1.0
            )

        def update(p, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(p, batch)
            updates, opt_state = self._tx.update(grads, opt_state)
            return optax.apply_updates(p, updates), opt_state, loss

        self._update = jax.jit(update)
        self._forward = jax.jit(forward)

    def train_batch(self, np_batch) -> float:
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in np_batch.items()}
        self._params, self._opt_state, loss = self._update(
            self._params, self._opt_state, jb
        )
        return float(loss)

    def predict_logits(self, rtg, obs, act_in, timesteps):
        return np.asarray(self._forward(
            self._params, rtg, obs, act_in, timesteps
        ))


class DT:
    def __init__(self, config: DTConfig):
        c = config
        self.config = c
        self.iteration = 0
        self._rng = np.random.RandomState(c.seed)
        self._episodes = self._load_episodes()
        obs0 = self._episodes[0]["obs"]
        self._obs_dim = int(obs0.shape[-1])
        self.learner = DTLearner(c, self._obs_dim)

    def _load_episodes(self) -> List[Dict[str, np.ndarray]]:
        c = self.config
        by_ep: Dict[Any, List[tuple]] = {}
        for batch in c.dataset.iter_batches(batch_size=1024,
                                            batch_format="numpy"):
            n = len(batch[c.episode_column])
            for i in range(n):
                by_ep.setdefault(
                    batch[c.episode_column][i].item()
                    if hasattr(batch[c.episode_column][i], "item")
                    else batch[c.episode_column][i],
                    [],
                ).append((
                    int(batch[c.time_column][i]),
                    np.asarray(batch[c.obs_column][i],
                               np.float32).reshape(-1),
                    int(batch[c.action_column][i]),
                    float(batch[c.reward_column][i]),
                ))
        episodes = []
        for rows in by_ep.values():
            rows.sort(key=lambda r: r[0])
            obs = np.stack([r[1] for r in rows])
            acts = np.asarray([r[2] for r in rows], np.int32)
            rews = np.asarray([r[3] for r in rows], np.float32)
            rtg = np.cumsum(rews[::-1])[::-1].astype(np.float32)
            episodes.append({"obs": obs, "actions": acts,
                             "rewards": rews, "rtg": rtg})
        if not episodes:
            raise ValueError("offline dataset contains no episodes")
        return episodes

    def _sample_batch(self) -> Dict[str, np.ndarray]:
        c = self.config
        K = c.context_length
        B = c.minibatch_size
        bos = c.num_actions   # BOS/pad action id
        out = {
            "obs": np.zeros((B, K, self._obs_dim), np.float32),
            "actions": np.zeros((B, K), np.int32),
            "act_in": np.full((B, K), bos, np.int32),
            "rtg": np.zeros((B, K, 1), np.float32),
            "timesteps": np.zeros((B, K), np.int32),
            "mask": np.zeros((B, K), np.float32),
        }
        for b in range(B):
            ep = self._episodes[self._rng.randint(len(self._episodes))]
            T = len(ep["actions"])
            start = self._rng.randint(T)
            end = min(T, start + K)
            n = end - start
            out["obs"][b, :n] = ep["obs"][start:end]
            out["actions"][b, :n] = ep["actions"][start:end]
            if start > 0:
                out["act_in"][b, 0] = ep["actions"][start - 1]
            out["act_in"][b, 1:n] = ep["actions"][start:end - 1]
            out["rtg"][b, :n, 0] = ep["rtg"][start:end]
            out["timesteps"][b, :n] = np.arange(
                start, end
            ) % self.config.max_ep_len
            out["mask"][b, :n] = 1.0
        return out

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        c = self.config
        loss = float("nan")
        for _ in range(c.batches_per_iteration):
            loss = self.learner.train_batch(self._sample_batch())
        return {
            "training_iteration": self.iteration,
            "loss": loss,
            "num_episodes": len(self._episodes),
        }

    def compute_action(self, history: Dict[str, List[Any]],
                       target_return: float) -> int:
        """Next action given the running episode ``history``
        ({"obs": [...], "actions": [...], "rewards": [...]}) and the
        conditioning target return (ref: DT inference — rtg decremented
        by observed rewards)."""
        c = self.config
        K = c.context_length
        obs_hist = [np.asarray(o, np.float32).reshape(-1)
                    for o in history["obs"]]
        act_hist = list(history.get("actions", []))
        rew_hist = list(history.get("rewards", []))
        rtg = target_return - float(np.sum(rew_hist))
        t0 = max(0, len(obs_hist) - K)
        window = obs_hist[t0:]
        n = len(window)
        bos = c.num_actions
        obs = np.zeros((1, K, self._obs_dim), np.float32)
        act_in = np.full((1, K), bos, np.int32)
        rtgs = np.zeros((1, K, 1), np.float32)
        ts = np.zeros((1, K), np.int32)
        obs[0, :n] = np.stack(window)
        rtg_seq = []
        run = target_return
        for i, r in enumerate(rew_hist):
            rtg_seq.append(run)
            run -= r
        rtg_seq.append(run)
        rtg_win = rtg_seq[t0:t0 + n]
        rtgs[0, :len(rtg_win), 0] = rtg_win
        prev = act_hist[t0 - 1] if t0 > 0 else None
        if prev is not None:
            act_in[0, 0] = prev
        for i, a in enumerate(act_hist[t0:]):
            if i + 1 < K:
                act_in[0, i + 1] = a
        ts[0, :n] = (np.arange(t0, t0 + n) % c.max_ep_len)
        logits = self.learner.predict_logits(rtgs, obs, act_in, ts)
        return int(np.argmax(logits[0, n - 1]))

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.learner._params)

    def stop(self):
        pass
