"""CQL: conservative Q-learning — offline continuous control.

Ref analogue: rllib/algorithms/cql (Kumar 2020): twin critics + a
deterministic actor trained purely from a logged Dataset of
transitions, with the CONSERVATIVE penalty added to the critic loss:
``alpha_cql * (logsumexp_a Q(s,a) - Q(s, a_data))`` pushes Q down on
out-of-distribution actions so the learned policy cannot exploit
over-estimated values it never saw data for. Built on the shared
TwinCriticLearner (core.py, shared with TD3); there are NO EnvRunners —
the offline pipeline is ray_tpu.data streaming minibatches into the
jitted update.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .algorithm import AlgorithmConfig
from .core import (
    DeterministicActorModule,
    QModule,
    TwinCriticLearner,
)


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.dataset = None
        self.obs_column = "obs"
        self.action_column = "action"
        self.reward_column = "reward"
        self.next_obs_column = "next_obs"
        self.done_column = "done"
        self.tau: float = 0.005
        self.cql_alpha: float = 1.0        # conservative penalty weight
        self.num_random_actions: int = 8   # logsumexp sample count
        self.epochs_per_iteration: int = 1

    _COLUMN_KEYS = ("obs_column", "action_column", "reward_column",
                    "next_obs_column", "done_column")

    def offline_data(self, dataset, **columns) -> "CQLConfig":
        self.dataset = dataset
        for k, v in columns.items():
            if k not in self._COLUMN_KEYS:
                raise ValueError(
                    f"unknown offline_data column {k!r} "
                    f"(allowed: {self._COLUMN_KEYS})"
                )
            setattr(self, k, v)
        return self

    def build(self) -> "CQL":
        if self.dataset is None:
            raise ValueError("CQLConfig.offline_data(dataset=...) required")
        return CQL(self.copy())


class CQLLearner(TwinCriticLearner):
    """Twin-critic TD loss + conservative penalty on the shared
    TwinCriticLearner machinery; the actor maximizes Q1 every step
    (TD3-style delay is unnecessary offline, matching the reference's
    CQL)."""

    def __init__(self, cfg, obs_dim: int, act_dim: int):
        super().__init__(
            DeterministicActorModule(
                obs_dim, act_dim, cfg.hidden_size, cfg.seed
            ).init_params(),
            obs_dim=obs_dim, act_dim=act_dim, hidden=cfg.hidden_size,
            lr=cfg.lr, tau=cfg.tau, seed=cfg.seed,
        )
        self._gamma = cfg.gamma
        self._cql_alpha = cfg.cql_alpha
        self._nrand = cfg.num_random_actions
        self._rng = np.random.RandomState(cfg.seed + 3)

    def compute_loss(self, params, target, batch):
        import jax
        import jax.numpy as jnp

        obs, act = batch["obs"], batch["act"]
        nxt, rew, done = batch["next_obs"], batch["rew"], batch["done"]
        a2 = DeterministicActorModule.forward(target["actor"], nxt)
        tq = jnp.minimum(
            QModule.forward(target["q1"], nxt, a2),
            QModule.forward(target["q2"], nxt, a2),
        )
        backup = jax.lax.stop_gradient(
            rew + self._gamma * (1.0 - done) * tq
        )
        q1 = QModule.forward(params["q1"], obs, act)
        q2 = QModule.forward(params["q2"], obs, act)
        td = ((q1 - backup) ** 2 + (q2 - backup) ** 2).mean()

        # Conservative penalty: logsumexp over random + policy actions
        # minus Q on the DATASET actions, per critic (cql.py's
        # cql_loss).
        B = obs.shape[0]
        rand = batch["rand_actions"]          # [B, nrand, act_dim]
        pol = DeterministicActorModule.forward(params["actor"], obs)
        cand = jnp.concatenate([rand, pol[:, None, :]], axis=1)
        n_cand = cand.shape[1]
        obs_rep = jnp.repeat(obs[:, None, :], n_cand, axis=1).reshape(
            B * n_cand, -1
        )
        cand_flat = cand.reshape(B * n_cand, -1)

        def lse(qp, q_data):
            qs = QModule.forward(qp, obs_rep, cand_flat).reshape(
                B, n_cand
            )
            return (jax.scipy.special.logsumexp(qs, axis=1)
                    - q_data).mean()

        cql = lse(params["q1"], q1) + lse(params["q2"], q2)
        total = td + self._cql_alpha * cql
        return total, {
            "td_loss": td,
            "cql_penalty": cql,
            "q1_mean": q1.mean(),
        }

    def learn_on_batch(self, np_batch) -> Dict[str, Any]:
        B = len(np_batch["obs"])
        np_batch = dict(np_batch)
        np_batch["rand_actions"] = self._rng.uniform(
            -1.0, 1.0, size=(B, self._nrand, self._act_dim)
        ).astype(np.float32)
        stats = self.update_device(np_batch)
        stats = {**stats, **self.actor_update(np_batch)}
        return stats


class CQL:
    """Offline trainer: train() = epochs of minibatch updates streamed
    from the Dataset (no environment interaction)."""

    def __init__(self, config: CQLConfig):
        c = config
        self.config = c
        self.iteration = 0
        probe = next(iter(
            c.dataset.iter_batches(batch_size=1, batch_format="numpy")
        ))
        obs = np.asarray(probe[c.obs_column])
        act = np.asarray(probe[c.action_column])
        self._obs_dim = int(np.prod(obs.shape[1:])) or 1
        self._act_dim = int(np.prod(act.shape[1:])) or 1
        self.learner = CQLLearner(c, self._obs_dim, self._act_dim)

    def train(self) -> Dict[str, Any]:
        c = self.config
        self.iteration += 1
        stats: Dict[str, Any] = {}
        updates = 0
        if c.dataset.count() < c.minibatch_size:
            raise ValueError(
                f"dataset has {c.dataset.count()} rows < minibatch_size"
                f"={c.minibatch_size}; no training would happen"
            )
        for _ in range(c.epochs_per_iteration):
            for batch in c.dataset.iter_batches(
                batch_size=c.minibatch_size, batch_format="numpy",
                drop_last=True,
            ):
                obs = np.asarray(batch[c.obs_column],
                                 np.float32).reshape(
                    len(batch[c.obs_column]), -1
                )
                np_batch = {
                    "obs": obs,
                    "act": np.asarray(batch[c.action_column],
                                      np.float32).reshape(
                        len(obs), -1
                    ),
                    "rew": np.asarray(batch[c.reward_column],
                                      np.float32),
                    "next_obs": np.asarray(
                        batch[c.next_obs_column], np.float32
                    ).reshape(len(obs), -1),
                    "done": np.asarray(batch[c.done_column],
                                       np.float32),
                }
                stats = self.learner.learn_on_batch(np_batch)
                updates += 1
        stats = {k: float(v) for k, v in stats.items()}
        return {
            "training_iteration": self.iteration,
            "num_learner_updates": updates,
            **stats,
        }

    def get_weights(self):
        return self.learner.get_weights()

    def stop(self):
        pass
