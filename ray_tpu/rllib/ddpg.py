"""DDPG: deep deterministic policy gradient (continuous control).

Ref analogue: rllib/algorithms/ddpg (Lillicrap 2015) — the TD3
predecessor: ONE critic, no target-policy smoothing, actor updated
every critic step. Built on the shared TwinCriticLearner machinery
(core.py) with ``critics=1``: the critic TD loss backs up through the
polyak target actor + target critic, the actor step maximizes
Q(s, pi(s)) with its own optimizer, and rollouts use the same
Gaussian-noise DeterministicPolicy as TD3.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .core import DeterministicActorModule, QModule, TwinCriticLearner
from .env_runner import NEXT_OBS, TransitionEnvRunner
from .replay_buffers import ReplayBuffer
from .sample_batch import ACTIONS, DONES, OBS, REWARDS, SampleBatch


class DDPGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size: int = 100_000
        self.num_steps_sampled_before_learning_starts: int = 500
        self.num_updates_per_iteration: int = 64
        self.tau: float = 0.005
        self.exploration_noise: float = 0.1

    def build(self) -> "DDPG":
        return DDPG(self.copy())


class DDPGLearner(TwinCriticLearner):
    """Single-critic TD loss: backup = r + gamma*(1-d)*Q'(s', pi'(s'))
    — no twin-min, no smoothing noise (those are TD3's additions)."""

    def __init__(self, policy, cfg, obs_dim: int, act_dim: int,
                 low, high):
        import jax.numpy as jnp

        super().__init__(
            policy.get_weights(), obs_dim=obs_dim, act_dim=act_dim,
            hidden=cfg.hidden_size, lr=cfg.lr, tau=cfg.tau,
            seed=cfg.seed, critics=1,
        )
        self._gamma = cfg.gamma
        self._low = jnp.asarray(np.asarray(low, np.float32))
        self._high = jnp.asarray(np.asarray(high, np.float32))

    # Actions are stored in ENV units; critics consume [-1, 1].
    def _from_env(self, a):
        import jax.numpy as jnp

        u = (a - self._low) / (self._high - self._low) * 2.0 - 1.0
        return jnp.clip(u, -1.0, 1.0)

    def compute_loss(self, params, target, batch):
        import jax
        import jax.numpy as jnp

        obs, nxt = batch["obs"], batch["next_obs"]
        act = self._from_env(batch["actions"])
        a2 = DeterministicActorModule.forward(target["actor"], nxt)
        tq = QModule.forward(target["q1"], nxt, a2)
        backup = jax.lax.stop_gradient(
            batch["rew"] + self._gamma * (1.0 - batch["done"]) * tq
        )
        q = QModule.forward(params["q1"], obs, act)
        critic_loss = ((q - backup) ** 2).mean()
        return critic_loss, {
            "critic_loss": critic_loss,
            "q_mean": q.mean(),
        }

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, Any]:
        """One critic step + one actor step (every step — no delay).
        Stats stay ON DEVICE; callers float() once per iteration."""
        np_batch = {
            "obs": batch[OBS],
            "actions": np.asarray(batch[ACTIONS], np.float32),
            "rew": batch[REWARDS],
            "done": np.asarray(batch[DONES], np.float32),
            "next_obs": batch[NEXT_OBS],
        }
        stats = self.update_device(np_batch)
        return {**stats, **self.actor_update(np_batch)}


class DDPG(Algorithm):
    def _make_policy_factory(self, obs_dim: int, act_dim: int):
        from .policy import DeterministicPolicy

        if not getattr(self, "_continuous", False):
            raise ValueError(
                "DDPG supports Box (continuous) action spaces only"
            )
        config = self.config
        low, high = self._action_low, self._action_high

        def policy_factory(obs_dim=obs_dim, act_dim=act_dim,
                           hidden=config.hidden_size, seed=config.seed,
                           noise=config.exploration_noise):
            return DeterministicPolicy(
                obs_dim, act_dim, low, high, hidden, seed,
                exploration_noise=noise,
            )

        return policy_factory

    def _runner_class(self):
        return TransitionEnvRunner

    def _build_learner(self, policy):
        c = self.config
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._env_steps = 0
        return DDPGLearner(policy, c, self._obs_dim, self._num_actions,
                           self._action_low, self._action_high)

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        batches: List[SampleBatch] = ray_tpu.get(
            [r.sample.remote() for r in self.runners]
        )
        for b in batches:
            self.buffer.add(b)
            self._env_steps += b.count

        stats: Dict[str, Any] = {}
        num_updates = 0
        if self._env_steps >= c.num_steps_sampled_before_learning_starts:
            for _ in range(c.num_updates_per_iteration):
                mb = self.buffer.sample(c.minibatch_size)
                stats = self.learner.learn_on_batch(mb)
                num_updates += 1
            # ONE host sync for the whole update loop.
            stats = {k: float(v) for k, v in stats.items()}
            weights = self.learner.get_weights()
            ray_tpu.get(
                [r.set_weights.remote(weights) for r in self.runners]
            )

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "num_learner_updates": num_updates,
            "buffer_size": len(self.buffer),
            **stats,
        }
