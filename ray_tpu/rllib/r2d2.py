"""R2D2: recurrent experience replay in distributed RL.

Ref analogue: rllib/algorithms/r2d2 (Kapturowski 2019). A partially
observable env needs memory: the Q-network is an LSTM, replay stores
fixed-length SEQUENCES with the recurrent state captured at sequence
start (the paper's "stored state" strategy), and the learner unrolls
the online and target nets over each sequence with ``lax.scan``,
applying a masked double-Q TD loss per step. Rollouts run the same
LSTM cell in numpy, carrying hidden state across env steps and
resetting it at episode boundaries; sequences never cross an episode
boundary (short tails are zero-padded and masked).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import AlgorithmConfig
from .policy import init_mlp_params
from .replay_buffers import ReplayBuffer
from .sample_batch import SampleBatch


def _lstm_step_np(w, x, h, c):
    z = x @ w["wx"] + h @ w["wh"] + w["b"]
    H = h.shape[-1]
    i = 1.0 / (1.0 + np.exp(-z[..., :H]))
    f = 1.0 / (1.0 + np.exp(-z[..., H:2 * H]))
    g = np.tanh(z[..., 2 * H:3 * H])
    o = 1.0 / (1.0 + np.exp(-z[..., 3 * H:]))
    c2 = f * c + i * g
    h2 = o * np.tanh(c2)
    return h2, c2


class R2D2Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size: int = 2_000       # sequences
        self.num_steps_sampled_before_learning_starts: int = 400
        self.target_network_update_freq: int = 600
        self.num_updates_per_iteration: int = 24
        self.seq_len: int = 12
        self.lstm_size: int = 32
        self.epsilon_initial: float = 1.0
        self.epsilon_final: float = 0.05
        self.epsilon_timesteps: int = 6_000
        self.minibatch_size = 32            # sequences per batch

    def build(self) -> "R2D2":
        return R2D2(self.copy())


def _init_params(obs_dim: int, num_actions: int, hidden: int,
                 seed: int) -> Dict[str, Any]:
    rng = np.random.RandomState(seed)
    scale = 1.0 / np.sqrt(obs_dim + hidden)
    return {
        "wx": (rng.randn(obs_dim, 4 * hidden) * scale
               ).astype(np.float32),
        "wh": (rng.randn(hidden, 4 * hidden) * scale
               ).astype(np.float32),
        "b": np.zeros(4 * hidden, np.float32),
        "q": init_mlp_params(rng, [hidden, num_actions]),
    }


class _R2D2Policy:
    """numpy LSTM inference with carried hidden state."""

    def __init__(self, obs_dim, num_actions, hidden, seed):
        self.weights = _init_params(obs_dim, num_actions, hidden, seed)
        self.hidden = hidden
        self.num_actions = num_actions
        self.epsilon = 1.0
        self.reset_state()

    def reset_state(self):
        self.h = np.zeros(self.hidden, np.float32)
        self.c = np.zeros(self.hidden, np.float32)

    def set_weights(self, weights):
        self.weights = weights

    def set_epsilon(self, eps):
        self.epsilon = float(eps)

    def state(self):
        return self.h.copy(), self.c.copy()

    def compute_action(self, obs, rng):
        self.h, self.c = _lstm_step_np(
            self.weights, np.asarray(obs, np.float32).reshape(-1),
            self.h, self.c,
        )
        if rng.rand() < self.epsilon:
            return int(rng.randint(self.num_actions)), 0.0, 0.0
        (Wq, bq), = self.weights["q"]
        return int(np.argmax(self.h @ Wq + bq)), 0.0, 0.0


class _R2D2EnvRunner:
    """Collects padded fixed-length sequences with stored initial
    recurrent state; resets the LSTM at episode boundaries."""

    def __init__(self, env_creator, policy_factory, seed=0,
                 rollout_fragment_length=200, seq_len=12, **_):
        self.env = env_creator()
        self.policy = policy_factory()
        self.rng = np.random.RandomState(seed)
        self.fragment = rollout_fragment_length
        self.L = seq_len
        self._obs, _ = self.env.reset(seed=seed)
        self.policy.reset_state()
        self._episode_reward = 0.0
        self._episode_rewards: List[float] = []

    def set_weights(self, w):
        self.policy.set_weights(w)

    def set_epsilon(self, e):
        self.policy.set_epsilon(e)

    def sample(self) -> SampleBatch:
        L = self.L
        seqs: List[Dict[str, np.ndarray]] = []
        cur = self._new_seq()
        steps = 0
        while steps < self.fragment:
            obs = np.asarray(self._obs, np.float32).reshape(-1)
            a, _, _ = self.policy.compute_action(obs, self.rng)
            nxt, r, term, trunc, _ = self.env.step(a)
            done = bool(term or trunc)
            cur["obs"].append(obs)
            cur["actions"].append(a)
            cur["rewards"].append(float(r))
            cur["dones"].append(bool(term))
            self._episode_reward += float(r)
            steps += 1
            if done:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self.env.reset()
                self.policy.reset_state()
                cur["obs"].append(
                    np.asarray(nxt, np.float32).reshape(-1)
                )
                seqs.append(self._finish(cur, L))
                cur = self._new_seq()
            else:
                self._obs = nxt
                if len(cur["actions"]) == L:
                    cur["obs"].append(
                        np.asarray(self._obs, np.float32).reshape(-1)
                    )
                    seqs.append(self._finish(cur, L))
                    cur = self._new_seq()
        if cur["actions"]:
            cur["obs"].append(
                np.asarray(self._obs, np.float32).reshape(-1)
            )
            seqs.append(self._finish(cur, L))
        return SampleBatch({
            k: np.stack([s[k] for s in seqs])
            for k in seqs[0]
        })

    def _new_seq(self):
        h, c = self.policy.state()
        return {"obs": [], "actions": [], "rewards": [], "dones": [],
                "h0": h, "c0": c}

    def _finish(self, cur, L):
        n = len(cur["actions"])
        obs_dim = cur["obs"][0].shape[0]
        obs = np.zeros((L + 1, obs_dim), np.float32)
        obs[:n + 1] = np.stack(cur["obs"])
        out = {
            "obs": obs,
            "actions": np.zeros(L, np.int32),
            "rewards": np.zeros(L, np.float32),
            "dones": np.zeros(L, np.float32),
            "mask": np.zeros(L, np.float32),
            "h0": cur["h0"], "c0": cur["c0"],
        }
        out["actions"][:n] = cur["actions"]
        out["rewards"][:n] = cur["rewards"]
        out["dones"][:n] = np.asarray(cur["dones"], np.float32)
        out["mask"][:n] = 1.0
        return out

    def episode_stats(self) -> Dict[str, float]:
        recent = self._episode_rewards[-20:]
        return {
            "episodes_total": len(self._episode_rewards),
            "episode_reward_mean": float(np.mean(recent))
            if recent else 0.0,
        }


class R2D2Learner:
    """Sequence double-Q learner: lax.scan unroll of online + target
    LSTMs from the stored initial state, masked TD loss."""

    def __init__(self, obs_dim, num_actions, hidden, lr, gamma, seed):
        import jax
        import jax.numpy as jnp
        import optax

        self._tx = optax.adam(lr)
        self._params = jax.tree.map(
            jnp.asarray, _init_params(obs_dim, num_actions, hidden,
                                      seed)
        )
        self._target = jax.tree.map(lambda x: x, self._params)
        self._opt_state = self._tx.init(self._params)
        H = hidden

        def unroll(w, obs, h0, c0):
            """obs [B, T, D] -> q [B, T, A]."""
            def cell(carry, x):
                h, c = carry
                z = x @ w["wx"] + h @ w["wh"] + w["b"]
                i = jax.nn.sigmoid(z[..., :H])
                f = jax.nn.sigmoid(z[..., H:2 * H])
                g = jnp.tanh(z[..., 2 * H:3 * H])
                o = jax.nn.sigmoid(z[..., 3 * H:])
                c2 = f * c + i * g
                h2 = o * jnp.tanh(c2)
                return (h2, c2), h2

            _, hs = jax.lax.scan(
                cell, (h0, c0), jnp.swapaxes(obs, 0, 1)
            )
            hs = jnp.swapaxes(hs, 0, 1)          # [B, T, H]
            (Wq, bq), = w["q"]
            return hs @ Wq + bq

        def loss_fn(params, target, batch):
            q_all = unroll(params, batch["obs"], batch["h0"],
                           batch["c0"])                    # [B,T+1,A]
            tq_all = unroll(target, batch["obs"], batch["h0"],
                            batch["c0"])
            q_sa = jnp.take_along_axis(
                q_all[:, :-1], batch["actions"][..., None], axis=-1
            )[..., 0]                                      # [B,L]
            best = jnp.argmax(q_all[:, 1:], axis=-1)       # online pick
            q_next = jnp.take_along_axis(
                tq_all[:, 1:], best[..., None], axis=-1
            )[..., 0]
            y = batch["rewards"] + gamma * (1.0 - batch["dones"]) \
                * q_next
            td = (q_sa - jax.lax.stop_gradient(y)) * batch["mask"]
            loss = (td * td).sum() / jnp.maximum(
                batch["mask"].sum(), 1.0
            )
            return loss

        def update(params, opt_state, target, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, target, batch
            )
            updates, opt_state = self._tx.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._update = jax.jit(update)
        self._gamma = gamma

    def learn_on_batch(self, mb) -> float:
        import jax.numpy as jnp

        batch = {
            "obs": jnp.asarray(mb["obs"]),
            "actions": jnp.asarray(mb["actions"], jnp.int32),
            "rewards": jnp.asarray(mb["rewards"]),
            "dones": jnp.asarray(mb["dones"]),
            "mask": jnp.asarray(mb["mask"]),
            "h0": jnp.asarray(mb["h0"]),
            "c0": jnp.asarray(mb["c0"]),
        }
        self._params, self._opt_state, loss = self._update(
            self._params, self._opt_state, self._target, batch
        )
        return float(loss)

    def sync_target(self):
        import jax

        self._target = jax.tree.map(lambda x: x, self._params)

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self._params)


class R2D2:
    def __init__(self, config: R2D2Config):
        import ray_tpu

        self.config = config
        self.iteration = 0
        c = config
        creator = c.env_creator()
        probe = creator()
        obs_dim = int(np.prod(probe.observation_space.shape))
        if not hasattr(probe.action_space, "n"):
            raise ValueError("R2D2 supports discrete action spaces")
        num_actions = int(probe.action_space.n)
        if hasattr(probe, "close"):
            probe.close()
        self._obs_dim, self._num_actions = obs_dim, num_actions

        def policy_factory(obs_dim=obs_dim, n=num_actions,
                           hidden=c.lstm_size, seed=c.seed):
            return _R2D2Policy(obs_dim, n, hidden, seed)

        runner_cls = ray_tpu.remote(_R2D2EnvRunner)
        self.runners = [
            runner_cls.remote(
                creator, policy_factory, seed=c.seed + i,
                rollout_fragment_length=c.rollout_fragment_length,
                seq_len=c.seq_len,
            )
            for i in range(c.num_env_runners)
        ]
        self.learner = R2D2Learner(
            obs_dim, num_actions, c.lstm_size, c.lr, c.gamma, c.seed
        )
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._env_steps = 0
        self._last_target_sync = 0

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._env_steps / max(1, c.epsilon_timesteps))
        return c.epsilon_initial + frac * (
            c.epsilon_final - c.epsilon_initial
        )

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        self.iteration += 1
        c = self.config
        eps = self._epsilon()
        ray_tpu.get([r.set_epsilon.remote(eps) for r in self.runners])
        batches = ray_tpu.get([r.sample.remote() for r in self.runners])
        for b in batches:
            self.buffer.add(b)
            self._env_steps += int(b["mask"].sum())

        loss = float("nan")
        num_updates = 0
        if self._env_steps >= c.num_steps_sampled_before_learning_starts:
            for _ in range(c.num_updates_per_iteration):
                mb = self.buffer.sample(c.minibatch_size)
                loss = self.learner.learn_on_batch(mb)
                num_updates += 1
            if (self._env_steps - self._last_target_sync
                    >= c.target_network_update_freq):
                self.learner.sync_target()
                self._last_target_sync = self._env_steps
            w = self.learner.get_weights()
            ray_tpu.get(
                [r.set_weights.remote(w) for r in self.runners]
            )

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "num_learner_updates": num_updates,
            "epsilon": eps,
            "loss": loss,
        }

    def get_weights(self):
        return self.learner.get_weights()

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
