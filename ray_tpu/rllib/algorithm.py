"""Algorithm + AlgorithmConfig.

Ref analogue: rllib/algorithms/algorithm.py Algorithm (:190,
training_step:1616) and algorithm_config.py AlgorithmConfig builder.
``train()`` = one iteration: parallel EnvRunner sampling (CPU actors) →
Learner update (jax, accelerator) → weight broadcast, matching the
reference's SURVEY.md §3.6 loop with the NCCL learner group replaced by a
jax learner.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class AlgorithmConfig:
    def __init__(self):
        self.env: Optional[Any] = None
        self.env_config: Dict[str, Any] = {}
        self.num_env_runners: int = 2
        self.rollout_fragment_length: int = 200
        self.lr: float = 3e-4
        self.gamma: float = 0.99
        self.lambda_: float = 0.95
        self.train_batch_size: int = 400
        self.minibatch_size: int = 128
        self.num_epochs: int = 8
        self.hidden_size: int = 64
        self.seed: int = 0

    def environment(self, env=None, *, env_config=None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = env_config
        return self

    def env_runners(self, *, num_env_runners=None,
                    rollout_fragment_length=None) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kw) -> "AlgorithmConfig":
        for k, v in kw.items():
            key = "lambda_" if k == "lambda" else k
            if not hasattr(self, key):
                raise ValueError(f"unknown training param {k!r}")
            setattr(self, key, v)
        return self

    def debugging(self, *, seed=None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def env_creator(self) -> Callable[[], Any]:
        env = self.env
        cfg = dict(self.env_config)
        if callable(env):
            return lambda: env(**cfg) if cfg else env()
        if isinstance(env, str):
            def make():
                import gymnasium

                return gymnasium.make(env, **cfg)

            return make
        raise ValueError("config.environment(env=...) must be set to a "
                         "callable or gymnasium env id")

    def build(self) -> "Algorithm":
        raise NotImplementedError


class Algorithm:
    """Base: owns EnvRunner actors + a Learner; subclasses implement
    training_step()."""

    def __init__(self, config: AlgorithmConfig):
        import ray_tpu
        from .env_runner import EnvRunner

        self.config = config
        self.iteration = 0
        creator = config.env_creator()
        probe = creator()
        obs_dim = int(np.prod(probe.observation_space.shape))
        space = probe.action_space
        if hasattr(space, "n"):        # Discrete
            num_actions = int(space.n)
            self._continuous = False
            self._action_low = self._action_high = None
        else:                          # Box (continuous control)
            num_actions = int(np.prod(space.shape))
            self._continuous = True
            self._action_low = np.asarray(space.low, dtype=np.float32)
            self._action_high = np.asarray(space.high, dtype=np.float32)
        probe.close() if hasattr(probe, "close") else None
        self._obs_dim, self._num_actions = obs_dim, num_actions

        policy_factory = self._make_policy_factory(obs_dim, num_actions)
        runner_cls = ray_tpu.remote(self._runner_class())
        self.runners = [
            runner_cls.remote(
                creator, policy_factory,
                seed=config.seed + i,
                rollout_fragment_length=config.rollout_fragment_length,
                gamma=config.gamma, lam=config.lambda_,
            )
            for i in range(config.num_env_runners)
        ]
        self.learner = self._build_learner(policy_factory())

    def _require_discrete(self):
        """Guard for discrete-only algorithms: a Box action space must
        fail fast, not silently train a categorical policy over
        np.prod(shape) pseudo-actions."""
        if getattr(self, "_continuous", False):
            raise ValueError(
                f"{type(self).__name__} supports discrete action spaces "
                f"only; use SAC for continuous control"
            )

    def _make_policy_factory(self, obs_dim: int, num_actions: int):
        from .policy import MLPPolicy

        self._require_discrete()
        config = self.config

        def policy_factory(obs_dim=obs_dim, num_actions=num_actions,
                           hidden=config.hidden_size, seed=config.seed):
            return MLPPolicy(obs_dim, num_actions, hidden, seed)

        return policy_factory

    def _runner_class(self):
        from .env_runner import EnvRunner

        return EnvRunner

    def _build_learner(self, policy):
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        result = self.training_step()
        result["training_iteration"] = self.iteration
        return result

    def get_weights(self):
        return self.learner.get_weights()

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
