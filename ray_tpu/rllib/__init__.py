"""ray_tpu.rllib: reinforcement learning (RLlib equivalent, TPU-native:
CPU EnvRunner actors + jax Learner on the accelerator)."""

from .algorithm import Algorithm, AlgorithmConfig  # noqa: F401
from .env_runner import EnvRunner  # noqa: F401
from .policy import MLPPolicy  # noqa: F401
from .a2c import A2C, A2CConfig  # noqa: F401
from .a3c import A3C, A3CConfig  # noqa: F401
from .alpha_zero import (  # noqa: F401
    AlphaZero,
    AlphaZeroConfig,
    MCTS,
    TicTacToe,
)
from .ars import ARS, ARSConfig  # noqa: F401
from .maddpg import MADDPG, MADDPGConfig  # noqa: F401
from .r2d2 import R2D2, R2D2Config  # noqa: F401
from .recurrent_ppo import RecurrentPPO, RecurrentPPOConfig  # noqa: F401
from .bandit import (  # noqa: F401
    Bandit,
    BanditLinTSConfig,
    BanditLinUCBConfig,
)
from .apex_ddpg import ApexDDPG, ApexDDPGConfig  # noqa: F401
from .apex_dqn import ApexDQN, ApexDQNConfig  # noqa: F401
from .ddppo import DDPPO, DDPPOConfig  # noqa: F401
from .slateq import SlateQ, SlateQConfig  # noqa: F401
from .crr import CRR, CRRConfig  # noqa: F401
from .ddpg import DDPG, DDPGConfig  # noqa: F401
from .dqn import DQN, DQNConfig  # noqa: F401
from .dt import DT, DTConfig  # noqa: F401
from .pg import PG, PGConfig  # noqa: F401
from .qmix import QMIX, QMIXConfig  # noqa: F401
from .es import ES, ESConfig  # noqa: F401
from .marwil import MARWIL, MARWILConfig  # noqa: F401
from .impala import IMPALA, IMPALAConfig  # noqa: F401
from .bc import BC, BCConfig  # noqa: F401
from .cql import CQL, CQLConfig  # noqa: F401
from .multi_agent import (  # noqa: F401
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from .sac import SAC, SACConfig  # noqa: F401
from .ppo import PPO, PPOConfig  # noqa: F401
from .appo import APPO, APPOConfig  # noqa: F401
from .td3 import TD3, TD3Config  # noqa: F401
from .core import (  # noqa: F401
    ActorCriticModule,
    DeterministicActorModule,
    Learner,
    LearnerGroup,
    QModule,
    RLModule,
)
from .replay_buffers import (  # noqa: F401
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from .sample_batch import SampleBatch, compute_gae  # noqa: F401

from ray_tpu.util import usage_stats as _usage
_usage.record_library_usage("rllib")
from .registry import get_algorithm_config, list_algorithms  # noqa: F401,E402
