"""A2C: synchronous advantage actor-critic.

Ref analogue: rllib/algorithms/a2c (A3C's synchronous variant; the
reference later moved it to rllib_contrib but ships it in this
snapshot's algorithm roster). One gradient pass per sampled batch —
vanilla policy gradient on GAE advantages + value regression + entropy
bonus, no surrogate clipping and no epoch reuse (that is PPO's
addition). Shares the ActorCriticModule / Learner layer and the GAE
EnvRunner plane with PPO.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .core import ActorCriticModule, Learner
from .sample_batch import (
    ACTIONS,
    ADVANTAGES,
    OBS,
    RETURNS,
    SampleBatch,
)


class A2CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01

    def build(self) -> "A2C":
        return A2C(self.copy())


class A2CLearner(Learner):
    """Plain policy-gradient loss: -logp*adv + c_v*mse(V,R) - c_e*H."""

    def __init__(self, policy, lr: float, vf_coeff: float,
                 ent_coeff: float):
        super().__init__(policy.get_weights(), lr=lr)
        self._vf_coeff = vf_coeff
        self._ent_coeff = ent_coeff

    def compute_loss(self, params, target, batch):
        import jax
        import jax.numpy as jnp

        logits, values = ActorCriticModule.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1
        )[:, 0]
        adv = batch["adv"]
        adv_n = (adv - adv.mean()) / (adv.std() + 1e-8)
        pi_loss = -(logp * adv_n).mean()
        vf_loss = ((values - batch["returns"]) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = (pi_loss + self._vf_coeff * vf_loss
                 - self._ent_coeff * entropy)
        return total, {
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }


class A2C(Algorithm):
    def _build_learner(self, policy):
        c = self.config
        return A2CLearner(policy, c.lr, c.vf_loss_coeff, c.entropy_coeff)

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        batches: List[SampleBatch] = []
        while sum(b.count for b in batches) < c.train_batch_size:
            batches.extend(ray_tpu.get(
                [r.sample.remote() for r in self.runners]
            ))
        batch = SampleBatch.concat(batches)

        # ONE synchronous gradient pass over the fresh batch (minibatched
        # for memory, still a single epoch — on-policy).
        stats: Dict[str, Any] = {}
        for mb in batch.minibatches(min(c.minibatch_size, batch.count)):
            stats = self.update_minibatch(mb)
        stats = {k: float(v) for k, v in stats.items()}

        weights = self.learner.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self.runners])

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": batch.count,
            **stats,
        }

    def update_minibatch(self, mb: SampleBatch) -> Dict[str, Any]:
        return self.learner.update_device({
            "obs": mb[OBS],
            "actions": np.asarray(mb[ACTIONS], dtype=np.int32),
            "adv": mb[ADVANTAGES],
            "returns": mb[RETURNS],
        })
