"""APPO: asynchronous PPO (learner/actor split).

Ref analogue: rllib/algorithms/appo/appo.py — IMPALA's asynchronous
architecture with PPO's clipped surrogate. EnvRunners sample
CONTINUOUSLY (each runner always has a sample() in flight; the driver
never barriers on the slowest); the learner consumes whichever batch
lands first, corrects for policy lag with clipped importance ratios
computed against the BEHAVIOR logps recorded at sample time, and
broadcasts fresh weights every ``broadcast_interval`` updates. The
learner itself can be a remote actor (LearnerGroup remote mode) so
sampling and gradient steps overlap — the split the reference's
Learner/LearnerGroup architecture exists for.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .core import LearnerGroup
from .ppo import PPOLearner
from .sample_batch import (
    ACTIONS,
    ADVANTAGES,
    LOGPS,
    OBS,
    RETURNS,
    SampleBatch,
)


class APPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param: float = 0.3
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        # Clip on the importance ratio against stale behavior policies
        # (ref: APPO's IS-ratio clipping atop the PPO surrogate).
        self.is_ratio_clip: float = 2.0
        # Weights push to runners every N learner updates, not every
        # update — the async point of the architecture.
        self.broadcast_interval: int = 4
        # Batches consumed per train() iteration.
        self.batches_per_iteration: int = 8
        # Host the learner in its own actor (overlaps with sampling).
        self.remote_learner: bool = False

    def build(self) -> "APPO":
        return APPO(self.copy())


class APPO(Algorithm):
    def _build_learner(self, policy):
        c = self.config

        def factory(weights=policy.get_weights(), c=c):
            class _W:  # minimal get_weights shim for the factory
                @staticmethod
                def get_weights():
                    return weights

            # The IS-ratio clip against stale behavior policies lives
            # directly in PPOLearner.compute_loss (is_ratio_clip): one
            # loss body serves both algorithms.
            return PPOLearner(
                _W, c.lr, c.clip_param, c.vf_loss_coeff,
                c.entropy_coeff, is_ratio_clip=c.is_ratio_clip,
            )

        self.learner_group = LearnerGroup(
            factory, remote=c.remote_learner
        )
        self._inflight: Dict[Any, Any] = {}  # sample ref -> runner
        self._pending_updates: List[Any] = []  # remote-mode stat refs
        self._updates_since_broadcast = 0
        self._total_updates = 0
        return self.learner_group

    def _ensure_sampling(self):
        """Every runner keeps exactly one sample() in flight."""
        busy = set(self._inflight.values())
        for r in self.runners:
            if r not in busy:
                self._inflight[r.sample.remote()] = r

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        stats: Dict[str, float] = {}
        consumed = 0
        env_steps = 0
        while consumed < c.batches_per_iteration:
            self._ensure_sampling()
            ready, _ = ray_tpu.wait(
                list(self._inflight), num_returns=1, timeout=60
            )
            if not ready:
                break
            ref = ready[0]
            runner = self._inflight.pop(ref)
            batch: SampleBatch = ray_tpu.get(ref, timeout=60)
            # Immediately resubmit THIS runner: sampling never drains.
            self._inflight[runner.sample.remote()] = runner
            result = self.learner_group.update_async({
                "obs": batch[OBS],
                "actions": np.asarray(batch[ACTIONS], dtype=np.int32),
                "old_logp": batch[LOGPS],
                "adv": batch[ADVANTAGES],
                "returns": batch[RETURNS],
            })
            if isinstance(result, dict):
                stats = result  # local mode runs inline
            else:
                # Remote learner: do NOT wait — the gradient step
                # overlaps with the next ray_tpu.wait on sample refs
                # (the learner/actor split's point). Stats resolve at
                # iteration end.
                self._pending_updates.append(result)
            consumed += 1
            env_steps += batch.count
            self._total_updates += 1
            self._updates_since_broadcast += 1
            if self._updates_since_broadcast >= c.broadcast_interval:
                # The learner actor processes calls in order, so this
                # weights read queues after every submitted update.
                weights = self.learner_group.get_weights()
                for r in self.runners:
                    r.set_weights.remote(weights)
                self._updates_since_broadcast = 0
        if self._pending_updates:
            # Resolve the async updates' stats (also a barrier that
            # keeps the pending list bounded per iteration).
            resolved = ray_tpu.get(self._pending_updates, timeout=300)
            self._pending_updates.clear()
            if resolved:
                stats = resolved[-1]
        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners], timeout=60
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        out = {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": env_steps,
            "num_learner_updates": self._total_updates,
        }
        if isinstance(stats, dict):
            out.update(stats)
        return out

    def get_weights(self):
        return self.learner_group.get_weights()

    def stop(self):
        super().stop()
        if getattr(self, "learner_group", None) is not None:
            self.learner_group.shutdown()
