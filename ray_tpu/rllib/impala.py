"""IMPALA: asynchronous actor-critic with V-trace off-policy correction.

Ref analogue: rllib/algorithms/impala/ (Espeholt et al. 2018). Runners
sample CONTINUOUSLY — the learner consumes whatever fragments are ready
each step instead of barriering on every runner — so rollouts lag the
learner's weights by a step or two; V-trace importance weights (rho/c
truncation) correct exactly that staleness. Sampling stays on CPU actors,
the V-trace learner is jax on the accelerator.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .sample_batch import (
    ACTIONS,
    BOOTSTRAP_OBS,
    DONES,
    LOGPS,
    OBS,
    REWARDS,
    SampleBatch,
)


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 6e-4
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.rho_clip: float = 1.0  # V-trace rho-bar
        self.c_clip: float = 1.0    # V-trace c-bar
        # Max fragments consumed per training_step (bounds staleness).
        self.max_batches_per_step: int = 4

    def build(self) -> "IMPALA":
        return IMPALA(self.copy())


class IMPALALearner:
    """jax V-trace actor-critic learner."""

    def __init__(self, policy, lr: float, gamma: float, rho_clip: float,
                 c_clip: float, vf_coeff: float, ent_coeff: float):
        import jax
        import jax.numpy as jnp
        import optax

        self._tx = optax.adam(lr)
        self._params = jax.tree.map(jnp.asarray, policy.get_weights())
        self._opt_state = self._tx.init(self._params)

        def forward(params, obs):
            h = obs
            for W, b in params["trunk"]:
                h = jnp.tanh(h @ W + b)
            (Wp, bp), = params["pi"]
            (Wv, bv), = params["vf"]
            return h @ Wp + bp, (h @ Wv + bv)[..., 0]

        def vtrace(behav_logp, target_logp, rewards, dones, values,
                   bootstrap):
            """V-trace targets over one time-major fragment (Espeholt
            eq. 1): vs = V(x_s) + sum_t gamma^(t-s) * prod(c) * dt_V."""
            rho = jnp.exp(target_logp - behav_logp)
            rho_bar = jnp.minimum(rho, rho_clip)
            c_bar = jnp.minimum(rho, c_clip)
            discounts = gamma * (1.0 - dones)
            values_next = jnp.concatenate(
                [values[1:], bootstrap[None]]
            )
            deltas = rho_bar * (
                rewards + discounts * values_next - values
            )

            def scan_fn(acc, inp):
                delta, disc, c = inp
                acc = delta + disc * c * acc
                return acc, acc

            _, advs = jax.lax.scan(
                scan_fn, jnp.zeros_like(bootstrap),
                (deltas, discounts, c_bar), reverse=True,
            )
            vs = values + advs
            vs_next = jnp.concatenate([vs[1:], bootstrap[None]])
            pg_adv = rho_bar * (
                rewards + discounts * vs_next - values
            )
            return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)

        def loss_fn(params, obs, actions, behav_logp, rewards, dones,
                    boot_obs):
            # Evaluate the fragment's T observations plus the one AFTER the
            # last transition in a single forward: the bootstrap must be
            # V(s_{T+1}), not V(s_T) (masked by (1-done) inside vtrace).
            all_obs = jnp.concatenate([obs, boot_obs[None]], axis=0)
            logits_all, values_all = forward(params, all_obs)
            logits, values = logits_all[:-1], values_all[:-1]
            bootstrap = values_all[-1]
            logp_all = jax.nn.log_softmax(logits)
            target_logp = jnp.take_along_axis(
                logp_all, actions[:, None], axis=1
            )[:, 0]
            vs, pg_adv = vtrace(
                behav_logp, target_logp, rewards, dones, values, bootstrap
            )
            pg_loss = -(target_logp * pg_adv).mean()
            vf_loss = ((values - vs) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        def update(params, opt_state, obs, actions, behav_logp, rewards,
                   dones, boot_obs):
            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, obs, actions, behav_logp, rewards, dones, boot_obs)
            updates, opt_state = self._tx.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            stats["total_loss"] = loss
            return params, opt_state, stats

        self._update = jax.jit(update)

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        import jax.numpy as jnp

        obs = jnp.asarray(batch[OBS])
        # Fragments from older runners may lack the bootstrap column; fall
        # back to the (biased) last-obs bootstrap rather than crash.
        boot = batch.get(BOOTSTRAP_OBS)
        boot = obs[-1] if boot is None else jnp.asarray(boot)
        self._params, self._opt_state, stats = self._update(
            self._params,
            self._opt_state,
            obs,
            jnp.asarray(batch[ACTIONS], dtype=jnp.int32),
            jnp.asarray(batch[LOGPS]),
            jnp.asarray(batch[REWARDS]),
            jnp.asarray(batch[DONES], dtype=jnp.float32),
            boot,
        )
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self._params)


class IMPALA(Algorithm):
    def _build_learner(self, policy):
        c = self.config
        learner = IMPALALearner(
            policy, c.lr, c.gamma, c.rho_clip, c.c_clip,
            c.vf_loss_coeff, c.entropy_coeff,
        )
        # Continuous sampling: every runner always has a fragment in
        # flight; training_step consumes whatever finished.
        self._pending = [(r, r.sample.remote()) for r in self.runners]
        return learner

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        refs = [ref for _, ref in self._pending]
        ready, _ = ray_tpu.wait(
            refs, num_returns=1, timeout=30.0
        )
        ready_ids = {r.id() for r in ready}
        stats: Dict[str, float] = {}
        consumed = 0
        still = []
        for runner, ref in self._pending:
            if ref.id() in ready_ids and consumed < c.max_batches_per_step:
                batch = ray_tpu.get(ref)
                stats = self.learner.update(batch)
                consumed += 1
                # Ship fresh weights, resubmit the runner immediately:
                # the lag between these two is what V-trace corrects.
                runner.set_weights.remote(self.learner.get_weights())
                still.append((runner, runner.sample.remote()))
            else:
                still.append((runner, ref))
        self._pending = still

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_batches_consumed": consumed,
            **stats,
        }

    def stop(self):
        self._pending = []
        super().stop()
