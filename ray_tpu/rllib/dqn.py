"""DQN: off-policy Q-learning with replay and a target network.

Ref analogue: rllib/algorithms/dqn/ (dqn.py training_step:623, double-Q +
target network sync) — sampling stays on CPU EnvRunner actors
(epsilon-greedy), learning is a jax double-DQN TD update on the
accelerator, with uniform or prioritized replay
(utils/replay_buffers/prioritized_replay_buffer.py). The reference's
Rainbow components ship as config flags: ``dueling`` (Wang 2016
V + A - mean(A) heads, the reference's `dueling` option) and ``n_step``
(multi-step TD backup folded into the stored transitions, the
reference's `n_step` option); double-Q is on by default.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .env_runner import NEXT_OBS, TransitionEnvRunner
from .replay_buffers import PrioritizedReplayBuffer, ReplayBuffer
from .sample_batch import ACTIONS, DONES, OBS, REWARDS, SampleBatch


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size: int = 50_000
        self.num_steps_sampled_before_learning_starts: int = 1_000
        self.target_network_update_freq: int = 500  # env steps
        self.num_updates_per_iteration: int = 32
        self.epsilon_initial: float = 1.0
        self.epsilon_final: float = 0.05
        self.epsilon_timesteps: int = 10_000  # linear decay horizon
        self.double_q: bool = True
        self.dueling: bool = False
        self.n_step: int = 1
        self.prioritized_replay: bool = False
        self.prioritized_replay_alpha: float = 0.6
        self.prioritized_replay_beta: float = 0.4

    def build(self) -> "DQN":
        return DQN(self.copy())


DISCOUNT = "discount"  # per-row bootstrap discount gamma^k * (1-done)


def nstep_returns(batch: SampleBatch, n: int, gamma: float
                  ) -> SampleBatch:
    """Fold an n-step lookahead into a sequential fragment batch:
    reward_t <- sum_{k<n} gamma^k r_{t+k}, next_obs_t <- obs_{t+n},
    and a DISCOUNT column gamma^{k_used}*(1-done) for the bootstrap.
    The lookahead stops at any EPISODE BOUNDARY — termination or
    truncation (the runner resets either way; crossing one would blend
    the next episode into the target) — while the bootstrap mask uses
    DONES alone, so truncated episodes still bootstrap. The fragment
    tail bootstraps early (the reference accepts the same
    fragment-boundary truncation)."""
    from .env_runner import BOUNDARY

    rew = np.asarray(batch[REWARDS], np.float64)
    done = np.asarray(batch[DONES], bool)
    boundary = (np.asarray(batch[BOUNDARY], bool)
                if BOUNDARY in batch else done)
    nxt = np.asarray(batch[NEXT_OBS])
    T = len(rew)
    r_n = np.zeros(T, np.float32)
    nxt_n = nxt.copy()
    disc = np.zeros(T, np.float32)
    for t in range(T):
        acc, g = 0.0, 1.0
        k = 0
        while True:
            acc += g * rew[t + k]
            g *= gamma
            if boundary[t + k] or k + 1 >= n or t + k + 1 >= T:
                break
            k += 1
        r_n[t] = acc
        nxt_n[t] = nxt[t + k]
        disc[t] = 0.0 if done[t + k] else g
    out = SampleBatch(dict(batch))
    out[REWARDS] = r_n
    out[NEXT_OBS] = nxt_n
    out[DISCOUNT] = disc
    return out


class DQNLearner:
    """jax double-DQN learner with a lagged target network; plain or
    dueling heads (the head layout follows the params pytree)."""

    def __init__(self, policy, lr: float, double_q: bool):
        import jax
        import jax.numpy as jnp
        import optax

        self._tx = optax.adam(lr)
        self._params = jax.tree.map(jnp.asarray, policy.get_weights())
        self._target = jax.tree.map(jnp.asarray, self._params)
        self._opt_state = self._tx.init(self._params)

        def q_forward(params, obs):
            h = obs
            for W, b in params["trunk"]:
                h = jnp.tanh(h @ W + b)
            if "q" in params:
                (Wq, bq), = params["q"]
                return h @ Wq + bq
            (Wv, bv), = params["v"]
            (Wa, ba), = params["a"]
            v = h @ Wv + bv
            a = h @ Wa + ba
            return v + a - a.mean(axis=-1, keepdims=True)

        def loss_fn(params, target, obs, actions, rewards, discount,
                    next_obs, weights):
            q = q_forward(params, obs)
            q_sa = jnp.take_along_axis(q, actions[:, None], axis=1)[:, 0]
            q_next_target = q_forward(target, next_obs)
            if double_q:
                # Action selection by the online net, evaluation by the
                # target net (van Hasselt 2016).
                best = jnp.argmax(q_forward(params, next_obs), axis=1)
            else:
                best = jnp.argmax(q_next_target, axis=1)
            q_next = jnp.take_along_axis(
                q_next_target, best[:, None], axis=1
            )[:, 0]
            targets = rewards + discount * q_next
            td = q_sa - jax.lax.stop_gradient(targets)
            loss = (weights * td * td).mean()
            return loss, td

        def update(params, opt_state, target, obs, actions, rewards,
                   discount, next_obs, weights):
            (loss, td), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, target, obs, actions, rewards, discount, next_obs,
              weights)
            updates, opt_state = self._tx.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        self._update = jax.jit(update)

    def update(self, batch: SampleBatch) -> Dict[str, Any]:
        import jax.numpy as jnp

        weights = batch.get("weights")
        w = (jnp.asarray(weights) if weights is not None
             else jnp.ones(batch.count, dtype=jnp.float32))
        self._params, self._opt_state, loss, td = self._update(
            self._params,
            self._opt_state,
            self._target,
            jnp.asarray(batch[OBS]),
            jnp.asarray(batch[ACTIONS], dtype=jnp.int32),
            jnp.asarray(batch[REWARDS]),
            jnp.asarray(batch[DISCOUNT]),
            jnp.asarray(batch[NEXT_OBS]),
            w,
        )
        return {"loss": float(loss), "td_error": np.asarray(td)}

    def sync_target(self):
        import jax

        self._target = jax.tree.map(lambda x: x, self._params)

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self._params)


class DQN(Algorithm):
    def _make_policy_factory(self, obs_dim: int, num_actions: int):
        self._require_discrete()
        from .policy import DuelingQPolicy, QPolicy

        config = self.config
        cls = DuelingQPolicy if config.dueling else QPolicy

        def policy_factory(cls=cls, obs_dim=obs_dim,
                           num_actions=num_actions,
                           hidden=config.hidden_size, seed=config.seed):
            return cls(obs_dim, num_actions, hidden, seed)

        return policy_factory

    def _runner_class(self):
        return TransitionEnvRunner

    def _build_learner(self, policy):
        c = self.config
        self._rng = np.random.RandomState(c.seed)
        if c.prioritized_replay:
            self.buffer: ReplayBuffer = PrioritizedReplayBuffer(
                c.buffer_size, alpha=c.prioritized_replay_alpha,
                beta=c.prioritized_replay_beta, seed=c.seed,
            )
        else:
            self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._env_steps = 0
        self._last_target_sync = 0
        return DQNLearner(policy, c.lr, c.double_q)

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._env_steps / max(1, c.epsilon_timesteps))
        return c.epsilon_initial + frac * (
            c.epsilon_final - c.epsilon_initial
        )

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        # 1) sample transitions from every runner at the current epsilon.
        eps = self._epsilon()
        ray_tpu.get([r.set_epsilon.remote(eps) for r in self.runners])
        batches: List[SampleBatch] = ray_tpu.get(
            [r.sample.remote() for r in self.runners]
        )
        for b in batches:
            self._env_steps += b.count
            self.buffer.add(nstep_returns(b, c.n_step, c.gamma))

        stats: Dict[str, Any] = {}
        num_updates = 0
        if self._env_steps >= c.num_steps_sampled_before_learning_starts:
            # 2) learner updates on replayed minibatches.
            for _ in range(c.num_updates_per_iteration):
                mb = self.buffer.sample(c.minibatch_size)
                out = self.learner.update(mb)
                stats["loss"] = out["loss"]
                if isinstance(self.buffer, PrioritizedReplayBuffer):
                    self.buffer.update_priorities(
                        mb["batch_indexes"], out["td_error"]
                    )
                num_updates += 1
            # 3) lagged target sync by env-step budget.
            if (self._env_steps - self._last_target_sync
                    >= c.target_network_update_freq):
                self.learner.sync_target()
                self._last_target_sync = self._env_steps
            # 4) broadcast fresh weights to the rollout plane.
            weights = self.learner.get_weights()
            ray_tpu.get(
                [r.set_weights.remote(weights) for r in self.runners]
            )

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "num_learner_updates": num_updates,
            "epsilon": eps,
            "buffer_size": len(self.buffer),
            **stats,
        }
