"""PPO: clipped-surrogate policy optimization.

Ref analogue: rllib/algorithms/ppo/ (ppo.py:392 training_step, torch
learner) — here the Learner is jax (runs on the accelerator when present:
SURVEY.md §3.6's LearnerGroup→GPU becomes Learner→TPU) and the rollout
plane stays numpy on CPU actors.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .core import ActorCriticModule, Learner
from .sample_batch import (
    ACTIONS,
    ADVANTAGES,
    LOGPS,
    OBS,
    RETURNS,
    SampleBatch,
)


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param: float = 0.2
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01

    def build(self) -> "PPO":
        return PPO(self.copy())


class PPOLearner(Learner):
    """Clipped-surrogate loss on the shared Learner layer (ref:
    ppo_learner / Learner.compute_loss — the module is the shared
    ActorCriticModule, the grad/apply plumbing is inherited).
    ``is_ratio_clip`` (APPO) additionally caps the importance ratio
    against stale behavior policies before the PPO clip."""

    def __init__(self, policy, lr: float, clip: float, vf_coeff: float,
                 ent_coeff: float, is_ratio_clip: float = None):
        super().__init__(policy.get_weights(), lr=lr)
        self._clip = clip
        self._vf_coeff = vf_coeff
        self._ent_coeff = ent_coeff
        self._is_clip = is_ratio_clip

    def compute_loss(self, params, target, batch):
        import jax
        import jax.numpy as jnp

        logits, values = ActorCriticModule.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1
        )[:, 0]
        ratio = jnp.exp(logp - batch["old_logp"])
        stats = {}
        if self._is_clip is not None:
            # Stale-policy guard FIRST, then the PPO clip (APPO).
            ratio = jnp.minimum(ratio, self._is_clip)
            stats["mean_is_ratio"] = ratio.mean()
        adv = batch["adv"]
        adv_n = (adv - adv.mean()) / (adv.std() + 1e-8)
        surr = jnp.minimum(
            ratio * adv_n,
            jnp.clip(ratio, 1 - self._clip, 1 + self._clip) * adv_n,
        )
        pi_loss = -surr.mean()
        vf_loss = ((values - batch["returns"]) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = (pi_loss + self._vf_coeff * vf_loss
                 - self._ent_coeff * entropy)
        stats.update({
            "policy_loss": pi_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        })
        return total, stats

    def update_epochs(self, batch: SampleBatch, *, epochs: int,
                      minibatch_size: int, rng: np.random.RandomState
                      ) -> Dict[str, float]:
        stats: Dict[str, Any] = {}
        for _ in range(epochs):
            shuffled = batch.shuffle(rng)
            for mb in shuffled.minibatches(
                min(minibatch_size, batch.count)
            ):
                # Device-side stats: ONE host sync after all epochs,
                # keeping the minibatch loop async-dispatched.
                stats = self.update_device({
                    "obs": mb[OBS],
                    "actions": np.asarray(mb[ACTIONS], dtype=np.int32),
                    "old_logp": mb[LOGPS],
                    "adv": mb[ADVANTAGES],
                    "returns": mb[RETURNS],
                })
        return {k: float(v) for k, v in stats.items()}


class PPO(Algorithm):
    def _build_learner(self, policy):
        c = self.config
        self._rng = np.random.RandomState(c.seed)
        return PPOLearner(
            policy, c.lr, c.clip_param, c.vf_loss_coeff, c.entropy_coeff
        )

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        batches: List[SampleBatch] = []
        while sum(b.count for b in batches) < c.train_batch_size:
            batches.extend(
                ray_tpu.get([r.sample.remote() for r in self.runners])
            )
        batch = SampleBatch.concat(batches)
        learner_stats = self.learner.update_epochs(
            batch, epochs=c.num_epochs, minibatch_size=c.minibatch_size,
            rng=self._rng,
        )
        weights = self.learner.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self.runners])
        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": batch.count,
            **learner_stats,
        }
