"""PPO: clipped-surrogate policy optimization.

Ref analogue: rllib/algorithms/ppo/ (ppo.py:392 training_step, torch
learner) — here the Learner is jax (runs on the accelerator when present:
SURVEY.md §3.6's LearnerGroup→GPU becomes Learner→TPU) and the rollout
plane stays numpy on CPU actors.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .sample_batch import (
    ACTIONS,
    ADVANTAGES,
    LOGPS,
    OBS,
    RETURNS,
    SampleBatch,
)


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param: float = 0.2
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01

    def build(self) -> "PPO":
        return PPO(self.copy())


class PPOLearner:
    """jax learner over the numpy policy pytree."""

    def __init__(self, policy, lr: float, clip: float, vf_coeff: float,
                 ent_coeff: float):
        import jax
        import jax.numpy as jnp
        import optax

        self._policy = policy
        self._tx = optax.adam(lr)
        self._params = jax.tree.map(jnp.asarray, policy.get_weights())
        self._opt_state = self._tx.init(self._params)

        def forward(params, obs):
            h = obs
            for W, b in params["trunk"]:
                h = jnp.tanh(h @ W + b)
            (Wp, bp), = params["pi"]
            (Wv, bv), = params["vf"]
            return h @ Wp + bp, (h @ Wv + bv)[..., 0]

        def loss_fn(params, obs, actions, old_logp, adv, returns):
            logits, values = forward(params, obs)
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, actions[:, None], axis=1
            )[:, 0]
            ratio = jnp.exp(logp - old_logp)
            adv_n = (adv - adv.mean()) / (adv.std() + 1e-8)
            surr = jnp.minimum(
                ratio * adv_n,
                jnp.clip(ratio, 1 - clip, 1 + clip) * adv_n,
            )
            pi_loss = -surr.mean()
            vf_loss = ((values - returns) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pi_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {
                "policy_loss": pi_loss,
                "vf_loss": vf_loss,
                "entropy": entropy,
            }

        def update(params, opt_state, obs, actions, old_logp, adv, returns):
            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, obs, actions, old_logp, adv, returns)
            updates, opt_state = self._tx.update(grads, opt_state)
            params = optax.apply_updates(params, updates)
            stats["total_loss"] = loss
            return params, opt_state, stats

        self._update = jax.jit(update)

    def update(self, batch: SampleBatch, *, epochs: int,
               minibatch_size: int, rng: np.random.RandomState
               ) -> Dict[str, float]:
        import jax.numpy as jnp

        stats = {}
        for _ in range(epochs):
            shuffled = batch.shuffle(rng)
            for mb in shuffled.minibatches(min(minibatch_size, batch.count)):
                self._params, self._opt_state, stats = self._update(
                    self._params,
                    self._opt_state,
                    jnp.asarray(mb[OBS]),
                    jnp.asarray(mb[ACTIONS], dtype=jnp.int32),
                    jnp.asarray(mb[LOGPS]),
                    jnp.asarray(mb[ADVANTAGES]),
                    jnp.asarray(mb[RETURNS]),
                )
        return {k: float(v) for k, v in stats.items()}

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self._params)


class PPO(Algorithm):
    def _build_learner(self, policy):
        c = self.config
        self._rng = np.random.RandomState(c.seed)
        return PPOLearner(
            policy, c.lr, c.clip_param, c.vf_loss_coeff, c.entropy_coeff
        )

    def training_step(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        batches: List[SampleBatch] = []
        while sum(b.count for b in batches) < c.train_batch_size:
            batches.extend(
                ray_tpu.get([r.sample.remote() for r in self.runners])
            )
        batch = SampleBatch.concat(batches)
        learner_stats = self.learner.update(
            batch, epochs=c.num_epochs, minibatch_size=c.minibatch_size,
            rng=self._rng,
        )
        weights = self.learner.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self.runners])
        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": batch.count,
            **learner_stats,
        }
