"""SlateQ: Q-learning for slate recommendation.

Ref analogue: rllib/algorithms/slateq (Ie 2019 "SlateQ: A Tractable
Decomposition for Reinforcement Learning with Recommendation Sets").
The action is a SLATE of k items out of a candidate set; the
combinatorial Q(s, slate) is decomposed under the single-choice user
model into per-item values:
    Q(s, A) = sum_{i in A} P(choice = i | s, A) * Q_item(s, i)
with P given by a conditional logit over item scores (and a no-click
option). Q_item is a per-item MLP trained by SARSA-style backup on
the CLICKED item; slate selection is the top-k items by
v_i * Q_item(s, i) (the paper's greedy decomposition, optimal for
the conditional-logit choice model).

Env protocol (recsys convention):
  reset() -> (user_obs, info)
  step(slate: list[int]) -> (user_obs, reward, terminated, truncated,
                             {"clicked": item_id or -1})
  env.num_items: catalog size; env.slate_size: k;
  env.item_features: [num_items, d_item] array.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from .algorithm import AlgorithmConfig
from .policy import init_mlp_params
from .replay_buffers import ReplayBuffer
from .sample_batch import SampleBatch


class SlateQConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size: int = 20_000
        self.num_steps_sampled_before_learning_starts: int = 300
        self.num_updates_per_iteration: int = 32
        self.target_network_update_freq: int = 500
        self.epsilon_initial: float = 1.0
        self.epsilon_final: float = 0.05
        self.epsilon_timesteps: int = 5_000

    def build(self) -> "SlateQ":
        return SlateQ(self.copy())


def _item_scores(weights, user, items):
    """Choice-model scores v_i = user . W . item (numpy)."""
    (W, b), = weights["choice"]
    return (user @ W + b) @ items.T


def _q_items(weights, user, items):
    """Q_item(s, i) for every item: MLP over [user, item] (numpy)."""
    n = len(items)
    x = np.concatenate(
        [np.repeat(user[None], n, 0), items], axis=1
    )
    h = x
    for W, b in weights["trunk"]:
        h = np.tanh(h @ W + b)
    (Wq, bq), = weights["q"]
    return (h @ Wq + bq)[:, 0]


class _SlatePolicy:
    """Greedy slate by v_i * Q_i with epsilon exploration."""

    def __init__(self, user_dim, item_dim, num_items, slate_size,
                 hidden, seed):
        rng = np.random.RandomState(seed)
        self.weights = {
            "trunk": init_mlp_params(
                rng, [user_dim + item_dim, hidden, hidden]
            ),
            "q": init_mlp_params(rng, [hidden, 1]),
            "choice": init_mlp_params(rng, [user_dim, item_dim]),
        }
        self.k = slate_size
        self.num_items = num_items
        self.epsilon = 1.0

    def set_weights(self, w):
        self.weights = w

    def get_weights(self):
        return self.weights

    def set_epsilon(self, e):
        self.epsilon = float(e)

    def compute_slate(self, user, items, rng) -> List[int]:
        if rng.rand() < self.epsilon:
            return list(rng.choice(self.num_items, self.k,
                                   replace=False))
        v = _item_scores(self.weights, user, items)
        q = _q_items(self.weights, user, items)
        return list(np.argsort(-(v * q))[:self.k])


class _SlateEnvRunner:
    """Steps a recsys env; emits (user, slate, clicked, reward,
    next_user, done) transitions."""

    def __init__(self, env_creator, policy_factory, seed=0,
                 rollout_fragment_length=100, **_):
        self.env = env_creator()
        self.policy = policy_factory()
        self.items = np.asarray(self.env.item_features, np.float32)
        self.rng = np.random.RandomState(seed)
        self.fragment = rollout_fragment_length
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_reward = 0.0
        self._episode_rewards: List[float] = []

    def set_weights(self, w):
        self.policy.set_weights(w)

    def set_epsilon(self, e):
        self.policy.set_epsilon(e)

    def sample(self) -> SampleBatch:
        users, slates, clicks, rews, nxts, dones = \
            [], [], [], [], [], []
        for _ in range(self.fragment):
            user = np.asarray(self._obs, np.float32).reshape(-1)
            slate = self.policy.compute_slate(user, self.items,
                                              self.rng)
            nxt, r, term, trunc, info = self.env.step(slate)
            users.append(user)
            slates.append(slate)
            clicks.append(int(info.get("clicked", -1)))
            rews.append(float(r))
            nxts.append(np.asarray(nxt, np.float32).reshape(-1))
            dones.append(bool(term))
            self._episode_reward += float(r)
            if term or trunc:
                self._episode_rewards.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = nxt
        return SampleBatch({
            "user": np.stack(users),
            "slate": np.asarray(slates, np.int32),
            "clicked": np.asarray(clicks, np.int32),
            "rew": np.asarray(rews, np.float32),
            "next_user": np.stack(nxts),
            "done": np.asarray(dones, np.float32),
        })

    def episode_stats(self) -> Dict[str, float]:
        recent = self._episode_rewards[-20:]
        return {
            "episodes_total": len(self._episode_rewards),
            "episode_reward_mean": float(np.mean(recent))
            if recent else 0.0,
        }


class SlateQLearner:
    """Jitted SARSA-on-clicked-item update with the slate
    decomposition target."""

    def __init__(self, policy, items: np.ndarray, slate_size: int,
                 lr: float, gamma: float):
        import jax
        import jax.numpy as jnp
        import optax

        self._tx = optax.adam(lr)
        self._params = jax.tree.map(jnp.asarray, policy.get_weights())
        self._target = jax.tree.map(lambda x: x, self._params)
        self._opt_state = self._tx.init(self._params)
        items_j = jnp.asarray(items)
        k = slate_size

        def q_items(p, users):
            """[B, N]: Q_item for every catalog item."""
            B = users.shape[0]
            N = items_j.shape[0]
            u = jnp.repeat(users[:, None, :], N, 1)
            it = jnp.repeat(items_j[None], B, 0)
            x = jnp.concatenate([u, it], -1).reshape(B * N, -1)
            h = x
            for W, b in p["trunk"]:
                h = jnp.tanh(h @ W + b)
            (Wq, bq), = p["q"]
            return (h @ Wq + bq).reshape(B, N)

        def scores(p, users):
            (W, b), = p["choice"]
            return (users @ W + b) @ items_j.T

        def loss_fn(p, tgt, batch):
            users, clicked = batch["user"], batch["clicked"]
            # Predicted Q of the CLICKED item (only clicked steps
            # carry a gradient — the no-click mask).
            q_all = q_items(p, users)
            q_c = jnp.take_along_axis(
                q_all, jnp.maximum(clicked, 0)[:, None], 1
            )[:, 0]
            # Target: next greedy slate under the decomposition, its
            # expected value under the conditional-logit choice model.
            nq_all = q_items(tgt, batch["next_user"])
            nv = scores(tgt, batch["next_user"])
            vq = nv * nq_all
            top = jax.lax.top_k(vq, k)[1]               # [B, k]
            v_top = jnp.take_along_axis(nv, top, 1)
            q_top = jnp.take_along_axis(nq_all, top, 1)
            # No-click option has score 0 in the logit.
            ex = jnp.exp(v_top - v_top.max(-1, keepdims=True))
            denom = ex.sum(-1) + jnp.exp(-v_top.max(-1))
            slate_value = (ex * q_top).sum(-1) / denom
            y = batch["rew"] + gamma * (1 - batch["done"]) * \
                jax.lax.stop_gradient(slate_value)
            mask = (clicked >= 0).astype(jnp.float32)
            td = (q_c - y) * mask
            td_loss = (td * td).sum() / jnp.maximum(mask.sum(), 1.0)
            # Choice-model MLE on the click logs (the paper trains the
            # user-choice model separately by maximum likelihood; the
            # Q loss above never touches the choice head — without
            # this term the slate ranking would use random scores).
            v_all = scores(p, users)
            v_slate = jnp.take_along_axis(v_all, batch["slate"], 1)
            choice_logits = jnp.concatenate(
                [v_slate, jnp.zeros_like(v_slate[:, :1])], axis=1
            )
            logp = jax.nn.log_softmax(choice_logits)
            ce = -jnp.take_along_axis(
                logp, batch["click_pos"][:, None], 1
            )[:, 0].mean()
            return td_loss + ce, (td_loss, ce)

        def update(p, opt_state, tgt, batch):
            (loss, _aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(p, tgt, batch)
            updates, opt_state = self._tx.update(grads, opt_state)
            return optax.apply_updates(p, updates), opt_state, loss

        self._update = jax.jit(update)

    def learn_on_batch(self, mb) -> float:
        import jax.numpy as jnp

        slate = np.asarray(mb["slate"], np.int64)
        clicked = np.asarray(mb["clicked"], np.int64)
        # Position of the clicked item within its slate; k = no-click.
        pos = np.full(len(clicked), slate.shape[1], np.int32)
        hit = slate == clicked[:, None]
        rows, cols = np.nonzero(hit)
        pos[rows] = cols
        batch = {
            "user": jnp.asarray(mb["user"]),
            "slate": jnp.asarray(slate, jnp.int32),
            "clicked": jnp.asarray(clicked, jnp.int32),
            "click_pos": jnp.asarray(pos),
            "rew": jnp.asarray(mb["rew"]),
            "next_user": jnp.asarray(mb["next_user"]),
            "done": jnp.asarray(mb["done"]),
        }
        self._params, self._opt_state, loss = self._update(
            self._params, self._opt_state, self._target, batch
        )
        return float(loss)

    def sync_target(self):
        import jax

        self._target = jax.tree.map(lambda x: x, self._params)

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self._params)


class SlateQ:
    def __init__(self, config: SlateQConfig):
        import ray_tpu

        self.config = config
        self.iteration = 0
        c = config
        creator = c.env_creator()
        probe = creator()
        user0, _ = probe.reset(seed=0)
        user_dim = int(np.asarray(user0).reshape(-1).shape[0])
        items = np.asarray(probe.item_features, np.float32)
        self._slate_size = int(probe.slate_size)
        num_items = int(probe.num_items)
        if hasattr(probe, "close"):
            probe.close()

        def policy_factory(user_dim=user_dim,
                           item_dim=items.shape[1],
                           num_items=num_items,
                           k=self._slate_size,
                           hidden=c.hidden_size, seed=c.seed):
            return _SlatePolicy(user_dim, item_dim, num_items, k,
                                hidden, seed)

        runner_cls = ray_tpu.remote(_SlateEnvRunner)
        self.runners = [
            runner_cls.remote(
                creator, policy_factory, seed=c.seed + i,
                rollout_fragment_length=c.rollout_fragment_length,
            )
            for i in range(c.num_env_runners)
        ]
        self.learner = SlateQLearner(
            policy_factory(), items, self._slate_size, c.lr, c.gamma
        )
        self.buffer = ReplayBuffer(c.buffer_size, seed=c.seed)
        self._env_steps = 0
        self._last_target_sync = 0

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._env_steps / max(1, c.epsilon_timesteps))
        return c.epsilon_initial + frac * (
            c.epsilon_final - c.epsilon_initial
        )

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        self.iteration += 1
        c = self.config
        eps = self._epsilon()
        ray_tpu.get([r.set_epsilon.remote(eps) for r in self.runners])
        batches = ray_tpu.get([r.sample.remote() for r in self.runners])
        for b in batches:
            self.buffer.add(b)
            self._env_steps += b.count

        loss = float("nan")
        num_updates = 0
        if self._env_steps >= c.num_steps_sampled_before_learning_starts:
            for _ in range(c.num_updates_per_iteration):
                loss = self.learner.learn_on_batch(
                    self.buffer.sample(c.minibatch_size)
                )
                num_updates += 1
            if (self._env_steps - self._last_target_sync
                    >= c.target_network_update_freq):
                self.learner.sync_target()
                self._last_target_sync = self._env_steps
            w = self.learner.get_weights()
            ray_tpu.get(
                [r.set_weights.remote(w) for r in self.runners]
            )

        ep_stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self.runners]
        )
        means = [s["episode_reward_mean"] for s in ep_stats
                 if s["episodes_total"] > 0]
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": float(np.mean(means)) if means else 0.0,
            "episodes_total": sum(s["episodes_total"] for s in ep_stats),
            "num_env_steps_sampled": self._env_steps,
            "num_learner_updates": num_updates,
            "epsilon": eps,
            "loss": loss,
        }

    def get_weights(self):
        return self.learner.get_weights()

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
